"""Deterministic scheduler-simulation bench (KT-PERF-SCHED family).

Drives the SAME policy code the live controller runs
(``kubeflow_tpu/controller/scheduler.py``) through a discrete-event
cluster simulation and A/Bs three arms over one synthetic mixed tenancy
(train + HPO sweep + serving scale-ups):

- ``fifo``       -- the pre-scheduler baseline: gangs admitted in strict
                    arrival order at spec size, no backfill past the
                    queue head (gang semantics), first-fit placement,
                    no resize, no preemption. This is what the repo's
                    controller did before ROADMAP item 2.
- ``sched_blind`` -- the full multi-tenant policy with the contention
                    term zeroed (``contention_weight=0``): measures how
                    much of the win is fairness/elasticity vs placement.
- ``sched``      -- the headline: contention-aware packing, weighted
                    max-min fairness, SLO preemption, reshard-aware
                    migration gating.

Both simulated worlds and the policy's internal cost model share ONE
contention physics (``contention_factor``), so the aware arm wins by
*placing* better, not by being graded on friendlier physics. Actuation
costs are the measured ones: same-domain resizes on reshard-capable
jobs pause for the worst measured live-reshard transition from the
latest reshard bench artifact (BENCH_r06: ~0.19 s), domain moves and
preemption-restarts pause for the checkpoint-restart budget (90 s) --
which is exactly why the planner's migration gate matters.

Deterministic by construction: no wall-clock, no RNG; fixed dt ticks.
Output is the ``parsed`` payload for ``BENCH_r07.json`` (the artifact
``analysis/perf.py::_check_sched`` ratchets).

Run:  python bench_sched.py            # JSON to stdout
"""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubeflow_tpu.controller.scheduler import (
    Domain,
    MultiTenantPolicy,
    Placement,
    PolicyConfig,
    SchedJob,
    comm_bytes_for_intensity,
    contention_factor,
    intensity_from_comm_bytes,
    jains_index,
    scale_efficiency,
    static_hbm_peak,
)

DT = 0.5                 # sim tick (s)
REPLAN_EVERY = 5.0       # scheduler round cadence (s)
RESTART_SECONDS = 90.0   # checkpoint-restart pause budget (spec, PR 8)
HORIZON = 1e9            # no-progress watchdog


@dataclass
class SimJob:
    """One job in the simulated mix."""

    key: str
    tenant: str
    weight: float
    workload: str            # serving | train | hpo
    min_chips: int
    max_chips: int
    intensity: float         # collective intensity (census-derived)
    per_chip: float          # solo tok/s per chip
    work: float              # tokens to produce before Succeeded
    arrival: float
    reshardable: bool = False
    spec_chips: int = 0      # FIFO arm's fixed gang size
    # Measured per-step wire bytes (the shard analysis family's
    # comm.bytes_per_step, stamped as kftpu.io/comm-bytes-per-step in a
    # real deployment). None = the job never got audited: the census
    # prior applies. Measured jobs resolve intensity through the same
    # log ramp the live scheduler uses.
    comm_bytes: Optional[float] = None

    # mutable sim state
    done: float = 0.0
    placement: Optional[Placement] = None
    pause_until: float = 0.0
    started: bool = False
    finish: Optional[float] = None
    preemptions: int = 0

    def __post_init__(self) -> None:
        if not self.spec_chips:
            self.spec_chips = self.max_chips


def job_mix() -> List[SimJob]:
    """The mixed train+HPO+serving tenancy (3 tenants, 10 jobs).

    Two collective-heavy train jobs (ring-attention-class intensity
    0.85) that co-located run at ~0.63x each; an HPO sweep of six
    collective-light trials arriving over time; two serving scale-ups
    arriving mid-run whose minimums force preemption of HPO trials.
    """
    # The long-lived train/serving jobs carry MEASURED comm bytes (as a
    # shard-audited deployment would); the short HPO trials never get
    # audited and keep the census prior. comm_bytes_for_intensity is the
    # exact ramp inverse, so measured jobs land on the same intensities
    # as before -- the arms' physics are unchanged and the bench only
    # ADDS provenance accounting.
    jobs = [
        SimJob("acme/train-a", "acme", 2.0, "train", 4, 12, 0.85,
               1000.0, 3_200_000, 0.0, reshardable=True, spec_chips=8,
               comm_bytes=comm_bytes_for_intensity(0.85)),
        SimJob("beta/train-b", "beta", 1.0, "train", 4, 12, 0.85,
               1000.0, 2_800_000, 0.0, reshardable=True, spec_chips=8,
               comm_bytes=comm_bytes_for_intensity(0.85)),
    ]
    for i, arrival in enumerate((0.0, 0.0, 0.0, 0.0, 60.0, 80.0)):
        jobs.append(SimJob(
            f"gamma/hpo-{i}", "gamma", 1.0, "hpo", 4, 4, 0.2,
            900.0, 400_000, arrival, spec_chips=4,
        ))
    # Serving scale-ups: min demand high enough that, with both trains
    # at elastic minimum and the live HPO trials, minimums exceed the
    # 32-chip cluster -> SLO preemption fires.
    jobs.append(SimJob("acme/serve-a", "acme", 2.0, "serving", 8, 8,
                       0.15, 1500.0, 900_000, 120.0, spec_chips=8,
                       comm_bytes=comm_bytes_for_intensity(0.15)))
    jobs.append(SimJob("beta/serve-b", "beta", 1.0, "serving", 8, 8,
                       0.15, 1500.0, 700_000, 150.0, spec_chips=8,
                       comm_bytes=comm_bytes_for_intensity(0.15)))
    return jobs


def resolve_sim_intensity(j: SimJob) -> tuple:
    """(intensity, source) exactly as the live scheduler resolves it."""
    if j.comm_bytes is not None:
        return intensity_from_comm_bytes(j.comm_bytes), "measured"
    return j.intensity, "prior"


def intensity_sources(jobs) -> dict:
    tally: dict = {}
    for j in jobs:
        src = resolve_sim_intensity(j)[1]
        tally[src] = tally.get(src, 0) + 1
    return tally


def resolve_sim_hbm_peak(j: SimJob) -> tuple:
    """(peak_bytes, fit_source) exactly as the live scheduler resolves
    it: no job in the mix carries a measured kftpu.io/hbm-peak-bytes
    sample, so each falls back to the audited mem.peak_bytes baseline
    for its workload class (the mem analysis family's ratchet)."""
    est = static_hbm_peak(j.workload)
    if est is not None:
        return est, "static"
    return None, "none"


def fit_sources(jobs) -> dict:
    tally: dict = {}
    for j in jobs:
        src = resolve_sim_hbm_peak(j)[1]
        tally[src] = tally.get(src, 0) + 1
    return tally


def domains() -> List[Domain]:
    # Two interconnect domains of 16 chips: large enough that two train
    # gangs CAN share one (which is exactly the contention-blind
    # failure mode the aware arm avoids).
    return [Domain("d0", 16), Domain("d1", 16)]


def progress_rates(jobs: List[SimJob], alpha: float) -> Dict[str, float]:
    """tok/s for every placed, unpaused job under the shared contention
    physics: intensity-weighted slowdown from domain co-residents."""
    by_dom: Dict[str, float] = {}
    for j in jobs:
        if j.placement is not None:
            by_dom[j.placement.domain] = (
                by_dom.get(j.placement.domain, 0.0) + j.intensity)
    rates = {}
    for j in jobs:
        p = j.placement
        if p is None:
            continue
        others = by_dom[p.domain] - j.intensity
        rates[j.key] = (j.per_chip * p.chips * scale_efficiency(p.chips)
                        * contention_factor(j.intensity, others, alpha))
    return rates


@dataclass
class ArmResult:
    makespan: float
    goodput: float                      # total tokens / makespan
    fairness: float                     # Jain over weighted tenant rates
    preemptions: int
    migrations: int
    migration_seconds: float
    per_job: List[dict] = field(default_factory=list)
    # Placements the memory-feasibility mask refused (job's audited
    # HBM peak fits no domain) across all scheduling rounds.
    mem_rejections: int = 0


def finalize(jobs: List[SimJob], t: float, preemptions: int,
             migrations: int, migration_seconds: float) -> ArmResult:
    total = sum(j.work for j in jobs)
    makespan = max(j.finish for j in jobs)
    # Weighted fairness at TENANT granularity (what the two-level
    # water-filling promises): tenant service rate = tenant tokens over
    # the tenant's active span, normalized by tenant weight.
    tenants: Dict[str, List[SimJob]] = {}
    for j in jobs:
        tenants.setdefault(j.tenant, []).append(j)
    norm_rates = []
    for members in tenants.values():
        tok = sum(m.work for m in members)
        span = (max(m.finish for m in members)
                - min(m.arrival for m in members))
        w = max(m.weight for m in members)
        norm_rates.append((tok / max(span, 1e-9)) / w)
    return ArmResult(
        makespan=round(makespan, 1),
        goodput=round(total / makespan, 1),
        fairness=round(jains_index(norm_rates), 4),
        preemptions=preemptions,
        migrations=migrations,
        migration_seconds=round(migration_seconds, 2),
        per_job=[{
            "job": j.key, "tenant": j.tenant, "class": j.workload,
            "arrival": j.arrival, "finish": round(j.finish, 1),
            "preemptions": j.preemptions,
        } for j in sorted(jobs, key=lambda j: j.key)],
    )


# --------------------------------------------------------------------------
# FIFO-gang baseline arm.
# --------------------------------------------------------------------------
def run_fifo(alpha: float) -> ArmResult:
    jobs = job_mix()
    doms = domains()
    t = 0.0
    while any(j.finish is None for j in jobs) and t < HORIZON:
        live = [j for j in jobs if j.finish is None and j.arrival <= t]
        # Admit strictly in arrival order at spec size; the queue head
        # blocks everyone behind it (gang semantics, no backfill).
        free = {d.name: d.chips for d in doms}
        for j in live:
            if j.placement is not None:
                free[j.placement.domain] -= j.placement.chips
        for j in sorted((j for j in live if j.placement is None),
                        key=lambda j: (j.arrival, j.key)):
            fit = next((d for d in doms
                        if free[d.name] >= j.spec_chips), None)
            if fit is None:
                break  # head-of-line: nothing behind may jump the queue
            j.placement = Placement(fit.name, j.spec_chips)
            j.started = True
            free[fit.name] -= j.spec_chips
        rates = progress_rates(live, alpha)
        for j in live:
            r = rates.get(j.key)
            if r is None:
                continue
            j.done += r * DT
            if j.done >= j.work:
                j.finish = t + DT
                j.placement = None
        t += DT
    return finalize(jobs, t, 0, 0, 0.0)


# --------------------------------------------------------------------------
# Policy arms (contention-aware and -blind share this driver).
# --------------------------------------------------------------------------
def run_policy(alpha: float, contention_weight: float,
               reshard_seconds: float) -> ArmResult:
    jobs = job_mix()
    doms = domains()
    cfg = PolicyConfig(
        contention_weight=contention_weight,
        contention_alpha=alpha,
        reshard_seconds=reshard_seconds,
        restart_seconds=RESTART_SECONDS,
        round_horizon_seconds=REPLAN_EVERY,
    )
    policy = MultiTenantPolicy(doms, cfg)
    t = 0.0
    next_round = 0.0
    preemptions = migrations = mem_rejections = 0
    migration_seconds = 0.0
    seq = {j.key: i for i, j in enumerate(jobs)}
    while any(j.finish is None for j in jobs) and t < HORIZON:
        live = [j for j in jobs if j.finish is None and j.arrival <= t]
        if t >= next_round and live:
            next_round = t + REPLAN_EVERY
            view = [SchedJob(
                key=j.key, tenant=j.tenant, weight=j.weight,
                workload=j.workload, min_chips=j.min_chips,
                max_chips=j.max_chips,
                collective_intensity=resolve_sim_intensity(j)[0],
                intensity_source=resolve_sim_intensity(j)[1],
                arrival_seq=seq[j.key], reshardable=j.reshardable,
                current=j.placement, tok_s_per_chip=j.per_chip,
                hbm_peak_bytes=resolve_sim_hbm_peak(j)[0],
                fit_source=resolve_sim_hbm_peak(j)[1],
            ) for j in sorted(live, key=lambda j: seq[j.key])]
            plan = policy.plan(view)
            mem_rejections += plan.mem_rejections
            by_key = {j.key: j for j in live}
            for dec in plan.decisions:
                j = by_key[dec.job]
                if j.pause_until > t and dec.action in (
                        "grow", "shrink", "migrate", "preempt"):
                    continue  # a resize is already actuating: never stack
                if dec.action in ("queue",):
                    continue
                if dec.action == "preempt":
                    j.placement = None
                    j.preemptions += 1
                    preemptions += 1
                    continue
                if dec.placement is None:
                    continue
                if dec.action == "admit":
                    j.placement = dec.placement
                    if j.started:
                        # resume-from-checkpoint after preemption
                        j.pause_until = t + RESTART_SECONDS
                        migration_seconds += RESTART_SECONDS
                    j.started = True
                elif dec.action in ("grow", "shrink", "migrate"):
                    j.placement = dec.placement
                    j.pause_until = t + dec.cost_seconds
                    migrations += 1
                    migration_seconds += dec.cost_seconds
        rates = progress_rates(
            [j for j in live if j.pause_until <= t], alpha)
        for j in live:
            r = rates.get(j.key)
            if r is None:
                continue
            j.done += r * DT
            if j.done >= j.work:
                j.finish = t + DT
                j.placement = None
                next_round = t + DT  # replan on completion: backfill now
        if any(j.arrival > t and j.arrival <= t + DT for j in jobs):
            next_round = t + DT  # replan on arrival
        t += DT
    res = finalize(jobs, t, preemptions, migrations, migration_seconds)
    res.mem_rejections = mem_rejections
    return res


# --------------------------------------------------------------------------
def measured_reshard_seconds(root: str = ".") -> tuple:
    """Worst measured live-reshard transition from the latest reshard
    bench artifact -- the scheduler's migration-cost accounting must use
    the MEASURED number (ISSUE 11), not a flattering guess."""
    from kubeflow_tpu.analysis import latest_reshard_bench

    parsed, artifact = latest_reshard_bench(root)
    if parsed is None:
        return 0.2, "default (no reshard bench artifact found)"
    rows = parsed.get("extra", {}).get("reshard", [])
    secs = max((r.get("reshard_seconds", 0.0) for r in rows),
               default=0.2)
    return secs, artifact


def main() -> int:
    alpha = 0.8
    reshard_s, cost_source = measured_reshard_seconds()
    fifo = run_fifo(alpha)
    blind = run_policy(alpha, contention_weight=0.0,
                       reshard_seconds=reshard_s)
    sched = run_policy(alpha, contention_weight=1.0,
                       reshard_seconds=reshard_s)

    def dump(a: ArmResult) -> dict:
        return {
            "makespan_s": a.makespan,
            "aggregate_goodput_tok_s": a.goodput,
            "weighted_fairness_index": a.fairness,
            "preemptions": a.preemptions,
            "migrations": a.migrations,
            "migration_seconds": a.migration_seconds,
            "mem_rejections": a.mem_rejections,
            "per_job": a.per_job,
        }

    result = {
        "metric": "sched_goodput_vs_fifo",
        "value": round(sched.goodput / fifo.goodput, 3),
        "unit": "x",
        "vs_baseline": round(sched.goodput / fifo.goodput, 3),
        "extra": {
            "sched": {
                "goodput_vs_fifo": round(sched.goodput / fifo.goodput, 3),
                "contention_gain": round(sched.goodput / blind.goodput, 3),
                "fairness_index": sched.fairness,
                "arms": {
                    "fifo": dump(fifo),
                    "sched_blind": dump(blind),
                    "sched": dump(sched),
                },
                "cluster": {
                    "domains": [{"name": d.name, "chips": d.chips}
                                for d in domains()],
                    "total_chips": sum(d.chips for d in domains()),
                    "jobs": len(job_mix()),
                    "tenants": 3,
                },
                "migration": {
                    "reshard_seconds_used": reshard_s,
                    "restart_seconds_used": RESTART_SECONDS,
                    "cost_source": cost_source,
                },
                # Which jobs resolved collective intensity from measured
                # shard-audit bytes (kftpu.io/comm-bytes-per-step) vs the
                # census prior. The ramp inverse is exact, so measured
                # jobs land on identical intensities -- provenance only.
                "intensity": {"sources": intensity_sources(job_mix())},
                # Memory-feasibility mask report: which jobs resolved a
                # per-device HBM peak (all "static" here -- the audited
                # mem.peak_bytes baseline; no measured samples in the
                # mix) and how many placements the mask refused. The
                # audited peaks are MBs against 16 GiB/chip v5e
                # domains, so rejections stay 0 -- the counter proves
                # the gate is wired without perturbing the arms.
                "memory": {
                    "rejections": sched.mem_rejections,
                    "fit_sources": fit_sources(job_mix()),
                },
                "sim": {
                    "dt_s": DT,
                    "replan_every_s": REPLAN_EVERY,
                    "contention_alpha": alpha,
                },
                "honesty": (
                    "policy code is the production scheduler module; the "
                    "cluster is simulated (deterministic discrete-event, "
                    "shared contention physics across all arms) -- arms "
                    "differ only in policy, and migration pauses use the "
                    "measured live-reshard seconds from the reshard bench"
                ),
            },
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
