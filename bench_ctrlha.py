"""Controller-crash HA bench (KT-PERF-CTRLHA family).

Certifies the ISSUE-19 contract end to end with REAL processes: a
child controller (``--serve`` mode of this same file) admits two
JAXJobs and spawns real training workers, then the ``controller.crash``
chaos seam SIGKILLs that controller at a deterministic reconcile hit.
The workers must not notice: they keep stepping through the outage
(verified from their metric logs), and a successor controller -- same
store file, fresh process -- must take over the actuation lease and
ADOPT them from the runtime journal: same pids, zero respawns,
restart_count unchanged.

Measured (ratcheted by ``analysis/perf.py::_check_ctrlha``):

- ``worker_deaths``        -- journaled pids that died with the
                              controller (must be 0)
- ``duplicate_spawns``     -- new pids/log files after adoption
                              (must be 0: adoption, not respawn)
- ``restart_count_delta``  -- per-job restart_count movement (must be
                              0: adoption is not a gang restart)
- ``adoption_seconds``     -- successor start -> last GangAdopted
                              event (includes the lease-expiry wait)

Replicas are 1 per job (cross-process SPMD is unimplemented on the XLA
CPU backend); the adoption machinery is identical for wider gangs.

Run:  python bench_ctrlha.py            # JSON line to stdout
      python bench_ctrlha.py --serve --store S --logs D   # (internal)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import time

LEASE_SECONDS = 2.0
TOTAL_CHIPS = 8
JOB_NAMES = ("ha1", "ha2")
NAMESPACE = "default"

# Crash the first controller at the SECOND reconcile of the second
# job: its first reconcile spawned (and journaled) its gang, and the
# resulting status persist re-enqueues it, so hit 1 is guaranteed to
# occur -- after BOTH jobs' workers are journaled.
CRASH_PLAN = json.dumps({
    "seed": 19,
    "faults": [
        {"kind": "crash", "site": "controller.crash",
         "target": f"{NAMESPACE}/{JOB_NAMES[-1]}", "at": [1]},
    ],
})


def _base_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    env["KFTPU_LEASE_SECONDS"] = str(LEASE_SECONDS)
    return env


# -- child: a plain controller over a shared store file ----------------------

def serve(store_path: str, log_dir: str) -> None:
    from kubeflow_tpu.controller import (
        ControllerLease,
        GangScheduler,
        JobController,
        ProcessLauncher,
        RuntimeJournal,
    )
    from kubeflow_tpu.store import ObjectStore

    store = ObjectStore(store_path)
    ctl = JobController(
        store,
        ProcessLauncher(log_dir=log_dir),
        GangScheduler(total_chips=TOTAL_CHIPS),
        journal=RuntimeJournal(store),
        lease=ControllerLease(
            store,
            duration_seconds=float(
                os.environ.get("KFTPU_LEASE_SECONDS", LEASE_SECONDS)),
        ),
    )
    asyncio.run(ctl.run())


# -- parent: orchestrate kill + adoption and measure -------------------------

def _make_job(name: str):
    from kubeflow_tpu.api import (
        JobKind,
        JobSpec,
        ProcessTemplate,
        ReplicaSpec,
        ReplicaType,
        Resources,
        TrainJob,
        apply_defaults,
    )
    from kubeflow_tpu.api.types import ObjectMeta

    return apply_defaults(TrainJob(
        kind=JobKind.JAXJob,
        metadata=ObjectMeta(name=name, namespace=NAMESPACE),
        spec=JobSpec(
            replica_specs={
                ReplicaType.Worker: ReplicaSpec(
                    replicas=1,
                    template=ProcessTemplate(
                        entrypoint="kubeflow_tpu.runtime.entry",
                        args=["--model", "llama", "--steps", "200000",
                              "--log-every", "5",
                              "--arg", "preset=llama-tiny",
                              "--arg", "batch_size=8",
                              "--arg", "seq_len=16"],
                    ),
                    resources=Resources(tpu=4),
                )
            }
        ),
    ))


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _journal_pids(store) -> dict:
    """{job_key: {worker_id: pid}} from the runtime journal."""
    from kubeflow_tpu.controller.journal import JOURNAL_KIND

    out: dict = {}
    for rec in store.list(JOURNAL_KIND):
        md = rec.get("metadata") or {}
        key = f"{md.get('namespace')}/{md.get('name')}"
        out[key] = {
            wid: int(ent["pid"])
            for wid, ent in (rec.get("workers") or {}).items()
        }
    return out


def _steps_in_log(path: str) -> int:
    from kubeflow_tpu.runtime.metrics import parse_metric_line

    n = 0
    try:
        with open(path, errors="replace") as f:
            for line in f:
                m = parse_metric_line(line)
                if m and "step" in m:
                    n = max(n, int(float(m["step"])) + 1)
    except OSError:
        pass
    return n


def _spawn_controller(store_path: str, log_dir: str,
                      chaos_plan: str | None) -> subprocess.Popen:
    env = _base_env()
    if chaos_plan:
        env["KFTPU_CHAOS_PLAN"] = chaos_plan
    else:
        env.pop("KFTPU_CHAOS_PLAN", None)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve",
         "--store", store_path, "--logs", log_dir],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def _wait(pred, timeout: float, interval: float = 0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    return None


def run_bench(workdir: str) -> dict:
    from kubeflow_tpu.api import TrainJob
    from kubeflow_tpu.store import ObjectStore

    store_path = os.path.join(workdir, "store.db")
    log_dir = os.path.join(workdir, "logs")
    os.makedirs(log_dir, exist_ok=True)

    store = ObjectStore(store_path)
    jobs = [_make_job(n) for n in JOB_NAMES]
    for job in jobs:
        store.put(job.kind.value, job.to_dict())
    job_keys = [f"{NAMESPACE}/{n}" for n in JOB_NAMES]

    victim_pids: dict = {}
    ha: dict = {}
    worker_pids: set = set()
    ctl_b = None
    try:
        # -- phase 1: controller A spawns the gangs, chaos kills it.
        ctl_a = _spawn_controller(store_path, log_dir, CRASH_PLAN)
        rc = _wait(lambda: ctl_a.poll(), timeout=180.0)
        if rc is None:
            ctl_a.kill()
            raise RuntimeError("controller A outlived its crash plan")
        ha["controller_killed"] = (rc == -signal.SIGKILL)
        t_kill = time.monotonic()

        victim_pids = _journal_pids(store)
        worker_pids = {p for ws in victim_pids.values()
                       for p in ws.values()}
        if sorted(victim_pids) != sorted(job_keys) or not worker_pids:
            raise RuntimeError(
                f"journal incomplete at crash: {victim_pids}")

        # -- phase 2: the outage. Workers must keep stepping with no
        # controller alive at all.
        logs = sorted(os.listdir(log_dir))
        before = {f: _steps_in_log(os.path.join(log_dir, f)) for f in logs}
        progressed = _wait(
            lambda: all(
                _steps_in_log(os.path.join(log_dir, f)) > before[f]
                for f in logs),
            timeout=60.0, interval=0.25)
        ha["workers_progressed_during_outage"] = bool(progressed)
        ha["outage_seconds_observed"] = round(time.monotonic() - t_kill, 3)

        # -- phase 3: successor adopts.
        t_b = time.monotonic()
        ctl_b = _spawn_controller(store_path, log_dir, None)

        def adopted_all():
            reasons: dict = {}
            for ev in store.list("Event"):
                if ev.get("reason") in ("GangAdopted", "GangAdoptionFailed"):
                    reasons.setdefault(ev.get("involved"), ev["reason"])
            if all(reasons.get(k) for k in job_keys):
                return reasons
            return None

        reasons = _wait(adopted_all, timeout=60.0)
        if reasons is None:
            raise RuntimeError("successor never adopted the gangs")
        ha["adopted"] = all(
            reasons.get(k) == "GangAdopted" for k in job_keys)
        ha["adoption_seconds"] = round(time.monotonic() - t_b, 3)

        # -- phase 4: the contract.
        after_pids = _journal_pids(store)
        new = {p for ws in after_pids.values() for p in ws.values()}
        ha["worker_deaths"] = sum(
            1 for p in worker_pids if not _pid_alive(p))
        ha["duplicate_spawns"] = (
            len(new - worker_pids)
            + max(0, len(os.listdir(log_dir)) - len(logs)))
        ha["pid_set_unchanged"] = (new == worker_pids)
        restarts = 0
        for job in jobs:
            obj = store.get(job.kind.value, job.name, job.namespace)
            restarts += TrainJob.from_dict(obj).status.restart_count
        ha["restart_count_delta"] = restarts
        ha["lease_seconds"] = LEASE_SECONDS
        ha["jobs"] = len(jobs)
        ha["workers"] = len(worker_pids)
    finally:
        if ctl_b is not None:
            ctl_b.terminate()
            try:
                ctl_b.wait(timeout=5)
            except subprocess.TimeoutExpired:
                ctl_b.kill()
        for pid in worker_pids:
            for sig in (signal.SIGTERM, signal.SIGKILL):
                try:
                    os.killpg(pid, sig)
                except (ProcessLookupError, PermissionError, OSError):
                    pass
        store.close()

    return {
        "metric": "ctrlha_adoption_seconds",
        "value": ha.get("adoption_seconds"),
        "unit": "s (successor start -> last GangAdopted, incl. lease expiry)",
        "vs_baseline": None,
        "extra": {"ctrlha": ha},
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--store")
    ap.add_argument("--logs")
    ap.add_argument("--workdir")
    args = ap.parse_args()
    if args.serve:
        serve(args.store, args.logs)
        return
    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        print(json.dumps(run_bench(args.workdir)))
        return
    import tempfile

    with tempfile.TemporaryDirectory(prefix="kftpu-ctrlha-") as td:
        print(json.dumps(run_bench(td)))


if __name__ == "__main__":
    main()
