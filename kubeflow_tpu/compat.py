"""Small shims over jax API renames, so one source tree runs on the
jax the image ships AND on current releases.

Covered here (the Pallas CompilerParams rename is shimmed locally in
ops/decode_attention.py, same pattern):

- ``jax.shard_map``: top-level promotion of
  ``jax.experimental.shard_map.shard_map``. The promoted API renamed
  ``check_rep`` -> ``check_vma`` and replaced ``auto`` (the mesh axes
  NOT manual in the region) with ``axis_names`` (the axes that ARE).
  ``shard_map`` below accepts the NEW spelling and translates when only
  the experimental function exists.
- ``jax.lax.axis_size``: newer jax exposes the STATIC size of a named
  mesh axis directly; older jax only has ``jax.core.axis_frame``, which
  returns that size as a plain int. Both are static (usable in Python
  control flow / ``range``), unlike ``psum(1, axis)``.
"""

from typing import Optional

import jax

__all__ = ["axis_size", "inside_manual_region", "shard_map"]


def inside_manual_region() -> bool:
    """True when tracing inside a shard_map manual region (e.g. the gpipe
    pipeline body). Nested shard_maps and GSPMD sharding constraints are
    both rejected there, so callers fall back (GSPMD attention, no-op
    constraint). New jax exposes the abstract mesh's axis types; on older
    jax any bound named axis means a manual region is on the trace stack,
    because the legacy fallback in :func:`shard_map` below always runs
    fully manual."""
    mesh_fn = getattr(jax.sharding, "get_abstract_mesh", None)
    if mesh_fn is not None:
        mesh = mesh_fn()
        return any(
            "Manual" in str(t) for t in getattr(mesh, "axis_types", ())
        )
    try:
        from jax._src import core as _src_core

        return bool(_src_core.get_axis_env().axis_sizes)
    except Exception:  # kt-lint: disable=KT-SWALLOW01 -- private-API probe
        # across jax lineages; absence just means "not manual".
        return False


def axis_size(axis_name) -> int:
    """Static size of a named axis inside a shard_map/manual region."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.core.axis_frame(axis_name)


def shard_map(f, mesh, in_specs, out_specs,
              axis_names: Optional[frozenset] = None,
              check_vma: Optional[bool] = None):
    """``jax.shard_map`` with new-style kwargs on either jax lineage."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as legacy

    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    # axis_names (axes that ARE manual) would translate to legacy
    # ``auto`` = the complement -- but legacy partial-auto lowering
    # emits a PartitionId instruction the XLA:CPU SPMD partitioner
    # rejects (observed jaxlib 0.4.x). Running fully manual instead is
    # value-equivalent for our callers: specs not naming the extra mesh
    # axes replicate over them either way, at worst re-sharding an
    # input that partial-auto would have left distributed.
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
