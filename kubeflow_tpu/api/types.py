"""Job API types.

The declarative surface of the control plane. Mirrors the capability set of
training-operator's ``kubeflow.org/v1`` API (SURVEY.md 3.1 T1):

- ``TrainJob`` is the envelope object (kind + metadata + spec + status),
  playing the role of a CRD instance.
- ``ReplicaSpec`` ~ the reference's ``ReplicaSpec{replicas, template,
  restartPolicy}``; the pod template becomes a ``ProcessTemplate`` because
  workloads here are host processes, not containers.
- ``RunPolicy`` carries cleanPodPolicy / ttlSecondsAfterFinished /
  activeDeadlineSeconds / backoffLimit / schedulingPolicy with the same
  semantics as the reference.
- ``JobStatus`` is the conditions + replicaStatuses state machine users
  watch, same shape as the reference's status subresource.

TPU-first deltas (SURVEY.md 3.5, 5.3):

- ``Resources.tpu`` counts chips; gang admission is all-or-nothing at
  slice granularity (a slice is indivisible on TPU).
- ``ElasticPolicy`` means *slice-count* elasticity: resize happens by
  quiesce -> checkpoint -> respawn with a new process count -> resharded
  restore, not per-chip join/leave as in torch elastic.
- ``CheckpointPolicy`` is first-class (the reference leaves checkpointing
  to user code; our runtime owns it via orbax).
"""

from __future__ import annotations

import enum
import time
from typing import Any, Literal, Optional

from pydantic import BaseModel, ConfigDict, Field, model_validator


class JobKind(str, enum.Enum):
    """Supported job kinds.

    JAXJob is the native kind. TFJob/PyTorchJob/MPIJob keep the reference's
    replica vocabularies and env-injection contracts (SURVEY.md 3.1 T3-T5)
    so specs written against the reference's API shape port over.
    """

    JAXJob = "JAXJob"
    TFJob = "TFJob"
    PyTorchJob = "PyTorchJob"
    MPIJob = "MPIJob"
    XGBoostJob = "XGBoostJob"
    PaddleJob = "PaddleJob"


class ReplicaType(str, enum.Enum):
    """Union of replica vocabularies across kinds.

    Per-kind valid sets are enforced in validation.py (the reference does
    this in per-controller ValidateV1*JobSpec functions).
    """

    Worker = "Worker"
    Master = "Master"
    Chief = "Chief"
    PS = "PS"
    Evaluator = "Evaluator"
    Launcher = "Launcher"


class RestartPolicy(str, enum.Enum):
    """Per-replica restart policy (reference: Never/OnFailure/Always/ExitCode).

    ExitCode: only exit codes classified as transient (see
    ``controller.restarts.is_retryable_exit``) trigger a restart.
    """

    Never = "Never"
    OnFailure = "OnFailure"
    Always = "Always"
    ExitCode = "ExitCode"


class CleanPodPolicy(str, enum.Enum):
    """What to tear down on job completion (reference default: Running)."""

    Running = "Running"
    All = "All"
    NoneP = "None"


class ConditionType(str, enum.Enum):
    Created = "Created"
    Running = "Running"
    Restarting = "Restarting"
    Succeeded = "Succeeded"
    Failed = "Failed"
    Suspended = "Suspended"


class JobPhase(str, enum.Enum):
    """Condensed single-value phase derived from conditions."""

    Pending = "Pending"
    Running = "Running"
    Restarting = "Restarting"
    Succeeded = "Succeeded"
    Failed = "Failed"
    Suspended = "Suspended"


class Resources(BaseModel):
    """Per-replica resource request.

    ``tpu`` counts chips (the google.com/tpu resource of the north star);
    admission treats the chips of one replica as an indivisible unit.
    """

    model_config = ConfigDict(extra="forbid")

    tpu: int = 0
    cpu: float = 1.0
    memory_gb: float = 1.0


class ProcessTemplate(BaseModel):
    """Process template, standing in for the reference's pod template.

    ``entrypoint`` is a python module path run as ``python -m <module>``
    (or an executable path when ``exec_`` is true). The controller appends
    rendezvous env (coordinator address, process id/count) per job kind at
    spawn time -- the analog of TF_CONFIG / MASTER_ADDR / hostfile wiring.
    """

    model_config = ConfigDict(extra="forbid", populate_by_name=True)

    entrypoint: str
    args: list[str] = Field(default_factory=list)
    env: dict[str, str] = Field(default_factory=dict)
    workdir: Optional[str] = None
    exec_: bool = Field(default=False, alias="exec")


class ReplicaSpec(BaseModel):
    model_config = ConfigDict(extra="forbid")

    replicas: int = Field(default=1, ge=0)
    template: ProcessTemplate
    restart_policy: RestartPolicy = RestartPolicy.OnFailure
    resources: Resources = Field(default_factory=Resources)


class SchedulingPolicy(BaseModel):
    """Gang-scheduling knobs (reference: RunPolicy.schedulingPolicy, T7).

    ``min_available`` mirrors the reference's minMember and defaults to the
    full gang. Admission itself is always all-or-nothing at the formed gang
    size (TPU slice atomicity); forming *below* spec size is expressed via
    ``ElasticPolicy.min_replicas``, not this field.
    """

    model_config = ConfigDict(extra="forbid")

    min_available: Optional[int] = None
    queue: str = "default"
    priority: int = 0
    # Multi-tenant scheduler inputs (controller/scheduler.py). ``tenant``
    # groups jobs for cluster-level weighted max-min fairness (defaults
    # to the job's namespace when unset); ``weight`` is the tenant/job
    # share in the water-filling; ``priority_class`` fixes the workload
    # class used for SLO-aware preemption ordering (serving preempts
    # train preempts hpo) -- unset, the class is inferred from the
    # ``kftpu.io/workload-class`` annotation or the queue name.
    tenant: Optional[str] = None
    weight: float = Field(default=1.0, gt=0)
    priority_class: Optional[Literal["serving", "train", "hpo"]] = None
    # "Never" (default): the gang waits in the queue for free capacity.
    # "PreemptLowerPriority": a gang that cannot be admitted may evict
    # strictly-lower-priority running gangs (Volcano preempt action /
    # k8s PriorityClass preemptionPolicy semantics). On TPU the victim is
    # quiesced whole-slice and resumes from its latest checkpoint.
    preemption: Literal["Never", "PreemptLowerPriority"] = "Never"


class ElasticPolicy(BaseModel):
    """Slice-count elasticity (SURVEY.md 5.3).

    min/max replicas bound the worker count the reconciler may re-form the
    job at after failures or capacity changes. ``max_restarts`` bounds
    re-formations. On TPU, resize granularity is whole replicas (slices),
    re-formed via checkpoint/restore with resharding.
    """

    model_config = ConfigDict(extra="forbid")

    min_replicas: int = Field(default=1, ge=1)
    max_replicas: int = Field(default=1, ge=1)
    max_restarts: int = Field(default=3, ge=0)
    # Metric-driven resize (reference: ElasticPolicy metrics -> HPA).
    # ``metric`` names a key from the worker's KFTPU-METRIC lines (e.g.
    # "tokens_per_sec", "queue_depth"); the controller polls the lead
    # worker's output and applies the HPA formula
    # desired = ceil(current * value / target_value), clamped to
    # [min_replicas, max_replicas]. Resize = quiesce -> re-admit at the
    # new size -> resume from checkpoint (slice-granularity elasticity).
    metric: Optional[str] = None
    target_value: Optional[float] = Field(default=None, gt=0)
    metric_poll_seconds: float = Field(default=10.0, gt=0)
    # Live in-memory resharding (parallel/reshard.py): a resize is sent
    # to the running workers as a resize command instead of a gang
    # teardown -- the worker reshards its live state onto the new mesh
    # (a data-plane transfer measured in seconds, no orbax round-trip)
    # and acks over KFTPU-METRIC. Falls back to the checkpoint-restart
    # path when the plan is infeasible or the ack times out. Requires a
    # checkpoint dir (the fallback path and the command file live there).
    reshard_in_place: bool = False
    reshard_timeout_seconds: float = Field(default=60.0, gt=0)
    # Cede resize authority to the cluster scheduler: when True the
    # per-job metric scaler is disarmed (the cluster scheduler's rounds
    # become the single writer of resize decisions, so the two paths can
    # never issue concurrent resizes for one job). ``metric`` may still
    # be set -- it then only feeds the scheduler's throughput model.
    scheduler_managed: bool = False


class CheckpointPolicy(BaseModel):
    model_config = ConfigDict(extra="forbid")

    dir: Optional[str] = None
    interval_steps: int = Field(default=100, ge=1)
    keep: int = Field(default=3, ge=1)
    resume: bool = True


class ProfilingPolicy(BaseModel):
    """jax.profiler tracing for a window of training steps (SURVEY.md 5.1:
    the reference delegates profiling to in-container TensorBoard
    profilers; this runtime owns it via a job-spec flag). The trace is
    TensorBoard/Perfetto-viewable."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    dir: Optional[str] = None  # default: <log_dir>/profile/<job>
    start_step: int = Field(default=2, ge=0)  # skip compile steps
    num_steps: int = Field(default=3, ge=1)


class SLOSpec(BaseModel):
    """Service-level objectives the telemetry plane's burn-rate engine
    evaluates (multiwindow, Google SRE-workbook style): training jobs
    declare a goodput-fraction floor, serving jobs TTFT/ITL ceilings
    with an availability target. An alert fires only when BOTH the fast
    and the slow window burn the error budget faster than
    ``burn_threshold``; it lands as a store event, a pair of gauges, and
    pressure on the router's shed threshold and the scheduler's victim
    ordering."""

    model_config = ConfigDict(extra="forbid")

    # Training: minimum acceptable goodput fraction (compute seconds /
    # gang-hold seconds). The error budget is 1 - goodput_floor.
    goodput_floor: Optional[float] = Field(default=None, gt=0, le=1)
    # Serving: latency ceilings. A sample over the ceiling is "bad";
    # the budget is 1 - availability of samples allowed to be bad.
    ttft_ms: Optional[float] = Field(default=None, gt=0)
    itl_ms: Optional[float] = Field(default=None, gt=0)
    availability: float = Field(default=0.99, gt=0, lt=1)
    # Multiwindow burn-rate evaluation: the fast window catches a cliff
    # quickly, the slow window keeps one transient spike from paging.
    fast_window_seconds: float = Field(default=300.0, gt=0)
    slow_window_seconds: float = Field(default=3600.0, gt=0)
    burn_threshold: float = Field(default=2.0, gt=0)

    @model_validator(mode="after")
    def _windows_ordered(self) -> "SLOSpec":
        if self.fast_window_seconds > self.slow_window_seconds:
            raise ValueError(
                "fast_window_seconds must not exceed slow_window_seconds"
            )
        return self


class RunPolicy(BaseModel):
    """Job-level lifecycle policy; same field semantics as the reference."""

    model_config = ConfigDict(extra="forbid")

    clean_pod_policy: CleanPodPolicy = CleanPodPolicy.Running
    ttl_seconds_after_finished: Optional[int] = Field(default=None, ge=0)
    active_deadline_seconds: Optional[int] = Field(default=None, ge=1)
    backoff_limit: int = Field(default=3, ge=0)
    scheduling: SchedulingPolicy = Field(default_factory=SchedulingPolicy)
    suspend: bool = False
    # Hang detection (SURVEY.md 5.3 heartbeats): a worker that wedges
    # without exiting (e.g. a stuck collective) stalls the whole gang's
    # output. If no worker writes anything for this long, the gang is
    # restarted through the normal crash-loop path. Must exceed the
    # longest legitimate quiet period (first-step compile!). None = off.
    hang_timeout_seconds: Optional[float] = Field(default=None, gt=0)


class JobSpec(BaseModel):
    model_config = ConfigDict(extra="forbid")

    replica_specs: dict[ReplicaType, ReplicaSpec]
    run_policy: RunPolicy = Field(default_factory=RunPolicy)
    elastic: Optional[ElasticPolicy] = None
    checkpoint: CheckpointPolicy = Field(default_factory=CheckpointPolicy)
    profiling: ProfilingPolicy = Field(default_factory=ProfilingPolicy)
    # Process count per replica when one replica hosts multiple JAX
    # processes (== nproc_per_node in torch terms). Almost always 1 here:
    # one process per host, all local chips visible to it.
    nproc_per_replica: int = Field(default=1, ge=1)
    # Service-level objectives for the burn-rate engine. None = the
    # telemetry plane scrapes the job but never alerts on it.
    slo: Optional[SLOSpec] = None


class Condition(BaseModel):
    type: ConditionType
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition: float = Field(default_factory=time.time)


class ReplicaStatus(BaseModel):
    active: int = 0
    succeeded: int = 0
    failed: int = 0


class JobStatus(BaseModel):
    conditions: list[Condition] = Field(default_factory=list)
    replica_statuses: dict[ReplicaType, ReplicaStatus] = Field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    restart_count: int = 0
    # Observed worker count the job is currently formed at (elastic).
    formed_replicas: Optional[int] = None

    @property
    def phase(self) -> JobPhase:
        order = [
            ConditionType.Failed,
            ConditionType.Succeeded,
            ConditionType.Suspended,
            ConditionType.Restarting,
            ConditionType.Running,
            ConditionType.Created,
        ]
        active = {c.type for c in self.conditions if c.status}
        for t in order:
            if t in active:
                return {
                    ConditionType.Created: JobPhase.Pending,
                    ConditionType.Running: JobPhase.Running,
                    ConditionType.Restarting: JobPhase.Restarting,
                    ConditionType.Succeeded: JobPhase.Succeeded,
                    ConditionType.Failed: JobPhase.Failed,
                    ConditionType.Suspended: JobPhase.Suspended,
                }[t]
        return JobPhase.Pending

    def set_condition(self, ctype: ConditionType, reason: str = "", message: str = "") -> None:
        """Set ``ctype`` true, flipping mutually-exclusive conditions false.

        Mirrors the reference's util.UpdateJobConditions: Running/Restarting
        /Succeeded/Failed are mutually exclusive; Created stays true forever.
        """
        exclusive = {
            ConditionType.Running,
            ConditionType.Restarting,
            ConditionType.Succeeded,
            ConditionType.Failed,
            ConditionType.Suspended,
        }
        now = time.time()
        found = False
        for c in self.conditions:
            if c.type == ctype:
                if not c.status or c.reason != reason or c.message != message:
                    c.status, c.reason, c.message, c.last_transition = True, reason, message, now
                found = True
            elif ctype in exclusive and c.type in exclusive and c.status:
                c.status, c.last_transition = False, now
        if not found:
            self.conditions.append(
                Condition(type=ctype, reason=reason, message=message, last_transition=now)
            )

    def has_condition(self, ctype: ConditionType) -> bool:
        return any(c.type == ctype and c.status for c in self.conditions)


def phase_of_obj(obj: dict) -> str:
    """Condensed phase from a raw (dict) object's status conditions.

    The single source of the condition-priority ordering for clients that
    work with plain JSON (CLI tables, SDK polling); JobStatus.phase is the
    typed equivalent.
    """
    conds = obj.get("status", {}).get("conditions", [])
    active = {c.get("type") for c in conds if c.get("status")}
    for t in ("Failed", "Succeeded", "Suspended", "Restarting", "Running",
              "Ready", "Unready", "Created"):
        if t in active:
            return "Pending" if t == "Created" else t
    return "Pending"


class ObjectMeta(BaseModel):
    model_config = ConfigDict(extra="forbid")

    name: str
    namespace: str = "default"
    labels: dict[str, str] = Field(default_factory=dict)
    annotations: dict[str, str] = Field(default_factory=dict)
    uid: Optional[str] = None
    creation_time: Optional[float] = None
    generation: int = 0


class TrainJob(BaseModel):
    """The envelope object: one CRD-instance equivalent."""

    model_config = ConfigDict(extra="forbid")

    kind: JobKind = JobKind.JAXJob
    metadata: ObjectMeta
    spec: JobSpec
    status: JobStatus = Field(default_factory=JobStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def total_replicas(self) -> int:
        return sum(rs.replicas for rs in self.spec.replica_specs.values())

    def total_tpu_chips(self) -> int:
        return sum(
            rs.replicas * rs.resources.tpu for rs in self.spec.replica_specs.values()
        )

    @classmethod
    def from_dict(cls, obj: dict[str, Any]) -> "TrainJob":
        return cls.model_validate(obj)

    def to_dict(self) -> dict[str, Any]:
        return self.model_dump(mode="json", by_alias=True)
