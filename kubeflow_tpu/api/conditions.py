"""Shared condition-list machinery for dict-based statuses.

One implementation of the reference's UpdateJobConditions semantics
(mutually-exclusive active conditions, sticky Created, no-op writes do not
bump last_transition) used by every non-TrainJob status (Experiment,
Trial, InferenceService). JobStatus has the typed equivalent in types.py;
the no-op guard here is load-bearing: a condition write that always
changes the status would make reconcile -> persist -> watch-event ->
reconcile a self-triggering hot loop.
"""

from __future__ import annotations

import time
from typing import Any, Iterable


def set_condition(
    conditions: list[dict[str, Any]],
    ctype: str,
    exclusive: Iterable[str],
    reason: str = "",
    message: str = "",
) -> None:
    exclusive = set(exclusive)
    now = time.time()
    found = False
    for c in conditions:
        if c["type"] == ctype:
            if not c["status"] or c["reason"] != reason or c["message"] != message:
                c.update(status=True, reason=reason, message=message,
                         last_transition=now)
            found = True
        elif ctype in exclusive and c["type"] in exclusive and c["status"]:
            c["status"], c["last_transition"] = False, now
    if not found:
        conditions.append({
            "type": ctype, "status": True, "reason": reason,
            "message": message, "last_transition": now,
        })


def has_condition(conditions: list[dict[str, Any]], ctype: str) -> bool:
    return any(c["type"] == ctype and c["status"] for c in conditions)


def phase_of(conditions: list[dict[str, Any]], order: tuple[str, ...]) -> str:
    for t in order:
        if has_condition(conditions, t):
            return "Pending" if t == "Created" else t
    return "Pending"
