"""API types for the control plane: job specs, statuses, conditions.

Equivalent of training-operator's CRD Go structs (SURVEY.md section 3.1, T1):
TFJob/PyTorchJob/MPIJob/JAXJob types, ReplicaSpec, RunPolicy, JobStatus.
Here they are pydantic models: YAML specs are validated/defaulted on
submit, exactly as the reference's defaulting+validating webhooks (T8) do.
"""

from kubeflow_tpu.api.types import (  # noqa: F401
    CheckpointPolicy,
    CleanPodPolicy,
    Condition,
    ConditionType,
    ElasticPolicy,
    JobKind,
    JobPhase,
    JobSpec,
    JobStatus,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    Resources,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    SLOSpec,
    TrainJob,
)
from kubeflow_tpu.api.validation import apply_defaults, validate_job  # noqa: F401
