"""Defaulting + validation for job specs.

Equivalent of the reference's admission webhooks (SURVEY.md 3.1 T8): the
mutating webhook's defaults are applied at submit time so the stored spec
is complete; the validating webhook's per-kind rules are enforced here.
"""

from __future__ import annotations

from kubeflow_tpu.api.types import (
    ElasticPolicy,
    JobKind,
    ReplicaType,
    TrainJob,
)

# Valid replica vocabularies per kind, mirroring the per-controller
# validation in the reference (T3: TFJob PS/Worker/Chief/Evaluator/Master;
# T4: PyTorchJob Master/Worker; T5: MPIJob Launcher/Worker).
VALID_REPLICA_TYPES: dict[JobKind, set[ReplicaType]] = {
    JobKind.JAXJob: {ReplicaType.Worker},
    JobKind.TFJob: {
        ReplicaType.Chief,
        ReplicaType.Master,
        ReplicaType.Worker,
        ReplicaType.PS,
        ReplicaType.Evaluator,
    },
    JobKind.PyTorchJob: {ReplicaType.Master, ReplicaType.Worker},
    JobKind.MPIJob: {ReplicaType.Launcher, ReplicaType.Worker},
    JobKind.XGBoostJob: {ReplicaType.Master, ReplicaType.Worker},
    JobKind.PaddleJob: {ReplicaType.Master, ReplicaType.Worker},
}

# Replica types whose rank-0 success decides job success (reference: TFJob
# succeeds on chief/worker-0; PyTorchJob on master/worker-0; MPIJob on the
# launcher's exit code).
SUCCESS_POLICY_REPLICA: dict[JobKind, list[ReplicaType]] = {
    JobKind.JAXJob: [ReplicaType.Worker],
    JobKind.TFJob: [ReplicaType.Chief, ReplicaType.Master, ReplicaType.Worker],
    JobKind.PyTorchJob: [ReplicaType.Master, ReplicaType.Worker],
    JobKind.MPIJob: [ReplicaType.Launcher],
    JobKind.XGBoostJob: [ReplicaType.Master, ReplicaType.Worker],
    JobKind.PaddleJob: [ReplicaType.Master, ReplicaType.Worker],
}


class ValidationError(ValueError):
    pass


def apply_defaults(job: TrainJob) -> TrainJob:
    """Fill derived defaults; stored spec becomes complete (SURVEY.md 5.6)."""
    sched = job.spec.run_policy.scheduling
    if sched.min_available is None and job.total_replicas() > 0:
        # None on an all-zero-replica job (suspended/scaled-to-zero shape)
        # stays None, meaning "full gang, whatever size it forms at".
        sched.min_available = job.total_replicas()
    if job.spec.elastic is None and job.kind == JobKind.JAXJob:
        n = job.spec.replica_specs[ReplicaType.Worker].replicas if (
            ReplicaType.Worker in job.spec.replica_specs
        ) else 1
        if n >= 1:  # zero-replica (suspended) jobs get no elastic default
            job.spec.elastic = ElasticPolicy(min_replicas=n, max_replicas=n)
    return job


def validate_job(job: TrainJob) -> None:
    """Raise ValidationError on an invalid spec."""
    if not job.metadata.name or "/" in job.metadata.name:
        raise ValidationError(f"invalid job name {job.metadata.name!r}")
    if not job.spec.replica_specs:
        raise ValidationError("job has no replica specs")

    valid = VALID_REPLICA_TYPES[job.kind]
    for rtype, rspec in job.spec.replica_specs.items():
        if rtype not in valid:
            raise ValidationError(
                f"{job.kind.value} does not allow replica type {rtype.value}; "
                f"allowed: {sorted(t.value for t in valid)}"
            )
        if rspec.replicas < 0:
            raise ValidationError(f"{rtype.value}.replicas must be >= 0")
        if rspec.resources.tpu < 0:
            raise ValidationError(f"{rtype.value}.resources.tpu must be >= 0")
        if not rspec.template.entrypoint:
            raise ValidationError(f"{rtype.value}.template.entrypoint is required")

    # Kind-specific structural rules.
    if job.kind == JobKind.PyTorchJob:
        masters = job.spec.replica_specs.get(ReplicaType.Master)
        if masters and masters.replicas > 1:
            raise ValidationError("PyTorchJob allows at most 1 Master replica")
    if job.kind == JobKind.MPIJob:
        launcher = job.spec.replica_specs.get(ReplicaType.Launcher)
        if launcher is None:
            raise ValidationError("MPIJob requires a Launcher replica")
        if launcher.replicas != 1:
            raise ValidationError("MPIJob requires exactly 1 Launcher replica")

    el = job.spec.elastic
    if el is not None:
        if not (1 <= el.min_replicas <= el.max_replicas):
            raise ValidationError(
                f"elastic policy requires 1 <= min ({el.min_replicas}) <= max "
                f"({el.max_replicas})"
            )
        if (el.metric is None) != (el.target_value is None):
            raise ValidationError(
                "elastic metric-driven resize requires both metric and "
                "target_value (or neither)"
            )

    sched = job.spec.run_policy.scheduling
    if sched.min_available is not None and sched.min_available < 1:
        raise ValidationError("scheduling.min_available must be >= 1")
    if sched.min_available is not None and sched.min_available > job.total_replicas():
        raise ValidationError(
            f"scheduling.min_available ({sched.min_available}) exceeds total "
            f"replicas ({job.total_replicas()})"
        )
