"""Shared template substitution for spec rendering.

One walker serves both template engines -- HPO trial templates
(``${trialParameters.<name>}``) and pipeline steps
(``${pipelineParameters.<name>}`` / ``${steps.<name>.output}``). All
substitution is textual (``str(value)``), the reference's template-engine
contract: placeholders belong in string-typed fields (args, env); the
rendered object is re-validated afterwards so a placeholder smuggled into
a numeric field fails loudly.
"""

from __future__ import annotations

from typing import Any, Mapping


def substitute(template: Any, mapping: Mapping[str, Any]) -> Any:
    """Replace every placeholder key of ``mapping`` in every string leaf
    of ``template`` (dicts/lists walked recursively)."""

    def subst(v: Any) -> Any:
        if isinstance(v, str):
            for ph, val in mapping.items():
                if v == ph:
                    return str(val)
                if ph in v:
                    v = v.replace(ph, str(val))
            return v
        if isinstance(v, dict):
            return {k: subst(x) for k, x in v.items()}
        if isinstance(v, list):
            return [subst(x) for x in v]
        return v

    return subst(template)
