"""Free-port allocation for coordinator rendezvous endpoints."""

from __future__ import annotations

import socket

_allocated: set[int] = set()


def allocate_port() -> int:
    """Pick a free TCP port on localhost.

    The OS-assigned ephemeral port is released before the worker binds it,
    so there is a benign TOCTOU window; we additionally avoid handing out
    the same port twice within this process (concurrent jobs).
    """
    for _ in range(16):
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        if port not in _allocated:
            _allocated.add(port)
            return port
    raise RuntimeError("could not allocate a free port")
