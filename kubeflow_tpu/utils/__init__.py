"""Shared utilities: ports, structured logging, metric-line format."""
