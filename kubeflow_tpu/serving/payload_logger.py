"""Request/response payload logger (SURVEY.md 3.3 S6, KServe's logger).

The reference's agent sidecar posts CloudEvents-wrapped request/response
payloads to a sink URL. Here the model server logs them itself: each
predict produces up to two events (request, response) written as JSONL to
a file sink or POSTed to an http sink (localhost only -- this environment
has no egress, and the reference's sink is an in-cluster collector
anyway). Events follow the CloudEvents-ish shape KServe emits:
``{id, type, source, time, model, data}``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, Optional

logger = logging.getLogger(__name__)

MODE_ALL = "all"
MODE_REQUEST = "request"
MODE_RESPONSE = "response"
MODES = (MODE_ALL, MODE_REQUEST, MODE_RESPONSE)

TYPE_REQUEST = "org.kubeflow.serving.inference.request"
TYPE_RESPONSE = "org.kubeflow.serving.inference.response"


class PayloadLogger:
    def __init__(self, sink: str, mode: str = MODE_ALL,
                 source: str = "kftpu-modelserver",
                 max_bytes: int = 1 << 20) -> None:
        if mode not in MODES:
            raise ValueError(f"logger mode {mode!r} not in {MODES}")
        self.sink = sink
        self.mode = mode
        self.source = source
        self.max_bytes = max_bytes
        self._http = sink.startswith(("http://", "https://"))
        self._session = None  # lazily-created shared aiohttp session
        # Fire-and-forget emits: retain tasks so they aren't GC'd mid-run;
        # close() drains them.
        self._tasks: set = set()

    def new_id(self) -> str:
        return str(uuid.uuid4())

    def _event(self, etype: str, model: str, payload: Any,
               request_id: str) -> dict:
        data = json.dumps(payload)
        if len(data) > self.max_bytes:
            data = data[: self.max_bytes]
        return {
            "id": request_id,
            "type": etype,
            "source": self.source,
            "time": time.time(),
            "model": model,
            "data": data,
        }

    async def log_request(self, model: str, payload: Any,
                          request_id: str) -> None:
        if self.mode in (MODE_ALL, MODE_REQUEST):
            self._schedule(self._event(TYPE_REQUEST, model, payload,
                                       request_id))

    async def log_response(self, model: str, payload: Any,
                           request_id: str) -> None:
        if self.mode in (MODE_ALL, MODE_RESPONSE):
            self._schedule(self._event(TYPE_RESPONSE, model, payload,
                                       request_id))

    def _schedule(self, event: dict) -> None:
        """Fire-and-forget: the predict path never waits on the sink."""
        task = asyncio.get_running_loop().create_task(self._emit(event))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def close(self) -> None:
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def _emit(self, event: dict) -> None:
        """Best-effort: logging must never fail a prediction."""
        try:
            if self._http:
                import aiohttp

                if self._session is None or self._session.closed:
                    self._session = aiohttp.ClientSession(
                        timeout=aiohttp.ClientTimeout(total=2)
                    )
                await self._session.post(self.sink, json=event)
            else:
                line = json.dumps(event) + "\n"
                await asyncio.to_thread(self._append, line)
        except Exception as e:  # noqa: BLE001 -- sink failures are non-fatal
            logger.warning("payload logger sink %s failed: %s", self.sink, e)

    def _append(self, line: str) -> None:
        path = self.sink[len("file://"):] if self.sink.startswith("file://") \
            else self.sink
        with open(path, "a") as f:
            f.write(line)


def from_json(cfg: Optional[str]) -> Optional[PayloadLogger]:
    """Build from the --logger-json flag ('{\"sink\":..,\"mode\":..}')."""
    if not cfg:
        return None
    d = json.loads(cfg)
    if not d.get("sink"):
        return None
    return PayloadLogger(d["sink"], d.get("mode", MODE_ALL))
