"""InferenceService controller + autoscaler + scale-to-zero activator
(KServe-equivalent S2 + Knative KPA/activator semantics, SURVEY.md 4.5).

Reconcile loop (same event-driven shape as JobController/HPOController):

ISVC applied -> validate -> for each component, converge actual replica
server processes to the desired count -> probe /healthz until Ready ->
status conditions. Replica processes are spawned through the same
ProcessLauncher the training reconciler uses (the "kubelet").

Autoscaling: desired = clamp(ceil(in_flight / target_concurrency),
min_replicas, max_replicas); when min_replicas=0 and the service has been
idle past the grace period, desired drops to 0 (scale-to-zero). The
activator buffers requests that arrive with zero ready replicas, triggers
a scale-up, and replays once a replica reports ready -- the reference's
activator->KPA cold-start path (SURVEY.md 7.4 #5).

TPU note: replica processes on this host share the one visible chip; the
jit compile cache makes the cold-start path survivable. Replicas with
``resources.tpu > 0`` reserve chips through the shared GangScheduler, so
serving and training contend for the same pool: a serving scale-up
queues behind pending training gangs (no backfill past their admission
slot) and proceeds when capacity frees.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import json
import logging
import math
import os
import re
import time
from typing import Dict, List, Optional

import aiohttp
from aiohttp import web

from kubeflow_tpu import chaos
from kubeflow_tpu.controller.launcher import BaseLauncher, SpawnRequest, WorkerRef
from kubeflow_tpu.obs import trace
from kubeflow_tpu.serving.router import (
    Router,
    RouterConfig,
    prefix_route_key,
)
from kubeflow_tpu.serving.types import (
    KIND,
    TRAINED_MODEL_KIND,
    ComponentSpec,
    ComponentStatus,
    InferenceService,
    ModelFormat,
    ReplicaInfo,
    ReplicaState,
    RUNTIMES,
    ServingValidationError,
    TrainedModel,
    set_condition,
    validate_isvc,
    validate_trained_model,
)
from kubeflow_tpu.utils.ports import allocate_port

logger = logging.getLogger(__name__)

PRIMARY = "predictor"  # component the activator routes to by default
# Transformer replica services are tracked under "{ns}/{name}#transformer",
# canary predictor sets under "{ns}/{name}#canary"; the suffixes never
# appear in object names ('#' is not name-legal).
TRANSFORMER_SUFFIX = "#transformer"
EXPLAINER_SUFFIX = "#explainer"
CANARY_SUFFIX = "#canary"


def _key_parts(key: str) -> tuple[str, str]:
    """(ns, name) of a service key, component suffix stripped."""
    ns, name = key.split("/", 1)
    for suffix in (TRANSFORMER_SUFFIX, EXPLAINER_SUFFIX, CANARY_SUFFIX):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    return ns, name


def _rollout_state(isvc: InferenceService) -> tuple[dict, Optional[dict], int, bool]:
    """(applied predictor dump, stable revision, pct, canarying?) — the
    ONE definition of "a canary rollout is in flight" shared by reconcile
    and the autoscaler, so they can never disagree on which spec governs
    the stable set."""
    pdump = isvc.spec.predictor.model_dump(mode="json", exclude_none=True)
    stable = isvc.status.stable_predictor
    pct = isvc.spec.canary_traffic_percent
    canarying = stable is not None and stable != pdump and pct < 100
    return pdump, stable, pct, canarying


def _governing_predictor(isvc: InferenceService) -> Optional[ComponentSpec]:
    """The component spec the PRIMARY predictor set is running right now:
    the stable revision mid-rollout, else the applied spec."""
    _, stable, _, canarying = _rollout_state(isvc)
    if canarying:
        try:
            return ComponentSpec.model_validate(stable)
        except ValueError:
            return None
    return isvc.spec.predictor


class _Replica:
    """Controller-side record of one running server process."""

    def __init__(self, index: int, port: int, ref: WorkerRef,
                 comp_fp: Optional[str] = None,
                 grpc_port: Optional[int] = None,
                 role: str = "mixed") -> None:
        self.index = index
        self.port = port
        self.grpc_port = grpc_port
        self.ref = ref
        # Fleet data-plane role (docs/FLEET.md): "prefill" replicas take
        # KV-handoff prefills only, never routed decode traffic.
        self.role = role
        self.ready = False
        self.in_flight = 0  # proxied requests on this replica (drain gate)
        self.started_at = time.time()
        # Component-spec fingerprint this replica was spawned from;
        # rollouts retire replicas whose fingerprint no longer matches.
        self.comp_fp = comp_fp
        # Chip reservation key held in the shared GangScheduler (None
        # when the component requests no TPU chips).
        self.res_key: Optional[str] = None

    def info(self) -> ReplicaInfo:
        return ReplicaInfo(
            index=self.index,
            port=self.port,
            grpc_port=self.grpc_port,
            pid=self.ref.pid,
            state=ReplicaState.Ready if self.ready else ReplicaState.Pending,
            started_at=self.started_at,
        )


class _Service:
    """In-memory state for one ISVC (the controller's expectations)."""

    def __init__(self) -> None:
        self.replicas: Dict[int, _Replica] = {}
        self.desired: int = 0
        self.in_flight: int = 0
        self.last_request: float = time.time()
        self.next_index: int = 0
        self.rr: int = 0  # round-robin cursor
        self.ready_event = asyncio.Event()
        self.failure_count = 0
        self.spec_fingerprint: Optional[str] = None
        # Fingerprint of the COMPONENT spec the current replicas were
        # spawned from; a change means a new revision -> replace replicas.
        self.comp_fingerprint: Optional[str] = None
        # Deterministic canary split cursor (activator: seq%100 < pct).
        self.canary_seq: int = 0
        # Promoted canary replicas keep their original spawn job_key;
        # exit lookups resolve through these aliases.
        self.adopted_keys: set = set()
        # Multi-model placement (ModelMesh analog): model name -> the
        # replica index currently holding it, plus the spec fingerprint
        # each placed model was loaded from (spec changes force reload).
        self.model_locations: Dict[str, int] = {}
        self.model_spec_fps: Dict[str, str] = {}
        # Consecutive failed placement rounds (drives retry backoff).
        self.placement_failures: int = 0

    def ready_replicas(self) -> List[_Replica]:
        return [r for r in self.replicas.values() if r.ready]


class ISVCController:
    CRASH_LOOP_LIMIT = 5
    # Respawn backoff after a replica exit: the FIRST respawn is
    # immediate (recovery time is the fleet's headline number), repeats
    # back off exponentially so a crash-looping binary can't peg the
    # reconcile loop before CRASH_LOOP_LIMIT ends it.
    RESPAWN_BACKOFF_S = 0.5
    RESPAWN_BACKOFF_MAX_S = 8.0

    def __init__(
        self,
        store,
        launcher: BaseLauncher,
        log_dir: Optional[str] = None,
        state_dir: Optional[str] = None,
        probe_interval: float = 0.25,
        autoscale_interval: float = 2.0,
        gang=None,
        on_capacity_released=None,
    ) -> None:
        self.store = store
        self.launcher = launcher
        self.log_dir = log_dir
        # Shared chip-capacity model (controller/gang.py): serving
        # replicas with resources.tpu > 0 reserve chips through it, so
        # serving and training contend honestly for the same pool. None
        # = unlimited (unit tests without a control plane).
        self.gang = gang
        # Called after a chip-holding replica is released, so the
        # training reconciler can re-try its pending gangs.
        self.on_capacity_released = on_capacity_released
        self.state_dir = state_dir or "."
        self.probe_interval = probe_interval
        self.autoscale_interval = autoscale_interval
        # Control-plane ingress URL, injected into transformer replicas so
        # they call the predictor through the activator (the server sets
        # the real host:port at startup).
        self.base_url = "http://127.0.0.1:7450"
        self.services: Dict[str, _Service] = {}
        # Monotonic suffix for chip-reservation keys: replica indices
        # restart per service generation (canary sets, promotions), so a
        # bare key would collide with a still-held reservation of an
        # adopted replica and corrupt chip accounting.
        self._res_seq = itertools.count()
        self._queue: asyncio.Queue = asyncio.Queue()
        self._queued: set = set()
        self._stopped = asyncio.Event()
        self._http: Optional[aiohttp.ClientSession] = None
        self._probe_tasks: Dict[str, asyncio.Task] = {}
        # Multi-model placement tasks (one live per service) + services
        # that asked for another round while one was running.
        self._placement_tasks: Dict[str, asyncio.Task] = {}
        self._placement_pending: set = set()
        # Called with (key, replica) when a replica turns ready -- the
        # activator registers its prefix-cache re-warm here so a
        # respawned replica doesn't start every prefix cold.
        self.rewarm_hooks: List = []

    # -- loop -------------------------------------------------------------

    async def run(self) -> None:
        self._http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=600)
        )
        watch_q = self.store.watch(KIND)
        tm_q = self.store.watch(TRAINED_MODEL_KIND)
        for obj in self.store.list(KIND):
            self._enqueue(obj["metadata"]["namespace"], obj["metadata"]["name"])
        watcher = asyncio.create_task(self._pump_watch(watch_q))
        tm_watcher = asyncio.create_task(self._pump_tm_watch(tm_q))
        scaler = asyncio.create_task(self._autoscale_loop())
        try:
            while not self._stopped.is_set():
                get = asyncio.create_task(self._queue.get())
                stop = asyncio.create_task(self._stopped.wait())
                done, pending = await asyncio.wait(
                    {get, stop}, return_when=asyncio.FIRST_COMPLETED
                )
                for t in pending:
                    t.cancel()
                if get in done:
                    key = get.result()
                    self._queued.discard(key)
                    try:
                        await self._reconcile(*key.split("/", 1))
                    except Exception:
                        logger.exception("reconcile %s failed", key)
        finally:
            watcher.cancel()
            tm_watcher.cancel()
            scaler.cancel()
            for t in self._placement_tasks.values():
                t.cancel()
            self.store.unwatch(watch_q)
            self.store.unwatch(tm_q)
            for t in self._probe_tasks.values():
                t.cancel()
            for key in list(self.services):
                await self._scale_to(key, 0)
            await self._http.close()

    async def stop(self) -> None:
        self._stopped.set()

    async def _pump_watch(self, q: asyncio.Queue) -> None:
        while True:
            ev = await q.get()
            self._enqueue(ev.namespace, ev.name)

    async def _pump_tm_watch(self, q: asyncio.Queue) -> None:
        """A TrainedModel change re-reconciles the InferenceService whose
        replica pool serves it (DELETED events carry the last object
        snapshot, so the target is always readable). A RETARGETED model
        also re-reconciles its previous pool so the stray copy unloads."""
        last_target: Dict[str, str] = {}
        while True:
            ev = await q.get()
            tm_key = f"{ev.namespace}/{ev.name}"
            target = (ev.obj or {}).get("spec", {}).get("inference_service")
            prev = last_target.get(tm_key)
            if str(getattr(ev, "type", "")).endswith("DELETED"):
                last_target.pop(tm_key, None)
            elif target:
                last_target[tm_key] = target
            if target:
                self._enqueue(ev.namespace, target)
            if prev and prev != target:
                self._enqueue(ev.namespace, prev)

    def _enqueue(self, ns: str, name: str) -> None:
        key = f"{ns}/{name}"
        if key not in self._queued:
            self._queued.add(key)
            self._queue.put_nowait(key)

    # -- reconcile --------------------------------------------------------

    async def _reconcile(self, ns: str, name: str) -> None:
        key = f"{ns}/{name}"
        tkey = key + TRANSFORMER_SUFFIX
        ekey = key + EXPLAINER_SUFFIX
        ckey = key + CANARY_SUFFIX
        raw = self.store.get(KIND, name, ns)
        if raw is None:
            # Deleted: tear down replicas (all component sets); any
            # models placed on them are no longer served. An in-flight
            # placement round must die with the service, or it would
            # re-mark TrainedModels Loaded after this teardown.
            t = self._placement_tasks.pop(key, None)
            if t is not None:
                t.cancel()
            self._placement_pending.discard(key)
            for k in (key, tkey, ekey, ckey):
                svc = self.services.get(k)
                if svc is None:
                    continue
                for mname in list(svc.model_locations):
                    svc.model_locations.pop(mname, None)
                    self._write_tm_status(
                        ns, mname, loaded=False, replica_index=None,
                        url=None,
                    )
                await self._scale_to(k, 0)
                self.services.pop(k, None)
            return
        try:
            isvc = InferenceService.from_dict(raw)
            validate_isvc(isvc)
        except (ServingValidationError, ValueError) as e:
            self._write_failed(ns, name, "InvalidSpec", str(e))
            return

        # Revision/canary resolution (reference canaryTrafficPercent):
        # the promoted predictor spec lives in status.stable_predictor.
        # An applied spec that differs from it with pct<100 runs as a
        # separate canary set; pct>=100 promotes it; re-applying the
        # stable spec rolls the canary back.
        pdump, stable, pct, canarying = _rollout_state(isvc)
        if not canarying:
            if ckey in self.services:
                if stable is not None and stable != pdump:
                    await self._promote_canary(key)  # pct>=100: promote
                else:
                    # Rolled back to the stable spec: discard the canary,
                    # draining its in-flight requests (it was carrying
                    # pct% of traffic a moment ago).
                    await self._drain_set(ckey)
            if stable != pdump:
                isvc.status.stable_predictor = pdump  # persist promotion

        fingerprint = json.dumps(
            isvc.spec.model_dump(mode="json"), sort_keys=True
        )
        if isvc.spec.transformer is None and tkey in self.services:
            # Transformer removed from the spec: tear its replicas down.
            await self._scale_to(tkey, 0)
            self.services.pop(tkey, None)
        if isvc.spec.explainer is None and ekey in self.services:
            await self._scale_to(ekey, 0)
            self.services.pop(ekey, None)
        if canarying:
            stable_comp = ComponentSpec.model_validate(stable)
            components = [(key, stable_comp, "predictor"),
                          (ckey, isvc.spec.predictor, "canary")]
        else:
            components = [(key, isvc.spec.predictor, "predictor")]
        if isvc.spec.transformer is not None:
            components.append((tkey, isvc.spec.transformer, "transformer"))
        if isvc.spec.explainer is not None:
            components.append((ekey, isvc.spec.explainer, "explainer"))
        crash_looped = False
        for skey, comp, label in components:
            svc = self.services.setdefault(skey, _Service())
            # A changed spec resets the crash-loop counter so a corrected
            # re-apply recovers without delete+recreate (generation can't
            # be the key: status writes bump it too).
            if svc.spec_fingerprint != fingerprint:
                svc.spec_fingerprint = fingerprint
                svc.failure_count = 0
            if svc.failure_count >= self.CRASH_LOOP_LIMIT:
                # Crash-looping: stay down until the spec changes. A
                # crash-looping CANARY only pauses itself (stable set
                # keeps serving), and a crash-looping NEW REVISION
                # mid-rollout only retires its own cohort — the retiring
                # old-revision replicas keep serving (that is the whole
                # point of create-before-destroy). Only a plain crash
                # loop with no healthy cohort takes the service down and
                # suppresses the status write (it must not clobber the
                # Failed condition on_worker_exit recorded).
                has_old = any(
                    r.comp_fp != svc.comp_fingerprint
                    for r in svc.replicas.values()
                )
                if has_old:
                    for i, r in list(svc.replicas.items()):
                        if r.comp_fp == svc.comp_fingerprint:
                            await self._retire_replica(
                                skey, svc, i, drain=False
                            )
                else:
                    await self._scale_to(skey, 0)
                    if label != "canary":
                        crash_looped = True
                continue
            if svc.desired == 0 and not svc.replicas:
                # First reconcile (or post scale-to-zero restart): start
                # at min_replicas; the activator bumps desired on traffic.
                svc.desired = max(svc.desired, comp.min_replicas)
            if label == "canary":
                # A canary set always runs at least one replica so the
                # split has something to route to (its size ramps with
                # the percent against the stable set's desired count).
                stable_n = self.services[key].desired
                svc.desired = max(1, math.ceil(stable_n * pct / 100))
            svc.desired = max(min(svc.desired, comp.max_replicas),
                             comp.min_replicas if label != "canary" else 1)
            try:
                await self._converge(skey, isvc, comp, svc)
            except Exception as e:  # noqa: BLE001 - spawn errors -> Failed
                logger.exception("isvc %s: converge failed", skey)
                self._write_failed(ns, name, "SpawnError", str(e))
                return
        if isvc.spec.predictor.multi_model is not None:
            # Placement runs as a background task: a slow model load
            # (up to 120s per call) must not head-of-line-block the
            # shared reconcile loop for every other service.
            self._spawn_placement(ns, name, isvc.spec.predictor)
        if not crash_looped:
            self._write_status(
                isvc, self.services[key], self.services.get(tkey),
                esvc=self.services.get(ekey),
                csvc=self.services.get(ckey) if canarying else None,
                canary_pct=pct if canarying else None,
            )

    def _write_failed(self, ns: str, name: str, reason: str,
                      message: str) -> None:
        """Set a Failed condition; no-op when already set identically (a
        status write fires a watch event that re-reconciles, so an
        unconditional write here would be a self-triggering hot loop)."""

        raw = self.store.get(KIND, name, ns)
        if raw is None:
            return
        conds = raw.get("status", {}).get("conditions", [])
        for c in conds:
            if (c.get("type") == "Failed" and c.get("status")
                    and c.get("reason") == reason
                    and c.get("message") == message):
                return
        raw.setdefault("status", {})["conditions"] = [{
            "type": "Failed", "status": True, "reason": reason,
            "message": message, "last_transition": time.time(),
        }]
        self.store.put(KIND, raw)

    async def _reconcile_models(self, ns: str, name: str,
                                comp: ComponentSpec, svc: _Service) -> None:
        """ModelMesh-style placement (S7): converge the set of
        TrainedModels targeting this multi-model ISVC onto its ready
        replicas. Level-triggered against what each replica ACTUALLY has
        loaded (its /healthz model list) — controller-side bookkeeping
        alone would drift the first time a replica's LRU evicts.
        Placement is budget-aware rendezvous hashing: each model's
        replica preference order is stable, but a replica at its
        max_models_per_replica budget is skipped, so placement never
        oversubscribes a replica into eviction thrash."""
        import zlib

        budget = (comp.multi_model.max_models_per_replica
                  if comp.multi_model else 1)
        tms = []
        for raw in self.store.list(TRAINED_MODEL_KIND, ns):
            try:
                tm = TrainedModel.from_dict(raw)
                validate_trained_model(tm)
            except (ValueError, ServingValidationError):
                continue
            if tm.spec.inference_service == name:
                tms.append(tm)
        tms.sort(key=lambda t: t.metadata.name)
        # A model of a different format would be constructed by the
        # POOL's runtime and silently return wrong results — reject.
        pool_format = comp.model.format if comp.model else None
        mismatched = [
            tm for tm in tms if tm.spec.model.format != pool_format
        ]
        for tm in mismatched:
            logger.warning(
                "TrainedModel %s/%s format %s != pool runtime %s; "
                "not placing", ns, tm.metadata.name,
                tm.spec.model.format, pool_format,
            )
            self._write_tm_status(
                ns, tm.metadata.name, loaded=False,
                replica_index=None, url=None,
            )
        tms = [tm for tm in tms if tm.spec.model.format == pool_format]
        ready = sorted(i for i, r in svc.replicas.items() if r.ready)
        if not ready:
            # Nothing serves anymore (e.g. scaled to zero): statuses
            # must say so — a stale loaded=true with a dead url misleads
            # anything polling TrainedModels.
            for mname in list(svc.model_locations):
                self._write_tm_status(
                    ns, mname, loaded=False, replica_index=None, url=None
                )
            svc.model_locations.clear()
            return  # probes enqueue us again when a replica readies

        # Ground truth: what each ready replica holds right now
        # (concurrent probes: one wedged replica must not stall the
        # whole reconcile loop serially). A replica whose probe failed
        # is left out of this placement round entirely.
        probes = await asyncio.gather(
            *(self._replica_models(svc, i) for i in ready)
        )
        actual: Dict[int, set] = {
            i: models for i, models in zip(ready, probes)
            if models is not None
        }
        # Spec-change unloads may only be trusted as complete when every
        # replica answered — a stale copy could hide on an unprobed one.
        full_coverage = len(actual) == len(ready)
        ready = sorted(actual)
        if not ready:
            # Every probe failed this round: retry, or placement stalls
            # until some unrelated event arrives.
            asyncio.get_running_loop().call_later(
                2.0, self._enqueue, ns, name
            )
            return

        # A model whose SPEC changed must reload even though its name is
        # already on the target replica (the copy there was built from
        # the old spec). The recorded fingerprint only advances once the
        # stale copies are really gone — otherwise a failed unload would
        # leave the old revision serving forever while marked current.
        spec_change_failed = False
        for tm in tms:
            mname = tm.metadata.name
            fp = json.dumps(
                tm.spec.model.model_dump(mode="json"), sort_keys=True
            )
            if svc.model_spec_fps.get(mname) not in (None, fp):
                cleared = full_coverage
                for i in ready:
                    if mname in actual[i]:
                        if await self._model_call(svc, i, mname, "unload"):
                            actual[i].discard(mname)
                        else:
                            cleared = False
                if not cleared:
                    spec_change_failed = True
                    continue  # keep old fp; retried next round
            svc.model_spec_fps[mname] = fp
        for stale in set(svc.model_spec_fps) - {
            tm.metadata.name for tm in tms
        }:
            svc.model_spec_fps.pop(stale, None)

        # Budget-aware rendezvous placement.
        counts = {i: 0 for i in ready}
        placements: Dict[str, int] = {}
        for tm in tms:
            mname = tm.metadata.name
            order = sorted(
                ready,
                key=lambda i: zlib.crc32(f"{mname}@{i}".encode()),
            )
            target = next(
                (i for i in order if counts[i] < budget), None
            )
            if target is None:
                self._write_tm_status(
                    ns, mname, loaded=False, replica_index=None,
                    url=None,
                )
                continue
            counts[target] += 1
            placements[mname] = target

        # Unload strays (deleted models, or copies on the wrong replica)
        # BEFORE loading, so LRU budgets free up first.
        stray_calls = [
            self._model_call(svc, i, mname, "unload")
            for i in ready
            for mname in sorted(actual[i])
            if placements.get(mname) != i
        ]
        stray_failed = False
        if stray_calls:
            stray_results = await asyncio.gather(*stray_calls)
            # A failed stray unload keeps holding an LRU slot (and its
            # model memory) — it must be retried like a failed load.
            stray_failed = not all(stray_results)

        # Load what's missing (concurrently — loads mostly land on
        # different replicas); record truth-backed locations.
        async def place(tm) -> tuple[str, Optional[int], bool]:
            mname = tm.metadata.name
            target = placements.get(mname)
            if target is None:
                return mname, None, False
            ok = True
            if mname not in actual[target]:
                ok = await self._model_call(
                    svc, target, mname, "load",
                    body={
                        "storage_uri": tm.spec.model.storage_uri,
                        "options": tm.spec.model.options,
                    },
                )
            return mname, target, bool(ok)

        results = await asyncio.gather(
            *(place(tm) for tm in tms if tm.metadata.name in placements)
        )
        locations: Dict[str, int] = {}
        any_failed = False
        for mname, target, ok in results:
            if ok and target is not None:
                locations[mname] = target
            else:
                any_failed = True
            self._write_tm_status(
                ns, mname, loaded=ok,
                replica_index=target if ok else None,
                url=(f"/serving/{ns}/{name}/v2/models/{mname}/infer"
                     if ok else None),
            )
        svc.model_locations = locations
        if spec_change_failed or stray_failed:
            any_failed = True
        if any_failed:
            # A transiently failed load writes an identical LoadFailed
            # status next round (no-op, no watch event) — without an
            # explicit requeue nothing would ever retry it. Exponential
            # backoff (2s..60s) so a permanently bad model does not
            # hammer the replicas' serialized load lock forever.
            svc.placement_failures += 1
            delay = min(2.0 * (2 ** min(svc.placement_failures - 1, 5)),
                        60.0)
            asyncio.get_running_loop().call_later(
                delay, self._enqueue, ns, name
            )
        else:
            svc.placement_failures = 0

    async def _replica_models(self, svc: _Service,
                              index: int) -> Optional[set]:
        """Model names loaded on a replica, or None when the probe fails
        — a failed probe must NOT read as 'holds nothing', or the
        controller would evict-and-rebuild healthy models on a replica
        that was merely slow for one probe."""
        rep = svc.replicas.get(index)
        if rep is None:
            return None
        try:
            async with self._http.get(
                f"http://127.0.0.1:{rep.port}/healthz",
                timeout=aiohttp.ClientTimeout(total=5),
            ) as resp:
                body = await resp.json()
                return set(body.get("models", []))
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return None

    async def _model_call(self, svc: _Service, index: int, model: str,
                          verb: str, body: Optional[dict] = None) -> bool:
        rep = svc.replicas.get(index)
        if rep is None:
            return False
        try:
            async with self._http.post(
                f"http://127.0.0.1:{rep.port}/v2/repository/models/"
                f"{model}/{verb}",
                json=body,
                timeout=aiohttp.ClientTimeout(total=120),
            ) as resp:
                if resp.status != 200:
                    logger.warning(
                        "model %s %s on replica %d: HTTP %d %s",
                        model, verb, index, resp.status,
                        (await resp.text())[:200],
                    )
                    return False
                return True
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning("model %s %s on replica %d: %s",
                           model, verb, index, e)
            return False

    def _write_tm_status(self, ns: str, name: str, *, loaded: bool,
                         replica_index: Optional[int],
                         url: Optional[str]) -> None:
        raw = self.store.get(TRAINED_MODEL_KIND, name, ns)
        if raw is None:
            return
        new_status = {
            "loaded": loaded,
            "conditions": [{
                "type": "Ready" if loaded else "Unready",
                "status": True,
                "reason": "Loaded" if loaded else "LoadFailed",
                "message": "",
                "last_transition": time.time(),
            }],
        }
        if replica_index is not None:
            new_status["replica_index"] = replica_index
        if url is not None:
            new_status["url"] = url
        old = dict(raw.get("status", {}))
        cmp_old = {k: v for k, v in old.items() if k != "conditions"}
        cmp_new = {k: v for k, v in new_status.items() if k != "conditions"}
        old_ready = any(
            c.get("type") == "Ready" and c.get("status")
            for c in old.get("conditions", [])
        )
        if (cmp_old == cmp_new and old_ready == loaded
                and old.get("conditions")):
            # No-op guard (a status write re-triggers our own watch) —
            # but a condition-less fresh object must get its FIRST
            # condition even when the comparable fields match.
            return
        raw = dict(raw)
        raw["status"] = new_status
        self.store.put(TRAINED_MODEL_KIND, raw)

    def _release_chips(self, rep: Optional[_Replica]) -> None:
        if rep is None or rep.res_key is None or self.gang is None:
            return
        self.gang.release(rep.res_key)
        rep.res_key = None
        if self.on_capacity_released is not None:
            self.on_capacity_released()

    def _spawn_placement(self, ns: str, name: str,
                         comp: ComponentSpec) -> None:
        """One placement task per service at a time; a reconcile that
        arrives mid-placement marks it pending and the task re-enqueues
        the service when done (so no placement round is lost)."""
        key = f"{ns}/{name}"
        running = self._placement_tasks.get(key)
        if running is not None and not running.done():
            self._placement_pending.add(key)
            return
        svc = self.services.get(key)
        if svc is None:
            return

        async def run() -> None:
            try:
                await self._reconcile_models(ns, name, comp, svc)
            except Exception:  # noqa: BLE001
                logger.exception("model placement for %s failed", key)
            finally:
                if key in self._placement_pending:
                    self._placement_pending.discard(key)
                    self._enqueue(ns, name)

        self._placement_tasks[key] = asyncio.create_task(run())

    async def _retire_replica(self, key: str, svc: _Service, index: int,
                              drain: bool = True) -> None:
        """THE one way a replica leaves a set: popped from the service,
        probe task cancelled, then drained (graceful) or killed (hard);
        its chip reservation returns to the shared pool once dead."""
        rep = svc.replicas.pop(index, None)
        t = self._probe_tasks.pop(f"{key}#{index}", None)
        if t:
            t.cancel()
        if rep is None:
            return
        if drain:
            await self._drain_and_kill(key, rep)
        else:
            rep.ready = False
            await self.launcher.kill(rep.ref)
            self._release_chips(rep)

    async def _drain_replicas(self, key: str, svc: _Service) -> None:
        """Drain every replica of a set: out of rotation immediately,
        killed once in-flight requests finish. Shared by rollback
        discard and full-set teardown."""
        for i in list(svc.replicas):
            await self._retire_replica(key, svc, i)
        svc.ready_event.clear()

    async def _drain_set(self, key: str) -> None:
        """Remove a whole replica set gracefully: out of rotation now,
        killed only after in-flight requests finish."""
        svc = self.services.pop(key, None)
        if svc is not None:
            await self._drain_replicas(key, svc)

    async def _promote_canary(self, key: str) -> None:
        """Canary promoted to 100%: its replicas (already running the new
        revision, already warm) BECOME the primary set. The old stable
        replicas join it as a RETIRING cohort (their comp_fp differs) so
        _converge drains them one-for-one as new-revision replicas come
        up — promotion at a small canary percent must not collapse
        capacity onto the few canary replicas."""
        ckey = key + CANARY_SUFFIX
        csvc = self.services.pop(ckey, None)
        if csvc is None:
            return
        old = self.services.get(key)
        csvc.adopted_keys.add(ckey)
        if old is not None:
            csvc.desired = max(csvc.desired, old.desired)
            csvc.adopted_keys |= old.adopted_keys
            for i, rep in list(old.replicas.items()):
                t = self._probe_tasks.pop(f"{key}#{i}", None)
                if t:
                    t.cancel()
                new_i = csvc.next_index
                csvc.next_index += 1
                rep.index = new_i
                csvc.replicas[new_i] = rep
            old.replicas.clear()
        self.services[key] = csvc
        # Re-home probe tasks: pending canary replicas must keep probing
        # under the primary key (their old-key probes would give up).
        for i, rep in list(csvc.replicas.items()):
            t = self._probe_tasks.pop(f"{ckey}#{i}", None)
            if t:
                t.cancel()
            if not rep.ready:
                self._probe_tasks[f"{key}#{i}"] = asyncio.create_task(
                    self._probe_ready(key, i)
                )
        logger.info("isvc %s: canary promoted (%d replicas adopted)",
                    key, len(csvc.replicas))

    async def _converge(self, key: str, isvc: InferenceService,
                        comp: ComponentSpec, svc: _Service) -> None:
        # Revision change: the running replicas were spawned from a
        # different component spec. Create-before-destroy: old replicas
        # KEEP SERVING while new-revision ones spawn; they drain only
        # once a new replica is ready — an ordinary spec update must not
        # open a cold-start window (the 8B jax runtime takes minutes to
        # load; 0 ready replicas would 503 the service meanwhile).
        comp_fp = json.dumps(comp.model_dump(mode="json"), sort_keys=True)
        if (svc.comp_fingerprint is not None
                and svc.comp_fingerprint != comp_fp and svc.replicas):
            logger.info(
                "isvc %s: revision change, rolling %d replicas "
                "(create-before-destroy)", key, len(svc.replicas),
            )
        svc.comp_fingerprint = comp_fp
        current = {
            i: r for i, r in svc.replicas.items() if r.comp_fp == comp_fp
        }
        retiring = {
            i: r for i, r in svc.replicas.items() if r.comp_fp != comp_fp
        }
        # Scale up the current revision. Chip-requesting replicas go
        # through the shared capacity model first: a refused reservation
        # stops the scale-up (the autoscale tick retries as capacity
        # frees), so serving queues behind training gangs honestly.
        chips = comp.resources.tpu
        while len(current) < svc.desired:
            index = svc.next_index
            res_key = None
            if self.gang is not None and chips > 0:
                res_key = f"{key}#r{index}.{next(self._res_seq)}"
                if not self.gang.try_reserve(res_key, chips):
                    # Retire an old replica ONLY when the refusal is a
                    # genuine capacity shortage with nobody queued ahead:
                    # on a pending-gang barrier the freed chips would go
                    # to the gang, not the rollout — draining the healthy
                    # old revision would be a self-inflicted outage.
                    starved = self.gang.free_chips < chips
                    if retiring and starved and not self.gang.pending():
                        # Our own old revision holds the chips the new
                        # one needs: fall back to destroy-before-create
                        # for one replica (a capacity-constrained
                        # rollout cannot be gapless); its drained chips
                        # admit the next attempt.
                        idx = sorted(retiring)[0]
                        retiring.pop(idx)
                        await self._retire_replica(key, svc, idx)
                        logger.info(
                            "isvc %s: retiring old-revision replica %d "
                            "to free chips for the rollout", key, idx,
                        )
                    else:
                        logger.info(
                            "isvc %s: waiting for %d chips (free: %d)",
                            key, chips, self.gang.free_chips,
                        )
                    break
            svc.next_index += 1
            port = allocate_port()
            # Bundled runtimes serve OIP gRPC alongside HTTP; custom
            # entrypoints aren't assumed to accept the flag.
            grpc_port = allocate_port() if comp.custom is None else None
            # Disaggregated routing: the first routing.prefill_replicas
            # live replicas of the revision hold the prefill role; the
            # count re-fills as replicas churn.
            role = "mixed"
            if (comp.routing is not None
                    and comp.routing.prefill_replicas > 0):
                n_pre = sum(
                    1 for r in current.values() if r.role == "prefill"
                )
                role = ("prefill"
                        if n_pre < comp.routing.prefill_replicas
                        else "decode")
            req = self._spawn_request(isvc, comp, index, port, key,
                                      grpc_port=grpc_port, role=role)
            try:
                ref = await self.launcher.spawn(req)
            except Exception:
                if res_key is not None:
                    self.gang.release(res_key)
                raise
            rep = _Replica(index, port, ref, comp_fp=comp_fp,
                           grpc_port=grpc_port, role=role)
            rep.res_key = res_key
            svc.replicas[index] = rep
            current[index] = rep
            probe_key = f"{key}#{index}"
            self._probe_tasks[probe_key] = asyncio.create_task(
                self._probe_ready(key, index)
            )
            logger.info("isvc %s: spawned replica %d on port %d", key, index, port)
        # Old revision drains ONE-FOR-ONE with ready new replicas, so
        # in-rotation capacity never dips below the old level while the
        # new revision is still loading (each readiness probe enqueues a
        # reconcile, which drains the next batch).
        ready_new = sum(1 for r in current.values() if r.ready)
        if retiring and ready_new:
            for index in sorted(retiring)[:ready_new]:
                retiring.pop(index)
                await self._retire_replica(key, svc, index)
        # Scale down within the current revision (highest index first;
        # KServe reaps newest too).
        while len(current) > svc.desired:
            index = max(current)
            current.pop(index)
            await self._retire_replica(key, svc, index)
        if not svc.ready_replicas():
            svc.ready_event.clear()

    async def _drain_and_kill(self, key: str, rep: _Replica,
                              drain_timeout: float = 30.0) -> None:
        """Stop routing to the replica, let in-flight requests finish, then
        kill. The drain runs as a background task so reconcile never blocks
        behind a slow request."""

        rep.ready = False  # out of the activator's rotation immediately

        async def drain():
            deadline = time.monotonic() + drain_timeout
            while rep.in_flight > 0 and time.monotonic() < deadline:
                await asyncio.sleep(0.1)
            await self.launcher.kill(rep.ref)
            self._release_chips(rep)
            logger.info("isvc %s: reaped replica %d (drained)", key, rep.index)

        asyncio.create_task(drain())

    async def _scale_to(self, key: str, n: int) -> None:
        svc = self.services.get(key)
        if svc is None:
            return
        svc.desired = n
        while len(svc.replicas) > n:
            await self._retire_replica(
                key, svc, max(svc.replicas), drain=False
            )
        if not svc.ready_replicas():
            svc.ready_event.clear()

    def _spawn_request(self, isvc: InferenceService, comp: ComponentSpec,
                       index: int, port: int,
                       service_key: Optional[str] = None,
                       grpc_port: Optional[int] = None,
                       role: str = "mixed") -> SpawnRequest:
        ns, name = isvc.metadata.namespace, isvc.metadata.name
        service_key = service_key or f"{ns}/{name}"
        env = {"PORT": str(port)}
        # Trace context rides into serving replicas exactly as it does
        # into training workers (controller/envvars.py).
        env.update(trace.propagation_env())
        if role != "mixed":
            # Surfaced by the replica's /healthz (trace labels + the
            # activator's load poll); behavior lives in the router.
            env["KFTPU_REPLICA_ROLE"] = role
        if service_key.endswith((TRANSFORMER_SUFFIX, EXPLAINER_SUFFIX)):
            # Transformer/explainer processes call the predictor back
            # through the activator (scale-from-zero applies), pinned to
            # the predictor component via header by TransformerModel.
            env["KFTPU_PREDICTOR_URL"] = (
                f"{self.base_url}/serving/{ns}/{name}"
            )
            env["KFTPU_PREDICTOR_MODEL"] = (
                (isvc.spec.predictor.model.name
                 if isvc.spec.predictor.model else None) or name
            )
        if comp.custom is not None:
            entrypoint = comp.custom.entrypoint
            args = list(comp.custom.args)
            env.update(comp.custom.env)
        elif (service_key.endswith(EXPLAINER_SUFFIX)
                and comp.model is None):
            # Bundled default: the model-agnostic feature-ablation
            # explainer (validation guarantees explainer model: is unset).
            entrypoint = "kubeflow_tpu.serving.runtimes.explainer_server"
            args = ["--model-name", name, "--port", str(port),
                    "--options-json", "{}"]
            if grpc_port:
                args += ["--grpc-port", str(grpc_port)]
        else:
            m = comp.model
            if m.format == ModelFormat.custom:
                raise ServingValidationError("custom format needs custom spec")
            entrypoint = RUNTIMES[m.format]
            model_dir = os.path.join(
                self.state_dir, "models", ns, name
            )
            if comp.multi_model is not None:
                # ModelMesh replica: boots empty; the placement loop
                # admits TrainedModels via the V2 repository API.
                args = [
                    "--multi-model",
                    "--max-loaded",
                    str(comp.multi_model.max_models_per_replica),
                    "--port", str(port),
                    "--model-dir", model_dir,
                    "--options-json", json.dumps(m.options),
                ]
            else:
                args = [
                    "--model-name", m.name or name,
                    "--port", str(port),
                    "--model-dir", model_dir,
                    "--options-json", json.dumps(m.options),
                ]
                if m.storage_uri:
                    args += ["--storage-uri", m.storage_uri]
            if grpc_port:
                args += ["--grpc-port", str(grpc_port)]
        if comp.logger is not None:
            # Part of the runtime flag contract (runtimes/common.py);
            # custom entrypoints opting into logger: must accept it too.
            args += ["--logger-json", json.dumps(
                {"sink": comp.logger.sink, "mode": comp.logger.mode}
            )]
        fault = chaos.should("controller.spawn", f"{service_key}#{index}")
        if fault is not None and fault.kind == "spawn_env" and fault.env:
            # Chaos seam: plant env (typically a child KFTPU_CHAOS_PLAN)
            # into exactly the replica the plan names -- how the chaos
            # bench arms an in-replica crash without touching its code.
            env.update(fault.env)
        return SpawnRequest(
            job_key=service_key,
            replica_type="server",
            index=index,
            entrypoint=entrypoint,
            args=tuple(args),
            env=tuple(sorted(env.items())),
        )

    async def _probe_ready(self, key: str, index: int) -> None:
        """Poll the replica's /healthz until it reports ready."""

        while not self._stopped.is_set():
            svc = self.services.get(key)
            if svc is None or index not in svc.replicas:
                return
            rep = svc.replicas[index]
            try:
                async with self._http.get(
                    f"http://127.0.0.1:{rep.port}/healthz",
                    timeout=aiohttp.ClientTimeout(total=2),
                ) as resp:
                    body = await resp.json()
                    if body.get("ready"):
                        rep.ready = True
                        svc.failure_count = 0
                        svc.ready_event.set()
                        self._enqueue(*_key_parts(key))
                        for hook in self.rewarm_hooks:
                            # Fire-and-forget: a failed re-warm only
                            # costs the new replica cold prefixes.
                            asyncio.create_task(hook(key, rep))
                        return
            except Exception as e:  # noqa: BLE001 -- not-ready is normal
                # while the replica boots, but a swallowed probe error
                # also hid real bugs (bad port, garbage healthz JSON);
                # debug-log with replica context so stalls are traceable.
                logger.debug(
                    "readiness probe %s[%d] port %d: %s", key, index,
                    rep.port, e,
                )
            await asyncio.sleep(self.probe_interval)

    async def on_worker_exit(self, ref: WorkerRef, code: int) -> bool:
        """Called by the shared exit dispatcher for server replicas.

        Returns True if the exit belonged to a serving replica."""

        if ref.req.replica_type != "server":
            return False
        # Resolve by launcher generation (globally unique), not by spawn
        # job_key/index: promotion re-keys adopted replicas, and a spawn
        # key like "ns/name#canary" may since have been re-occupied by a
        # NEWER canary set — a key-based lookup would misattribute the
        # exit (or swallow it, leaving a dead replica in rotation).
        svc = key = index = rep = None
        for skey, s in self.services.items():
            for i, r in list(s.replicas.items()):
                if r.ref.generation == ref.generation:
                    svc, key, index, rep = s, skey, i, r
                    break
            if svc is not None:
                break
        if svc is None:
            spawn_key = ref.req.job_key
            known = spawn_key in self.services or any(
                spawn_key in s.adopted_keys for s in self.services.values()
            )
            # Ours-but-already-replaced (stale) vs not a serving exit.
            return known
        svc.replicas.pop(index, None)
        self._probe_tasks.pop(f"{key}#{index}", None)
        self._release_chips(rep)
        if not svc.ready_replicas():
            svc.ready_event.clear()
        svc.failure_count += 1
        logger.warning(
            "isvc %s replica %d exited code=%d (failures=%d)",
            key, index, code, svc.failure_count,
        )
        # Crash-looping guard: stop respawning after repeated failures;
        # the status shows Failed with the failure count.
        if svc.failure_count < self.CRASH_LOOP_LIMIT:
            if svc.failure_count <= 1:
                self._enqueue(*_key_parts(key))
            else:
                delay = min(
                    self.RESPAWN_BACKOFF_S * 2 ** (svc.failure_count - 2),
                    self.RESPAWN_BACKOFF_MAX_S,
                )
                logger.info("isvc %s: respawn of replica %d backed off "
                            "%.1fs", key, index, delay)

                async def _respawn(key=key, delay=delay):
                    await asyncio.sleep(delay)
                    if not self._stopped.is_set():
                        self._enqueue(*_key_parts(key))

                self._probe_tasks[
                    f"respawn#{key}#{ref.generation}"
                ] = asyncio.create_task(_respawn())
        elif svc.failure_count == self.CRASH_LOOP_LIMIT:
            ns, name = _key_parts(key)
            # Canary-ness is decided by the service's CURRENT role, not
            # the spawn key: promoted replicas keep their #canary
            # job_key but ARE the primary set — their crash loop must
            # mark the whole service Failed.
            is_canary = svc is self.services.get(
                f"{ns}/{name}" + CANARY_SUFFIX
            )
            if is_canary:
                # A bad canary must not blackhole the service: the stable
                # set keeps serving (the activator skips a canary with no
                # ready replicas). Record a non-exclusive condition so the
                # operator sees the rollout is stuck.
                self._write_condition(
                    ns, name, "CanaryCrashLoop",
                    f"canary replica exited {svc.failure_count} times "
                    f"(last code {code}); traffic stays on stable",
                )
            elif any(
                r.comp_fp != svc.comp_fingerprint
                for r in svc.replicas.values()
            ):
                # New revision crash-looping mid-rollout while the old
                # revision's retiring replicas still serve: pause the
                # rollout, don't fail (and so don't 503) the service.
                self._write_condition(
                    ns, name, "RolloutCrashLoop",
                    f"new-revision replica exited {svc.failure_count} "
                    f"times (last code {code}); previous revision keeps "
                    "serving",
                )
            else:
                self._write_failed(
                    ns, name, "CrashLoop",
                    f"replica exited {svc.failure_count} times "
                    f"(last code {code})",
                )
        return True

    def _write_condition(self, ns: str, name: str, ctype: str,
                         message: str) -> None:
        """Set a non-exclusive informational condition (does not touch
        Ready/Unready/Failed) via the shared condition machinery. No-op
        when identical (a status write re-triggers reconcile via our own
        watch)."""
        from kubeflow_tpu.api import conditions as cond

        raw = self.store.get(KIND, name, ns)
        if raw is None:
            return
        conds = raw.setdefault("status", {}).setdefault("conditions", [])
        for c in conds:
            if (c.get("type") == ctype and c.get("status")
                    and c.get("message") == message):
                return
        cond.set_condition(conds, ctype, (), reason=ctype, message=message)
        self.store.put(KIND, raw)

    # -- autoscaler -------------------------------------------------------

    async def _autoscale_loop(self) -> None:
        while not self._stopped.is_set():
            await asyncio.sleep(self.autoscale_interval)
            for key, svc in list(self.services.items()):
                if key.endswith(CANARY_SUFFIX):
                    # Canary sets are sized by the rollout percent in
                    # reconcile, not by traffic.
                    continue
                ns, name = _key_parts(key)
                raw = self.store.get(KIND, name, ns)
                if raw is None:
                    continue
                try:
                    parsed = InferenceService.from_dict(raw)
                except ValueError:
                    continue
                if key.endswith(TRANSFORMER_SUFFIX):
                    comp = parsed.spec.transformer
                elif key.endswith(EXPLAINER_SUFFIX):
                    comp = parsed.spec.explainer
                else:
                    # Mid-rollout the stable set RUNS the stable
                    # revision; scale it by that spec's bounds, not the
                    # unpromoted canary spec's.
                    comp = _governing_predictor(parsed)
                if comp is None:
                    continue
                if svc.desired > len(svc.replicas) and not any(
                    c.get("type") == "Failed" and c.get("status")
                    for c in raw.get("status", {}).get("conditions", [])
                ):
                    # Chip-starved (scale-up stopped at a refused
                    # reservation): retry — training may have released.
                    # A Failed service (e.g. can-never-fit chip request)
                    # stays down until its spec changes.
                    self._enqueue(ns, name)
                want = math.ceil(svc.in_flight / comp.target_concurrency)
                want = min(max(want, comp.min_replicas), comp.max_replicas)
                idle = time.time() - svc.last_request
                if (comp.min_replicas == 0 and svc.in_flight == 0
                        and idle > comp.scale_to_zero_grace_seconds):
                    want = 0
                elif want == 0 and (svc.in_flight > 0 or svc.desired > 0):
                    want = max(want, 1 if svc.in_flight else svc.desired)
                if want != svc.desired:
                    logger.info(
                        "isvc %s: autoscale %d -> %d (in_flight=%d idle=%.0fs)",
                        key, svc.desired, want, svc.in_flight, idle,
                    )
                    svc.desired = want
                    self._enqueue(ns, name)

    # -- status -----------------------------------------------------------

    def _write_status(self, isvc: InferenceService, svc: _Service,
                      tsvc: Optional[_Service] = None,
                      esvc: Optional[_Service] = None,
                      csvc: Optional[_Service] = None,
                      canary_pct: Optional[int] = None) -> None:
        raw = self.store.get(KIND, isvc.metadata.name, isvc.metadata.namespace)
        if raw is None:
            return
        status = isvc.status
        if csvc is not None:
            status.canary = ComponentStatus(
                desired_replicas=csvc.desired,
                ready_replicas=len(csvc.ready_replicas()),
                replicas=[r.info() for r in csvc.replicas.values()],
            )
            status.canary_percent = canary_pct
        else:
            status.canary = None
            status.canary_percent = None
        if csvc is None or csvc.ready_replicas():
            # Rollout resolved (promoted/rolled back) or the canary is
            # healthy again: the stuck-rollout marker must not outlive
            # the condition it reports.
            status.conditions = [
                c for c in status.conditions
                if c.get("type") != "CanaryCrashLoop"
            ]
        if svc.failure_count < self.CRASH_LOOP_LIMIT:
            # Spec change reset the counter (or the new revision came
            # good): the paused-rollout marker is stale.
            status.conditions = [
                c for c in status.conditions
                if c.get("type") != "RolloutCrashLoop"
            ]
        ready = svc.ready_replicas()
        status.predictor.desired_replicas = svc.desired
        status.predictor.ready_replicas = len(ready)
        status.predictor.replicas = [r.info() for r in svc.replicas.values()]
        if tsvc is not None:
            if status.transformer is None:
                status.transformer = ComponentStatus()
            status.transformer.desired_replicas = tsvc.desired
            status.transformer.ready_replicas = len(tsvc.ready_replicas())
            status.transformer.replicas = [
                r.info() for r in tsvc.replicas.values()
            ]
        else:
            # Transformer removed from the spec: clear its stale status
            # (replicas/PIDs that no longer exist) rather than carry it.
            status.transformer = None
        if esvc is not None:
            if status.explainer is None:
                status.explainer = ComponentStatus()
            status.explainer.desired_replicas = esvc.desired
            status.explainer.ready_replicas = len(esvc.ready_replicas())
            status.explainer.replicas = [
                r.info() for r in esvc.replicas.values()
            ]
        else:
            status.explainer = None
        status.in_flight = svc.in_flight
        status.last_request_time = svc.last_request
        status.url = (
            f"/serving/{isvc.metadata.namespace}/{isvc.metadata.name}"
        )
        set_condition(status, "Created", "Reconciled")
        # Ready = every present component has a ready replica or is
        # legitimately scaled to zero (the activator wakes it).
        t_ready = (
            tsvc is None or tsvc.ready_replicas() or tsvc.desired == 0
        )
        e_ready = (
            esvc is None or esvc.ready_replicas() or esvc.desired == 0
        )
        if ready and t_ready and e_ready:
            set_condition(status, "Ready", "MinimumReplicasAvailable",
                          f"{len(ready)}/{svc.desired} replicas ready")
        elif svc.desired == 0:
            set_condition(status, "Unready", "ScaledToZero",
                          "scaled to zero; activator buffers requests")
        else:
            stuck = []
            if not ready:
                stuck.append(f"predictor 0/{svc.desired}")
            if tsvc is not None and not t_ready:
                stuck.append(f"transformer 0/{tsvc.desired}")
            if esvc is not None and not e_ready:
                stuck.append(f"explainer 0/{esvc.desired}")
            set_condition(status, "Unready", "WaitingForReplicas",
                          f"waiting for replicas: {', '.join(stuck)}")
        new = dict(raw)
        new["status"] = status.model_dump(mode="json", exclude_none=True)
        if new["status"] != raw.get("status"):
            self.store.put(KIND, new)


class Activator:
    """Routing + scale-from-zero buffer, mounted on the control-plane app.

    ``/serving/{ns}/{name}/{tail}`` proxies to a ready predictor replica
    (round-robin). With zero ready replicas it bumps desired, waits on the
    service's ready_event (holding the request, as Knative's activator
    does), then replays.
    """

    # In-flight retry budget: a request that dies with its replica is
    # re-dispatched onto a survivor (inference is idempotent: no state
    # outlives the exchange). 2 = the original attempt plus two more.
    MAX_RETRIES = 2
    # Prefixes re-warmed into a respawned replica (newest first).
    REWARM_PREFIXES = 8

    def __init__(self, controller: ISVCController,
                 cold_start_timeout: float = 180.0) -> None:
        self.controller = controller
        self.cold_start_timeout = cold_start_timeout
        # Prefix-affinity data plane (docs/FLEET.md): one Router per
        # service key, engaged only when the predictor spec carries a
        # ``routing`` block. Load-poll tasks live in the controller's
        # _probe_tasks map so the run loop's shutdown path cancels them.
        self._routers: Dict[str, Router] = {}
        self._router_fps: Dict[str, str] = {}
        # (model, prompt) of recent routed requests, per service key --
        # the donor material for re-warming a respawned replica's
        # prefix cache over the PR 7 KV-handoff endpoints.
        self._recent_texts: Dict[str, "collections.OrderedDict"] = {}
        # Replicas mid-warm-up: ready (probe passed) but still importing
        # migrated prefix entries. Excluded from the affinity ring until
        # the transfer lands, so the first requests a newcomer sees are
        # hits, not a cold-cache TTFT spike. RR fallback ignores this
        # set -- with every replica warming, availability wins.
        self._warming: Dict[str, set] = {}
        controller.rewarm_hooks.append(self._rewarm_replica)

    @staticmethod
    async def _wants_stream(req: web.Request) -> bool:
        """OpenAI routes signal streaming in the body ("stream": true).
        req.json() caches the payload, so the buffered path can still
        read it."""
        try:
            body = await req.json()
        except Exception:  # noqa: BLE001 - non-JSON: buffered path 400s
            return False
        return bool(isinstance(body, dict) and body.get("stream"))

    async def handle(self, req: web.Request) -> web.StreamResponse:
        tail = req.match_info.get("tail", "")
        if req.method == "POST" and (
            tail.endswith("generate_stream")
            or (tail.startswith("openai/") and await self._wants_stream(req))
        ):
            # SSE token streaming: chunks must pass through as they
            # arrive -- buffering the body would turn TTFT into
            # time-to-last-token for every streaming client.
            return await self._handle_stream(req, tail)
        status, payload, ctype = await self.proxy(
            req.match_info["ns"], req.match_info["name"], tail,
            method=req.method,
            body=await req.read(),
            content_type=req.content_type or "application/json",
            component=req.headers.get("X-Kftpu-Component", "").lower(),
            query_string=req.query_string,
        )
        headers = {}
        if status == 429:
            # proxy() returns a bare 3-tuple (the InferenceGraph calls
            # it in-process), so shed metadata rides the JSON payload
            # and is lifted into the standard header here.
            try:
                ra = json.loads(payload).get("retry_after_s")
                if ra is not None:
                    headers["Retry-After"] = str(max(1, math.ceil(ra)))
            except Exception as e:  # noqa: BLE001 - payload stays as-is
                logger.debug("429 payload without retry_after_s: %s", e)
        return web.Response(body=payload, status=status, content_type=ctype,
                            headers=headers)

    async def _handle_stream(self, req: web.Request,
                             tail: str) -> web.StreamResponse:
        """Streaming variant of handle(): same routing/cold-start core,
        but the upstream body is forwarded chunk-by-chunk. Always routes
        to the PREDICTOR (token streams don't compose with the
        transformer's whole-payload pre/postprocess contract)."""
        ns, name = req.match_info["ns"], req.match_info["name"]
        body = await req.read()
        out: Optional[web.StreamResponse] = None
        emitted = 0  # SSE events already written to the client
        tried: set = set()
        last_exc: Optional[BaseException] = None
        for attempt in range(self.MAX_RETRIES + 1):
            err, svc, replica = await self._route(
                ns, name, tail, component=PRIMARY, body=body,
                exclude=tried or None,
            )
            if err is not None:
                if out is not None or last_exc is not None:
                    break  # no survivor to resume on
                status, payload, ctype = err
                headers = {}
                if status == 429:
                    try:
                        ra = json.loads(payload).get("retry_after_s")
                        if ra is not None:
                            headers["Retry-After"] = str(
                                max(1, math.ceil(ra)))
                    except Exception as e:  # noqa: BLE001
                        logger.debug(
                            "429 payload without retry_after_s: %s", e)
                return web.Response(body=payload, status=status,
                                    content_type=ctype, headers=headers)
            try:
                url = f"http://127.0.0.1:{replica.port}/{tail}"
                if req.query_string:
                    url += f"?{req.query_string}"
                async with self.controller._http.request(
                    "POST", url, data=body if body else None,
                    headers={"Content-Type":
                             req.content_type or "application/json"},
                ) as upstream:
                    if out is None:
                        out = web.StreamResponse(status=upstream.status)
                        out.headers["Content-Type"] = upstream.headers.get(
                            "Content-Type", "text/event-stream"
                        )
                        out.headers["Cache-Control"] = "no-cache"
                        await out.prepare(req)
                    # Resume-by-offset: on a replay after a mid-stream
                    # death, drop the first ``emitted`` events -- the
                    # client already has them; forwarding them again
                    # would duplicate tokens. Chunk boundaries are not
                    # event boundaries, so split on the SSE delimiter.
                    skip = emitted
                    buf = b""
                    async for chunk in upstream.content.iter_any():
                        buf += chunk
                        while b"\n\n" in buf:
                            event, buf = buf.split(b"\n\n", 1)
                            if skip > 0:
                                skip -= 1
                                continue
                            await out.write(event + b"\n\n")
                            emitted += 1
                    if buf and skip <= 0:
                        await out.write(buf)
                    await out.write_eof()
                    self._note_result(svc, replica, ok=True)
                    return out
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                self._note_result(svc, replica, ok=False)
                tried.add(replica.index)
                last_exc = e
                logger.warning(
                    "activator %s/%s: stream died on replica %d after "
                    "%d event(s) (%s); resuming on a survivor", ns, name,
                    replica.index, emitted, e,
                )
            finally:
                self._release(svc, replica)
        if out is None:
            return web.json_response({"error": f"upstream: {last_exc}"},
                                     status=502)
        # Headers already sent and no survivor: the only honest move is
        # an in-band error event + EOF -- a second response object can't
        # be prepared on this connection.
        try:
            await out.write(
                b"data: " + json.dumps(
                    {"error": f"upstream: {last_exc}"}
                ).encode() + b"\n\ndata: [DONE]\n\n"
            )
            await out.write_eof()
        except (ConnectionResetError, aiohttp.ClientError):
            pass
        return out

    async def proxy(
        self,
        ns: str,
        name: str,
        tail: str,
        method: str = "POST",
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        component: str = "",
        query_string: str = "",
    ) -> tuple[int, bytes, str]:
        """The activator core, callable in-process (HTTP handler and the
        InferenceGraph router both use it): route to a ready replica of
        the ingress component, cold-starting if needed. Returns
        (status, payload bytes, content type)."""

        tried: set = set()
        last_exc: Optional[BaseException] = None
        for attempt in range(self.MAX_RETRIES + 1):
            err, svc, replica = await self._route(
                ns, name, tail, component, body=body,
                exclude=tried or None,
            )
            if err is not None:
                # No (further) replica: a shed/cold-start error on the
                # first attempt is the answer; after a failed attempt it
                # means no survivor -- report the upstream failure.
                if last_exc is None:
                    return err
                break
            try:
                url = f"http://127.0.0.1:{replica.port}/{tail}"
                if query_string:
                    url += f"?{query_string}"
                async with self.controller._http.request(
                    method, url, data=body if body else None,
                    headers={"Content-Type": content_type},
                ) as resp:
                    payload = await resp.read()
                    self._note_result(svc, replica, ok=resp.status < 500)
                    return (resp.status, payload, resp.content_type)
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                # Connection-level failure: the replica died under the
                # request. Trip breaker accounting and re-dispatch onto
                # a survivor -- idempotent for inference, which keeps no
                # state past the exchange.
                self._note_result(svc, replica, ok=False)
                tried.add(replica.index)
                last_exc = e
                logger.warning(
                    "activator %s/%s: replica %d failed mid-request "
                    "(%s); retry %d/%d", ns, name, replica.index, e,
                    attempt + 1, self.MAX_RETRIES,
                )
            finally:
                self._release(svc, replica)
        return (502, json.dumps({"error": f"upstream: {last_exc}"}).encode(),
                "application/json")

    def _release(self, svc: "_Service",
                 replica: Optional["_Replica"]) -> None:
        if replica is not None:
            replica.in_flight -= 1
        svc.in_flight -= 1
        svc.last_request = time.time()

    def _note_result(self, svc: "_Service", replica: "_Replica",
                     ok: bool) -> None:
        """Feed a request outcome into the service's router breaker (a
        no-op for services without a prefix-routing block). Consecutive
        failures trip the per-replica circuit and pull it from the
        ring; a success while non-closed re-admits it."""
        key = next(
            (k for k, s in self.controller.services.items() if s is svc),
            None,
        )
        router = self._routers.get(key) if key is not None else None
        if router is None:
            return
        rid = str(replica.index)
        if rid not in router.replicas:
            return
        if ok:
            router.record_success(rid)
        else:
            router.record_failure(rid)

    async def _route(
        self, ns: str, name: str, tail: str, component: str = "",
        body: Optional[bytes] = None, exclude: Optional[set] = None,
    ) -> tuple:
        """Routing + replica reservation shared by the buffered and
        streaming paths: canary split, transformer ingress, multi-model
        placement, cold-start wait. Returns (err, svc, replica); on
        success err is None and BOTH svc.in_flight and replica.in_flight
        are already incremented -- the caller MUST _release(svc, replica)
        when the exchange ends. On error, nothing is left reserved.
        ``exclude`` holds replica indices a retrying caller already
        watched fail for THIS request -- they stay out of consideration
        even before their breaker trips."""

        def err(status: int, message: str) -> tuple:
            return ((status, json.dumps({"error": message}).encode(),
                     "application/json"), None, None)

        key = f"{ns}/{name}"
        ctrl = self.controller
        raw = ctrl.store.get(KIND, name, ns)
        if raw is None:
            return err(404, f"inference service {key} not found")
        # Fail fast on a Failed (crash-looping / invalid) service instead
        # of holding the request for the whole cold-start timeout.
        failed = [
            c for c in raw.get("status", {}).get("conditions", [])
            if c.get("type") == "Failed" and c.get("status")
        ]
        if failed:
            return err(
                503,
                f"service failed ({failed[0].get('reason')}): "
                f"{failed[0].get('message')}",
            )
        # :explain routes to the explainer component (the reference's
        # explain verb); its replicas call the predictor back through
        # here with X-Kftpu-Component: predictor. Presence check, not
        # truthiness: "explainer": {} is a VALID spec (bundled ablation
        # explainer with all defaults) and must still route.
        has_explainer = (raw.get("spec") or {}).get("explainer") is not None
        if (has_explainer and component != PRIMARY
                and tail.endswith(":explain")):
            key = key + EXPLAINER_SUFFIX
        # With a transformer present, it is the ingress component; its
        # replicas call back here with X-Kftpu-Component: predictor
        # (KServe: transformer fronts the predictor service).
        has_transformer = bool((raw.get("spec") or {}).get("transformer"))
        if (has_transformer and component != PRIMARY
                and not key.endswith(EXPLAINER_SUFFIX)):
            key = key + TRANSFORMER_SUFFIX
        elif not key.endswith(TRANSFORMER_SUFFIX):
            # Canary split on the predictor path: a deterministic cursor
            # sends pct of 100 consecutive requests to the canary set
            # (exact split, testable; random() only approximates).
            pct = (raw.get("spec") or {}).get("canary_traffic_percent", 100)
            csvc = ctrl.services.get(key + CANARY_SUFFIX)
            if 0 < pct < 100 and csvc is not None and csvc.ready_replicas():
                primary = ctrl.services.setdefault(key, _Service())
                primary.canary_seq = (primary.canary_seq + 1) % 100
                if primary.canary_seq < pct:
                    key = key + CANARY_SUFFIX
        svc = ctrl.services.setdefault(key, _Service())
        svc.last_request = time.time()
        svc.in_flight += 1
        replica = None
        prefer = None
        is_multi_model = bool(
            ((raw.get("spec") or {}).get("predictor") or {}).get(
                "multi_model")
        )
        if is_multi_model and not key.endswith(
            (TRANSFORMER_SUFFIX, EXPLAINER_SUFFIX)
        ):
            # (Model routing applies to the PREDICTOR hop only: a
            # transformer ingress forwards to the predictor itself.)
            # Multi-model routing: send the request to the replica that
            # holds the named model (ModelMesh's model-aware router).
            m = re.match(r"v[12]/models/([^/:]+)", tail)
            if m is not None:
                mname = m.group(1)
                prefer = svc.model_locations.get(mname)
                targets_pool = False
                if prefer is None:
                    # Store lookup only on the miss path — the placed
                    # hot path must not pay a per-request SELECT.
                    tm_raw = ctrl.store.get(TRAINED_MODEL_KIND, mname, ns)
                    targets_pool = (
                        tm_raw is not None
                        and (tm_raw.get("spec") or {}).get(
                            "inference_service") == name
                    )
                if prefer is None and targets_pool:
                    # The model EXISTS but isn't placed yet (cold pool /
                    # placement in flight): 503 is honest and retryable;
                    # an empty replica's 404 would read as "no such
                    # model". Kick the pool awake so the retry lands —
                    # unless placement is already in failure backoff
                    # (client polling must not defeat the backoff and
                    # hammer the replicas' serialized load lock).
                    if not svc.ready_replicas() and svc.desired < 1:
                        svc.desired = 1
                    if svc.placement_failures == 0:
                        ctrl._enqueue(*_key_parts(key))
                    self._release(svc, None)
                    return err(
                        503,
                        f"model {mname} is not placed yet "
                        "(placement in progress)",
                    )
        routing_raw = None
        if prefer is None and not key.endswith(
            (TRANSFORMER_SUFFIX, EXPLAINER_SUFFIX)
        ):
            routing_raw = ((raw.get("spec") or {}).get("predictor")
                           or {}).get("routing")
        if (routing_raw
                and routing_raw.get("policy", "prefix") == "prefix"
                and svc.ready_replicas()):
            # Prefix-affinity data plane (docs/FLEET.md). Engaged only
            # with ready replicas: the cold-start path below already
            # owns the wait-and-replay dance, and an empty ring has no
            # affinity to offer anyway.
            shed_err, replica = await self._router_route(
                key, svc, routing_raw, ns, tail, body, exclude=exclude
            )
            if shed_err is not None:
                self._release(svc, None)
                return shed_err, None, None
            if replica is not None:
                replica.in_flight += 1
                return None, svc, replica
            # fall through (router had no healthy candidate)
        try:
            replica = await self._get_replica(key, svc, prefer,
                                              exclude=exclude)
        except BaseException:
            # Client disconnect during the cold-start wait cancels us
            # here; a leaked in_flight would pin the autoscaler's
            # scale-to-zero condition false forever.
            self._release(svc, None)
            raise
        if replica is None:
            self._release(svc, None)
            return err(503, "no replica became ready in time")
        replica.in_flight += 1
        return None, svc, replica

    async def _get_replica(self, key: str, svc: _Service,
                           prefer: Optional[int] = None,
                           exclude: Optional[set] = None,
                           ) -> Optional[_Replica]:
        if prefer is not None:
            # Model-aware routing: only the preferred replica holds the
            # model. Falling back to an arbitrary replica would turn a
            # transient relocation into a misleading 404 — return "no
            # replica" (503, retryable) and let placement converge.
            rep = svc.replicas.get(prefer)
            if rep is not None and rep.ready and not (
                    exclude and prefer in exclude):
                return rep
            return None
        ready = svc.ready_replicas()
        if ready and exclude:
            ready = [r for r in ready if r.index not in exclude]
            if not ready:
                # Every ready replica already failed this request; a
                # cold-start wait would re-offer the same set.
                return None
        if not ready:
            # Cold start: ask for at least one replica and hold the request.
            if svc.desired < 1:
                svc.desired = 1
            self.controller._enqueue(*_key_parts(key))
            try:
                await asyncio.wait_for(
                    svc.ready_event.wait(), self.cold_start_timeout
                )
            except asyncio.TimeoutError:
                return None
            ready = svc.ready_replicas()
            if exclude:
                ready = [r for r in ready if r.index not in exclude]
            if not ready:
                return None
        svc.rr = (svc.rr + 1) % len(ready)
        return ready[svc.rr]

    # -- prefix-affinity data plane (docs/FLEET.md) ---------------------

    @staticmethod
    def _affinity_text(body: Optional[bytes]) -> str:
        """Pull the routing-relevant prompt text out of a request body.
        Covers the repo's inference dialects: v1 {"instances": [...]},
        v2/generate {"prompt"| "inputs"}, OpenAI {"messages": [...]}.
        Non-JSON or unrecognized bodies hash raw bytes -- identical
        payloads still co-locate, they just don't share a prefix key
        with a differently-framed equivalent."""
        if not body:
            return ""
        try:
            data = json.loads(body)
        except Exception:  # noqa: BLE001
            return body.decode("utf-8", "replace")
        if not isinstance(data, dict):
            return body.decode("utf-8", "replace")
        for k in ("prompt", "inputs", "text_input"):
            v = data.get(k)
            if isinstance(v, str) and v:
                return v
        msgs = data.get("messages")
        if isinstance(msgs, list) and msgs:
            parts = []
            for m in msgs:
                if isinstance(m, dict) and isinstance(m.get("content"), str):
                    parts.append(m["content"])
            if parts:
                return "\n".join(parts)
        inst = data.get("instances")
        if isinstance(inst, list) and inst:
            return json.dumps(inst[0], sort_keys=True)
        return body.decode("utf-8", "replace")

    def _router_for(self, key: str, routing_raw: dict) -> Router:
        fp = json.dumps(routing_raw, sort_keys=True)
        router = self._routers.get(key)
        if router is None or self._router_fps.get(key) != fp:
            router = Router(
                RouterConfig(
                    vnodes=int(routing_raw.get("vnodes", 64)),
                    slo_ttft_ms=routing_raw.get("slo_ttft_ms"),
                    long_prompt_threshold=routing_raw.get(
                        "long_prompt_threshold_chars"),
                ),
                name=key,
            )
            self._routers[key] = router
            self._router_fps[key] = fp
        return router

    async def _router_route(
        self, key: str, svc: _Service, routing_raw: dict,
        ns: str, tail: str, body: Optional[bytes],
        exclude: Optional[set] = None,
    ) -> tuple:
        """Returns (shed_err3 | None, replica | None). (None, None)
        means the router abstained -- caller falls back to round-robin.
        svc.in_flight is already held by _route; this neither takes nor
        releases it."""
        router = self._router_for(key, routing_raw)
        ready = svc.ready_replicas()
        # Keep mid-warm-up newcomers out of the ring: their prefix
        # migration is still landing (serving/kv_reshard). Unless they
        # are ALL warming -- then availability beats warm caches.
        warming = self._warming.get(key) or set()
        warm_ready = [r for r in ready if r.index not in warming]
        if warm_ready:
            ready = warm_ready
        router.sync_replicas({
            str(r.index): {"role": getattr(r, "role", "mixed")}
            for r in ready
        })
        # Router-side in_flight mirrors the activator's per-replica
        # reservation counts (leak-free by construction: _release owns
        # the decrement of the source of truth).
        by_rid = {str(r.index): r for r in ready}
        for rid, rep in by_rid.items():
            load = router.replicas.get(rid)
            if load is not None:
                load.in_flight = rep.in_flight
        self._ensure_load_poll(key, float(
            routing_raw.get("load_poll_seconds", 2.0)))
        text = self._affinity_text(body)
        m = re.match(r"v[12]/models/([^/:]+)", tail)
        if m is not None and text:
            # Remember what flowed through recently: the donor material
            # for re-warming a respawned replica's prefix cache.
            recent = self._recent_texts.setdefault(
                key, collections.OrderedDict())
            recent[(m.group(1), text)] = None
            recent.move_to_end((m.group(1), text))
            while len(recent) > 4 * self.REWARM_PREFIXES:
                recent.popitem(last=False)
        decision = router.route(
            prefix_route_key(text), prompt_len=len(text)
        )
        if decision.kind == "shed":
            payload = json.dumps({
                "error": "overloaded: estimated TTFT "
                         f"{decision.est_ttft_ms:.0f}ms exceeds SLO",
                "retry_after_s": decision.retry_after_s,
            }).encode()
            return (429, payload, "application/json"), None
        if decision.kind == "none" or decision.replica not in by_rid:
            return None, None
        replica = by_rid[decision.replica]
        if exclude and replica.index in exclude:
            # Already failed for this request: abstain so the RR
            # fallback (which honors ``exclude``) picks a survivor.
            return None, None
        if decision.kind == "disagg":
            pre = by_rid.get(decision.prefill_replica or "")
            if pre is None:
                # Prefill replicas are load-polled but not in the ready
                # decode set by_rid -- look them up directly.
                pre = next(
                    (r for r in ready
                     if str(r.index) == decision.prefill_replica), None)
            if pre is not None and pre is not replica:
                await self._disagg_handoff(pre, replica, tail, text)
        return None, replica

    async def _disagg_handoff(self, pre: "_Replica", dec: "_Replica",
                              tail: str, text: str) -> None:
        """Prefill ``text`` on the prefill replica and ship its KV
        packet to the decode replica over the runtime's prefix
        export/import endpoints. Best-effort: any failure logs and
        falls back to the decode replica prefilling locally -- the
        response stays correct either way."""
        m = re.search(r"v[12]/models/([^/:]+)", tail)
        if m is None:
            return
        mname, http = m.group(1), self.controller._http
        t0 = time.monotonic()
        try:
            with trace.span("kv-handoff", plane="serving", track="router",
                            prefill=pre.index, decode=dec.index):
                async with http.post(
                    f"http://127.0.0.1:{pre.port}/v2/models/{mname}"
                    "/prefix/export",
                    json={"prompt": text},
                ) as resp:
                    if resp.status != 200:
                        return  # 204: under one block; 4xx/5xx: skip
                    packet = await resp.read()
                if chaos.enabled():
                    # Chaos seam: a corrupt_packet fault flips one byte
                    # in flight; the import side must fail closed (the
                    # decode replica then prefills locally).
                    packet = chaos.corrupt_bytes(
                        packet, "kv.packet", str(dec.index))
                async with http.post(
                    f"http://127.0.0.1:{dec.port}/v2/models/{mname}"
                    "/prefix/import",
                    data=packet,
                    headers={"Content-Type": "application/octet-stream"},
                ) as resp:
                    resp.raise_for_status()
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning(
                "kv-handoff %s: prefill %d -> decode %d failed after "
                "%.2fs (%s); decode replica will prefill locally",
                mname, pre.index, dec.index, time.monotonic() - t0, e,
            )

    def _ensure_load_poll(self, key: str, interval: float) -> None:
        ctrl = self.controller
        tkey = f"loadpoll#{key}"
        t = ctrl._probe_tasks.get(tkey)
        if t is None or t.done():
            ctrl._probe_tasks[tkey] = asyncio.create_task(
                self._load_poll(key, interval)
            )

    async def _load_poll(self, key: str, interval: float) -> None:
        """Per-service poll feeding /healthz ``load`` gauges into the
        router (queue depth, active slots, TTFT EMA). Ends itself when
        the service or its router goes away; the controller's shutdown
        path cancels it via _probe_tasks."""
        ctrl = self.controller
        while not ctrl._stopped.is_set():
            svc = ctrl.services.get(key)
            router = self._routers.get(key)
            if svc is None or router is None or not svc.replicas:
                return
            for rep in svc.ready_replicas():
                rid = str(rep.index)
                fault = chaos.should("router.load_poll", rid)
                if fault is not None and fault.kind == "drop_poll":
                    # Chaos seam: the poll never happened -- exactly a
                    # dropped health response on the wire.
                    router.note_poll(rid, ok=False)
                    continue
                try:
                    async with ctrl._http.get(
                        f"http://127.0.0.1:{rep.port}/healthz",
                        timeout=aiohttp.ClientTimeout(total=2.0),
                    ) as resp:
                        data = await resp.json()
                except Exception as e:  # noqa: BLE001 - replica churn
                    logger.debug("load poll %s replica %s: %s",
                                 key, rep.index, e)
                    router.note_poll(rid, ok=False)
                    continue
                router.note_poll(rid, ok=True)
                load = (data or {}).get("load") or {}
                agg = {"queue_depth": 0, "slots_active": 0, "max_slots": 0}
                ema = 0.0
                for stats in load.values():
                    agg["queue_depth"] += int(stats.get("queue_depth", 0))
                    agg["slots_active"] += int(
                        stats.get("slots_active", 0))
                    agg["max_slots"] += int(stats.get("max_slots", 0))
                    ema = max(ema, float(stats.get("ttft_ema_ms", 0.0)))
                if load:
                    router.update_load(str(rep.index), {
                        **agg, "ttft_ema_ms": ema or None,
                    })
            try:
                await asyncio.sleep(interval)
            except asyncio.CancelledError:
                return

    async def _rewarm_replica(self, key: str, rep: "_Replica") -> None:
        """Warm a (re)spawned replica through the real migration path
        (serving/kv_reshard): poll the surviving donors' hottest-entry
        inventories, plan exactly the entries whose ring home the
        newcomer's arrival moves (router.ring_diff -- nothing else is
        worth shipping), and transfer each top-K entry from its
        least-pressured donor over the PR 7 export/import wire. The
        newcomer sits in ``_warming`` (out of the affinity ring) until
        the transfer lands, so its first routed requests hit a warm
        cache. Falls back to the recent-prompt re-warm when donors
        predate the inventory route. Best-effort throughout -- every
        failure just leaves that prefix cold."""
        from kubeflow_tpu.serving import kv_reshard

        ctrl = self.controller
        svc = ctrl.services.get(key)
        if svc is None:
            return
        donors = [r for r in svc.ready_replicas()
                  if r.index != rep.index]
        if not donors:
            return
        self._warming.setdefault(key, set()).add(rep.index)
        try:
            warmed = await self._migrate_into(key, rep, donors, kv_reshard)
            if warmed == 0:
                # Donors without /prefix/inventory (older image) still
                # speak export/import: re-warm from recent prompts.
                warmed = await self._rewarm_from_recent(key, rep, donors)
            if warmed:
                logger.info("isvc %s: re-warmed %d prefix entries into "
                            "replica %d", key, warmed, rep.index)
        finally:
            w = self._warming.get(key)
            if w is not None:
                w.discard(rep.index)
                if not w:
                    self._warming.pop(key, None)

    async def _migrate_into(self, key: str, rep: "_Replica",
                            donors: list, kv_reshard) -> int:
        """Plan + execute the ring-moved prefix transfer into ``rep``.
        Returns entries landed (0 when inventories are unavailable)."""
        ctrl = self.controller
        router = self._routers.get(key)
        vnodes = router.cfg.vnodes if router is not None else 64
        block = (router.cfg.block if router is not None
                 else kv_reshard.DEFAULT_BLOCK)
        pressures: Dict[str, float] = {}
        if router is not None:
            for rid, load in router.replicas.items():
                pressures[rid] = float(load.pressure())
        mnames: list = []
        for donor in donors:
            try:
                async with ctrl._http.get(
                    f"http://127.0.0.1:{donor.port}/healthz",
                    timeout=aiohttp.ClientTimeout(total=2),
                ) as resp:
                    mnames = list((await resp.json()).get("models") or [])
                break
            except Exception as e:  # noqa: BLE001 - donor churn
                logger.debug("rewarm %s: healthz donor %d: %s",
                             key, donor.index, e)
        before = [str(r.index) for r in donors]
        after = before + [str(rep.index)]
        by_rid = {str(r.index): r for r in donors}
        warmed = 0
        for mname in mnames:
            inventories: Dict[str, list] = {}
            for donor in donors:
                try:
                    async with ctrl._http.get(
                        f"http://127.0.0.1:{donor.port}/v2/models/"
                        f"{mname}/prefix/inventory",
                        params={"top_k": str(4 * self.REWARM_PREFIXES)},
                        timeout=aiohttp.ClientTimeout(total=5),
                    ) as resp:
                        if resp.status != 200:
                            continue
                        rows = (await resp.json()).get("entries") or []
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    continue
                if rows:
                    inventories[str(donor.index)] = rows
            if not inventories:
                continue
            manifest = kv_reshard.plan_prefix_migration(
                before, after, inventories, block=block, vnodes=vnodes,
                top_k=self.REWARM_PREFIXES, pressures=pressures or None,
            )
            for move in manifest["moves"]:
                if move["dst"] != str(rep.index):
                    continue  # this hook only warms the newcomer
                donor = by_rid.get(move["src"])
                if donor is None:
                    continue
                with trace.span("kv.migrate", plane="serving",
                                track="kv-migrate", src=move["src"],
                                dst=move["dst"],
                                bytes=int(move.get("bytes", 0)),
                                plen=int(move.get("plen", 0))) as sp:
                    try:
                        async with ctrl._http.post(
                            f"http://127.0.0.1:{donor.port}/v2/models/"
                            f"{mname}/prefix/export",
                            json={"token_ids": move["tokens"],
                                  "ensure": False},
                            timeout=aiohttp.ClientTimeout(total=5),
                        ) as resp:
                            if resp.status != 200:
                                sp.annotate(outcome="miss")
                                continue
                            packet = await resp.read()
                        async with ctrl._http.post(
                            f"http://127.0.0.1:{rep.port}/v2/models/"
                            f"{mname}/prefix/import",
                            data=packet,
                            headers={"Content-Type":
                                     "application/octet-stream"},
                            timeout=aiohttp.ClientTimeout(total=5),
                        ) as resp:
                            ok = resp.status == 200
                    except (aiohttp.ClientError,
                            asyncio.TimeoutError) as e:
                        sp.annotate(outcome="error",
                                    error=type(e).__name__)
                        logger.debug("rewarm %s[%d] via donor %s: %s",
                                     key, rep.index, move["src"], e)
                        continue
                    if ok:
                        warmed += 1
                        sp.annotate(outcome="ok")
                    else:
                        sp.annotate(outcome="error")
        return warmed

    async def _rewarm_from_recent(self, key: str, rep: "_Replica",
                                  donors: list) -> int:
        """Legacy re-warm: replay recently routed prompts through any
        donor's export route (donor tokenizes). Used only when the
        inventory-driven migration shipped nothing."""
        ctrl = self.controller
        recent = self._recent_texts.get(key)
        if not recent:
            return 0
        pairs = list(recent.keys())[-self.REWARM_PREFIXES:]
        warmed = 0
        with trace.span("replica-rewarm", plane="serving", track="router",
                        replica=rep.index, prefixes=len(pairs)):
            for mname, text in pairs:
                for donor in donors:
                    try:
                        async with ctrl._http.post(
                            f"http://127.0.0.1:{donor.port}/v2/models/"
                            f"{mname}/prefix/export",
                            json={"prompt": text},
                            timeout=aiohttp.ClientTimeout(total=5),
                        ) as resp:
                            if resp.status != 200:
                                break  # donor has no packet; next prefix
                            packet = await resp.read()
                        async with ctrl._http.post(
                            f"http://127.0.0.1:{rep.port}/v2/models/"
                            f"{mname}/prefix/import",
                            data=packet,
                            headers={"Content-Type":
                                     "application/octet-stream"},
                            timeout=aiohttp.ClientTimeout(total=5),
                        ) as resp:
                            if resp.status == 200:
                                warmed += 1
                        break
                    except (aiohttp.ClientError,
                            asyncio.TimeoutError) as e:
                        logger.debug("rewarm %s[%d] via donor %d: %s",
                                     key, rep.index, donor.index, e)
                        continue
        return warmed
