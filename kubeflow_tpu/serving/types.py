"""InferenceService / ServingRuntime API types (KServe-equivalent, SURVEY.md 3.3 S1).

Shape mirrors KServe's v1beta1 InferenceService: a predictor (plus optional
transformer) described either by a model {format, storage_uri} resolved
against a runtime registry, or by a custom process template; scaling with
``min_replicas=0`` meaning scale-to-zero behind the activator.

TPU-first deltas vs the reference:

- The runtime registry maps model formats to in-repo Python server modules
  (reference: ServingRuntime CRs naming container images); the ``jax``
  format is the PJRT/StableHLO LLM path (SURVEY.md 3.3 delta, config #5).
- Replicas are local server processes gang-free (serving replicas are
  independent, unlike training gangs); TPU chips are still counted against
  the shared capacity model so serving and training contend for the same
  slice, as they do on a real cell.
"""

from __future__ import annotations

import enum
import time
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field

from kubeflow_tpu.api import conditions
from kubeflow_tpu.api.types import ObjectMeta, Resources

KIND = "InferenceService"


class ModelFormat(str, enum.Enum):
    """Built-in model formats with bundled server runtimes (S5)."""

    sklearn = "sklearn"
    xgboost = "xgboost"  # Booster files; library optional (gated at load)
    lightgbm = "lightgbm"  # Booster files; library optional (gated at load)
    jax = "jax"  # JAX/StableHLO LLM predictor on PJRT (north-star config #5)
    jax_embed = "jax-embed"  # flax BERT text embeddings on TPU (S5 delta)
    huggingface = "huggingface"  # transformers on host CPU (S5 parity)
    pmml = "pmml"  # pypmml; library optional (gated at load)
    paddle = "paddle"  # paddle inference; library optional (gated at load)
    echo = "echo"  # conformance/test runtime (reference: custom example images)
    custom = "custom"


class ModelSpec(BaseModel):
    """What to serve: a format + where the weights live."""

    model_config = ConfigDict(extra="forbid")

    format: ModelFormat = ModelFormat.custom
    storage_uri: Optional[str] = None  # file://, hf://, or bare path
    name: Optional[str] = None  # served model name; defaults to ISVC name
    # Format-specific options passed to the runtime verbatim (e.g. the jax
    # runtime's preset/max_batch/max_seq_len). Reference analog: the
    # opaque args/env of a ServingRuntime container.
    options: Dict[str, Any] = Field(default_factory=dict)


class CustomSpec(BaseModel):
    """Custom server process (reference: custom predictor container)."""

    model_config = ConfigDict(extra="forbid")

    entrypoint: str  # python module run as ``python -m entrypoint``
    args: List[str] = Field(default_factory=list)
    env: Dict[str, str] = Field(default_factory=dict)


class LoggerSpec(BaseModel):
    """S6 request/response payload logging: JSONL file sink or http sink
    (KServe's logger.url/logger.mode)."""

    model_config = ConfigDict(extra="forbid")

    sink: str  # file path, file://, or http(s):// collector
    mode: str = "all"  # all | request | response


class MultiModelSpec(BaseModel):
    """ModelMesh-style high-density multi-model serving (S7): many
    models share this component's replica pool; each model is placed on
    one replica, loaded on demand, and evicted LRU when a replica
    exceeds ``max_models_per_replica``. Models are declared as separate
    ``TrainedModel`` objects referencing the InferenceService."""

    model_config = ConfigDict(extra="forbid")

    max_models_per_replica: int = Field(default=4, ge=1)


class RoutingSpec(BaseModel):
    """Fleet data-plane routing for a multi-replica component
    (serving/router.py, docs/FLEET.md). Absent -> the legacy
    round-robin activator path, byte-for-byte.

    ``policy="prefix"`` consistent-hash-routes requests on the prompt
    prefix (the activator keys on the leading request-body text; the
    granularity matches the engine prefix cache) so per-replica prefix
    caches compose into a fleet-level one, with queue/TTFT-aware
    second-choice spill. ``slo_ttft_ms`` arms load shedding: when every
    candidate's TTFT estimate exceeds it, the activator answers 429
    with a computed Retry-After. ``long_prompt_threshold_chars`` steers
    long prompts off their affinity home (to the prefill pool when
    ``prefill_replicas`` > 0 -- disaggregated mode, where the prefill
    replica hands the KV prefix to the decode replica over the packet
    wire format -- else to the least-loaded candidate)."""

    model_config = ConfigDict(extra="forbid")

    policy: str = "prefix"  # prefix | round_robin
    vnodes: int = Field(default=64, ge=1)
    slo_ttft_ms: Optional[float] = Field(default=None, gt=0)
    long_prompt_threshold_chars: Optional[int] = Field(default=None, ge=1)
    # First N replica indexes spawn as dedicated prefill replicas
    # (KFTPU_REPLICA_ROLE=prefill): they never take decode traffic,
    # only handoff prefills.
    prefill_replicas: int = Field(default=0, ge=0)
    # Activator -> replica /healthz load-poll period (seconds).
    load_poll_seconds: float = Field(default=2.0, gt=0)


class ComponentSpec(BaseModel):
    """One ISVC component (predictor or transformer)."""

    model_config = ConfigDict(extra="forbid")

    model: Optional[ModelSpec] = None
    custom: Optional[CustomSpec] = None
    multi_model: Optional[MultiModelSpec] = None
    logger: Optional[LoggerSpec] = None
    routing: Optional[RoutingSpec] = None
    resources: Resources = Field(default_factory=Resources)
    min_replicas: int = 1  # 0 = scale-to-zero
    max_replicas: int = 1
    # Autoscaling target: mean in-flight requests per replica (KServe's
    # default KPA metric is concurrency; same here).
    target_concurrency: float = 4.0
    # Idle seconds before the last replica is reaped when min_replicas=0.
    scale_to_zero_grace_seconds: float = 30.0


class InferenceServiceSpec(BaseModel):
    model_config = ConfigDict(extra="forbid")

    predictor: ComponentSpec
    transformer: Optional[ComponentSpec] = None
    # Explainer component (reference ISVC triple predictor/transformer/
    # explainer): serves ``:explain`` by calling the predictor and
    # returning per-feature attributions. With neither model nor custom
    # set, the bundled feature-ablation explainer runs
    # (serving/runtimes/explainer_server.py); custom: runs a process
    # subclassing serving.explainer.ExplainerModel.
    explainer: Optional[ComponentSpec] = None
    # Percent of traffic to the newest generation during a rollout
    # (reference: canaryTrafficPercent). 100 = all traffic to latest.
    canary_traffic_percent: int = 100


class ReplicaState(str, enum.Enum):
    Pending = "Pending"
    Ready = "Ready"
    Terminating = "Terminating"


class ReplicaInfo(BaseModel):
    model_config = ConfigDict(extra="forbid")

    index: int
    port: int
    # OIP gRPC port (serving/grpc_server.py). gRPC is served per-replica
    # and advertised here; the activator edge stays HTTP (its cold-start
    # buffer is an L7 HTTP mechanism, as in the reference where gRPC
    # rides the mesh gateway rather than the Knative activator).
    grpc_port: Optional[int] = None
    pid: Optional[int] = None
    state: ReplicaState = ReplicaState.Pending
    started_at: float = 0.0


class ComponentStatus(BaseModel):
    model_config = ConfigDict(extra="forbid")

    desired_replicas: int = 0
    ready_replicas: int = 0
    replicas: List[ReplicaInfo] = Field(default_factory=list)


class InferenceServiceStatus(BaseModel):
    model_config = ConfigDict(extra="forbid")

    conditions: List[dict] = Field(default_factory=list)
    url: Optional[str] = None
    predictor: ComponentStatus = Field(default_factory=ComponentStatus)
    transformer: Optional[ComponentStatus] = None
    explainer: Optional[ComponentStatus] = None
    # Revision/canary rollout (reference: canaryTrafficPercent + Knative
    # revisions). stable_predictor is the last PROMOTED predictor spec;
    # while a canary rollout is in flight the stable set keeps serving it
    # and the canary set runs the applied spec at canary_percent traffic.
    stable_predictor: Optional[dict] = None
    canary: Optional[ComponentStatus] = None
    canary_percent: Optional[int] = None
    # Activator-observed load, persisted for visibility (kftpu get isvc).
    in_flight: int = 0
    last_request_time: float = 0.0


class InferenceService(BaseModel):
    model_config = ConfigDict(extra="forbid")

    kind: str = KIND
    metadata: ObjectMeta
    spec: InferenceServiceSpec
    status: InferenceServiceStatus = Field(default_factory=InferenceServiceStatus)

    @classmethod
    def from_dict(cls, d: dict) -> "InferenceService":
        return cls.model_validate(d)

    def to_dict(self) -> dict:
        return self.model_dump(mode="json", exclude_none=True)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


TRAINED_MODEL_KIND = "TrainedModel"


class TrainedModelSpec(BaseModel):
    model_config = ConfigDict(extra="forbid")

    # The multi-model InferenceService whose replica pool serves this
    # model (KServe's TrainedModel.spec.inferenceService).
    inference_service: str
    model: ModelSpec


class TrainedModelStatus(BaseModel):
    model_config = ConfigDict(extra="forbid")

    conditions: List[dict] = Field(default_factory=list)
    url: Optional[str] = None
    # Which replica of the target service currently holds the model.
    replica_index: Optional[int] = None
    loaded: bool = False


class TrainedModel(BaseModel):
    model_config = ConfigDict(extra="forbid")

    kind: str = TRAINED_MODEL_KIND
    metadata: ObjectMeta
    spec: TrainedModelSpec
    status: TrainedModelStatus = Field(default_factory=TrainedModelStatus)

    @classmethod
    def from_dict(cls, d: dict) -> "TrainedModel":
        return cls.model_validate(d)

    def to_dict(self) -> dict:
        return self.model_dump(mode="json", exclude_none=True)


class ServingValidationError(ValueError):
    pass


def validate_trained_model(tm: TrainedModel) -> None:
    if tm.spec.model.format == ModelFormat.custom:
        raise ServingValidationError(
            "TrainedModel needs a bundled format (multi-model replicas "
            "construct models from the runtime registry)"
        )
    if tm.spec.model.format not in RUNTIMES:
        raise ServingValidationError(
            f"no runtime for format {tm.spec.model.format}"
        )


def validate_isvc(isvc: InferenceService) -> None:
    """Semantic validation beyond pydantic shape checks (webhook analog)."""

    for label, comp in (("predictor", isvc.spec.predictor),
                        ("transformer", isvc.spec.transformer),
                        ("explainer", isvc.spec.explainer)):
        if comp is None:
            continue
        if label == "explainer":
            # Explainers default to the bundled ablation runtime when
            # neither model nor custom is given; model: is not a thing.
            if comp.model is not None:
                raise ServingValidationError(
                    "explainer: use custom: (a process subclassing "
                    "serving.explainer.ExplainerModel) or leave empty "
                    "for the bundled feature-ablation explainer"
                )
        elif (comp.model is None) == (comp.custom is None):
            raise ServingValidationError(
                f"{label}: exactly one of model/custom must be set"
            )
        if comp.logger is not None and comp.logger.mode not in (
            "all", "request", "response"
        ):
            raise ServingValidationError(
                f"{label}: logger.mode must be all|request|response"
            )
        if comp.model is not None:
            if comp.model.format == ModelFormat.custom:
                raise ServingValidationError(
                    f"{label}: format=custom has no bundled runtime; use the "
                    f"custom: process spec instead of model:"
                )
            if comp.model.format not in RUNTIMES:
                raise ServingValidationError(
                    f"{label}: no runtime for format {comp.model.format}"
                )
        if comp.min_replicas < 0 or comp.max_replicas < 1:
            raise ServingValidationError(
                f"{label}: min_replicas>=0 and max_replicas>=1 required"
            )
        if comp.min_replicas > comp.max_replicas:
            raise ServingValidationError(
                f"{label}: min_replicas {comp.min_replicas} > "
                f"max_replicas {comp.max_replicas}"
            )
        if comp.target_concurrency <= 0:
            raise ServingValidationError(f"{label}: target_concurrency must be > 0")
        if comp.routing is not None:
            if comp.routing.policy not in ("prefix", "round_robin"):
                raise ServingValidationError(
                    f"{label}: routing.policy must be prefix|round_robin"
                )
            if label != "predictor":
                raise ServingValidationError(
                    "routing applies to predictors only (transformer/"
                    "explainer hops forward to the routed predictor)"
                )
            if comp.routing.prefill_replicas >= max(
                comp.min_replicas, comp.max_replicas
            ):
                raise ServingValidationError(
                    f"{label}: routing.prefill_replicas "
                    f"{comp.routing.prefill_replicas} must leave at "
                    "least one decode replica (< max_replicas)"
                )
        if comp.multi_model is not None:
            if label != "predictor":
                raise ServingValidationError(
                    "multi_model applies to predictors only"
                )
            if comp.model is None:
                raise ServingValidationError(
                    "multi_model needs model.format to select the "
                    "replica runtime (models themselves come from "
                    "TrainedModel objects)"
                )
            if isvc.spec.canary_traffic_percent < 100:
                raise ServingValidationError(
                    "multi_model pools do not support canary rollouts "
                    "(canary replicas would receive no model "
                    "placements); roll models via TrainedModel updates "
                    "instead"
                )
            if comp.model.storage_uri or comp.model.name:
                raise ServingValidationError(
                    "multi_model pools ignore model.storage_uri/name — "
                    "the pool's model spec only selects the runtime "
                    "(format/options); the served models come from "
                    "TrainedModel objects"
                )
    if not 0 <= isvc.spec.canary_traffic_percent <= 100:
        raise ServingValidationError("canary_traffic_percent must be in [0, 100]")
    if isvc.spec.transformer is not None:
        # Transformers are custom processes (the reference's transformers
        # are custom containers too); serving.transformer.TransformerModel
        # is the 10-line base class for writing one.
        if isvc.spec.transformer.custom is None:
            raise ServingValidationError(
                "transformer components must use custom: (a process "
                "subclassing serving.transformer.TransformerModel); "
                "model: formats apply to predictors only"
            )


# Runtime registry: model format -> server entry module (ServingRuntime CR
# analog; see serving/runtimes/). Custom formats bypass the registry.
RUNTIMES: Dict[ModelFormat, str] = {
    ModelFormat.sklearn: "kubeflow_tpu.serving.runtimes.sklearn_server",
    ModelFormat.xgboost: "kubeflow_tpu.serving.runtimes.xgboost_server",
    ModelFormat.lightgbm: "kubeflow_tpu.serving.runtimes.lightgbm_server",
    ModelFormat.jax: "kubeflow_tpu.serving.runtimes.jax_llm_server",
    ModelFormat.jax_embed: "kubeflow_tpu.serving.runtimes.jax_embed_server",
    ModelFormat.huggingface:
        "kubeflow_tpu.serving.runtimes.huggingface_server",
    ModelFormat.echo: "kubeflow_tpu.serving.runtimes.echo_server",
    ModelFormat.pmml: "kubeflow_tpu.serving.runtimes.pmml_server",
    ModelFormat.paddle: "kubeflow_tpu.serving.runtimes.paddle_server",
}


# Ready/Unready/Failed are mutually exclusive; Created is sticky.
_EXCLUSIVE = ("Ready", "Unready", "Failed")


def set_condition(status: InferenceServiceStatus, ctype: str,
                  reason: str = "", message: str = "") -> None:
    conditions.set_condition(status.conditions, ctype, _EXCLUSIVE, reason, message)


def now() -> float:
    return time.time()
