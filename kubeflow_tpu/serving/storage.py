"""Storage initializer (KServe-equivalent, SURVEY.md 3.3 S3).

The reference runs an initContainer that downloads ``storageUri`` to
``/mnt/models`` before the server starts; here ``initialize()`` is called
by the runtime process at boot (same sequencing: weights are local before
the server binds its port).

Supported schemes in this environment (zero egress, SURVEY.md 7.0):

- bare paths and ``file://``  -- local files/directories, symlinked into
  the model dir (copy-free: checkpoints are GBs).
- ``hf://org/name``           -- resolved against the local HF cache only
  (``HF_HOME``); a cache miss raises instead of attempting network.
- ``s3://``/``gs://``/``http(s)://`` -- recognized and rejected with a
  clear error (egress-gated; the reference's downloaders have no offline
  mode to emulate).
"""

from __future__ import annotations

import os
from typing import Optional


class StorageError(RuntimeError):
    pass


_GATED = ("s3://", "gs://", "http://", "https://")


def initialize(storage_uri: str, dest_dir: str) -> str:
    """Materialize ``storage_uri`` under ``dest_dir``; returns the model path.

    Directories and files are symlinked (not copied) -- local storage plays
    the role of the reference's object store, and the serving process never
    mutates model artifacts.
    """

    os.makedirs(dest_dir, exist_ok=True)
    for scheme in _GATED:
        if storage_uri.startswith(scheme):
            raise StorageError(
                f"scheme {scheme} requires network egress, which this "
                f"environment gates; stage the model locally and use file://"
            )
    if storage_uri.startswith("hf://"):
        return _resolve_hf(storage_uri[len("hf://"):], dest_dir)

    path = storage_uri[len("file://"):] if storage_uri.startswith("file://") else storage_uri
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.exists(path):
        raise StorageError(f"storage uri {storage_uri} -> {path}: does not exist")

    link = os.path.join(dest_dir, os.path.basename(path.rstrip("/")))
    if os.path.islink(link):
        if os.path.realpath(link) == os.path.realpath(path):
            return link
        os.remove(link)
    elif os.path.exists(link):
        raise StorageError(f"{link} exists and is not a symlink; refusing to clobber")
    os.symlink(path, link)
    return link


def _resolve_hf(repo_id: str, dest_dir: str) -> str:
    """Find ``repo_id`` in the local HF hub cache; never touches network."""

    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - hub ships with transformers
        raise StorageError("huggingface_hub not installed") from e
    try:
        path = snapshot_download(repo_id, local_files_only=True)
    except Exception as e:
        raise StorageError(
            f"hf://{repo_id} not in local cache and network egress is "
            f"gated ({e}); pre-stage the snapshot or use file://"
        ) from e
    link = os.path.join(dest_dir, repo_id.replace("/", "--"))
    if not os.path.exists(link):
        os.symlink(path, link)
    return link


def model_path(storage_uri: Optional[str], dest_dir: str) -> Optional[str]:
    """``initialize`` if a uri is given, else None (custom servers may not
    take weights at all)."""

    return initialize(storage_uri, dest_dir) if storage_uri else None
