"""Serving pillar (KServe-equivalent, SURVEY.md 3.3 + 7.1 step 7).

- ``types``     InferenceService/runtime-registry API types (S1)
- ``storage``   storage initializer (S3)
- ``model``     Model base class, repository, batcher (S4 + S6 batcher)
- ``server``    aiohttp V1/V2 protocol server (S4)
- ``runtimes``  bundled format runtimes: sklearn, jax LLM, echo (S5)
- ``controller``ISVC reconciler + autoscaler + scale-to-zero activator (S2)
"""

from kubeflow_tpu.serving.model import Batcher, InferenceError, Model, ModelRepository
from kubeflow_tpu.serving.server import ModelServer
from kubeflow_tpu.serving.types import (
    InferenceService,
    ModelFormat,
    ServingValidationError,
    validate_isvc,
)

__all__ = [
    "Batcher",
    "InferenceError",
    "InferenceService",
    "Model",
    "ModelFormat",
    "ModelRepository",
    "ModelServer",
    "ServingValidationError",
    "validate_isvc",
]
