"""Multi-replica serving data plane: prefix-affinity consistent-hash
routing, queue/TTFT-aware balancing with load shedding, and the
prefill/decode disaggregation KV-handoff wire format.

The engine (serving/engine.py) is one process; the controller
(serving/controller.py) already runs N of them behind an activator that
round-robins. This module is the missing routing brain, shared by the
activator, bench_serving.py's fleet phase, and tests:

* ``prefix_route_key`` -- the affinity key. Token prompts hash with the
  SAME blake2b chain scheme and block granularity as the engine's
  PrefixCache first block (seed ``b"kftpu-prefix"``), so two prompts
  that would share a cache entry inside one engine also land on the
  same replica -- the per-replica prefix cache composes into a
  fleet-level one without any shared state. The controller-side
  activator sees text, not tokens; byte inputs hash a byte-span of the
  same nominal size under a distinct seed (documented approximation:
  preserves the shared-prefix property, never collides with token keys).

* ``ConsistentHashRing`` -- vnode consistent hashing. Adding or
  removing one replica moves only ~1/N of the keyspace (tested), so a
  scale event doesn't flush every replica's prefix cache, and
  ``candidates(key, n)`` yields the next-distinct replicas clockwise
  for power-of-two-choices spill.

* ``Router`` -- policy: affinity primary, queue/TTFT-EMA-aware second
  choice, long-prompt steering (to the prefill pool when disaggregated,
  else to the least-loaded candidate), and load shedding with a
  computed Retry-After when every candidate's TTFT estimate exceeds the
  SLO. Pure host code, no jax import -- safe inside the controller.

* ``pack_kv_packet``/``unpack_kv_packet`` -- the disaggregation wire
  format. int8 KV-quantized entries ship exactly as the engine stores
  them since PR 1: ``q`` int8 [L, P, KV, D] plus scales ``s`` f32
  LANE-ALIGNED [L, KV, Smax] (sequence on the 128-lane minor axis), so
  a handoff is a raw byte copy on both ends -- no transpose, no
  requant, and decode attends bit-identically to a local prefill.
  ``handoff_prefix`` drives a full prefill-replica -> decode-replica
  transfer between two engines and stitches ``kv-handoff`` spans into
  the obs plane (docs/OBSERVABILITY.md) under the propagated trace id.

See docs/FLEET.md for the full model.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import struct
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from kubeflow_tpu.obs import registry as obs_registry
from kubeflow_tpu.obs import timeseries as obs_timeseries
from kubeflow_tpu.obs import trace

# ---------------------------------------------------------------------------
# Affinity keys (PrefixCache chain-hash scheme)
# ---------------------------------------------------------------------------

# Must match PrefixCache.chain_hashes exactly: the router's token key IS
# the engine cache's first-block chain hash (tested against it).
PREFIX_HASH_SEED = b"kftpu-prefix"
_BYTES_HASH_SEED = b"kftpu-prefix-bytes"
DEFAULT_BLOCK = 128


def chain_hash(tokens: Sequence[int], block: int = DEFAULT_BLOCK):
    """(covered_len, hash) of the longest block-multiple prefix --
    PrefixCache.chain_hashes' last row, recomputed jax-free so the
    controller can verify packets without importing the engine."""
    n = (len(tokens) // block) * block
    h = PREFIX_HASH_SEED
    for end in range(block, n + 1, block):
        blk = np.asarray(tokens[end - block:end], np.int64).tobytes()
        h = hashlib.blake2b(h + blk, digest_size=16).digest()
    return n, h


def prefix_route_key(prompt: Union[Sequence[int], bytes, str],
                     block: int = DEFAULT_BLOCK) -> bytes:
    """16-byte affinity key for a prompt.

    Tokens: blake2b(seed + first block) -- identical to the engine
    PrefixCache's first-block chain hash for prompts >= one block, so
    router affinity granularity IS cache-entry granularity. Shorter
    prompts hash whatever tokens exist (shared short prompts still
    co-locate; the different input length keeps keys distinct).

    Text/bytes (the activator, which has no tokenizer): hash the first
    ``4 * block`` bytes under a separate seed -- ~4 chars/token keeps
    the span comparable to one token block, and a shared system-prompt
    prefix longer than that span still yields one key.
    """
    if isinstance(prompt, str):
        prompt = prompt.encode("utf-8", "surrogatepass")
    if isinstance(prompt, (bytes, bytearray)):
        span = bytes(prompt[: 4 * block])
        return hashlib.blake2b(_BYTES_HASH_SEED + span,
                               digest_size=16).digest()
    blk = np.asarray(list(prompt[:block]), np.int64).tobytes()
    return hashlib.blake2b(PREFIX_HASH_SEED + blk, digest_size=16).digest()


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------


class ConsistentHashRing:
    """Classic vnode ring over replica ids (any hashable str).

    ``candidates(key, n)`` walks clockwise from the key's point and
    returns the first n DISTINCT replicas -- candidate 0 is the affinity
    home, candidate 1 the deterministic spill target. With v vnodes per
    replica, adding one replica to an N-replica ring claims ~1/(N+1) of
    the keyspace and leaves every other key's home untouched.
    """

    def __init__(self, vnodes: int = 64) -> None:
        self.vnodes = max(1, int(vnodes))
        self._points: List[tuple] = []  # sorted (point:int, rid)
        self._nodes: set = set()

    def _vnode_points(self, rid: str):
        for v in range(self.vnodes):
            d = hashlib.blake2b(f"{rid}#{v}".encode(), digest_size=8)
            yield int.from_bytes(d.digest(), "big")

    def add(self, rid: str) -> None:
        if rid in self._nodes:
            return
        self._nodes.add(rid)
        for p in self._vnode_points(rid):
            bisect.insort(self._points, (p, rid))

    def remove(self, rid: str) -> None:
        if rid not in self._nodes:
            return
        self._nodes.discard(rid)
        self._points = [pt for pt in self._points if pt[1] != rid]

    def __len__(self) -> int:
        return len(self._nodes)

    def nodes(self) -> set:
        return set(self._nodes)

    def candidates(self, key: bytes, n: int = 2) -> List[str]:
        if not self._points:
            return []
        point = int.from_bytes(
            hashlib.blake2b(key, digest_size=8).digest(), "big"
        )
        i = bisect.bisect_right(self._points, (point, "￿"))
        out: List[str] = []
        seen: set = set()
        for j in range(len(self._points)):
            _, rid = self._points[(i + j) % len(self._points)]
            if rid not in seen:
                seen.add(rid)
                out.append(rid)
                if len(out) >= n:
                    break
        return out


def ring_diff(before: Sequence[str], after: Sequence[str],
              keys: Sequence[bytes],
              vnodes: int = 64) -> Dict[bytes, tuple]:
    """Affinity homes that a membership change actually moved.

    Builds the two rings (``before`` / ``after`` replica-id sets, same
    vnode count the Router uses) and returns ``{key: (old_home,
    new_home)}`` for exactly the keys whose primary changed. This is
    the serving-plane migration planner's input: consistent hashing
    guarantees the moved set is ~changed/N of the keyspace, and a
    simultaneous add+remove moves precisely the union of the two
    single-change victim sets -- no key bounces through a third replica
    (tested in tests/test_router.py)."""
    ra, rb = ConsistentHashRing(vnodes), ConsistentHashRing(vnodes)
    for rid in before:
        ra.add(str(rid))
    for rid in after:
        rb.add(str(rid))
    moved: Dict[bytes, tuple] = {}
    for key in keys:
        old = ra.candidates(key, 1)
        new = rb.candidates(key, 1)
        old_home = old[0] if old else None
        new_home = new[0] if new else None
        if old_home != new_home:
            moved[key] = (old_home, new_home)
    return moved


# ---------------------------------------------------------------------------
# Replica load + routing policy
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Per-replica failure-driven ejection (docs/FLEET.md, failure
    semantics).

    closed -> open after ``failure_threshold`` CONSECUTIVE failures
    (any success resets the count). open -> half-open once the current
    reset timeout elapses; half-open admits EXACTLY ONE probe request.
    The probe's success closes the breaker fully (count and backoff
    reset); its failure re-opens with the timeout doubled (capped), so
    a still-dead replica is retried at 1s, 2s, 4s ... never hammered.

    Pure host state machine, injectable clock (``now``) so the unit
    tests drive it without sleeping. Thread-compatible the way the
    Router is: single attribute ops, no cross-statement invariants.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout_s: float = 1.0,
                 backoff_factor: float = 2.0,
                 max_reset_timeout_s: float = 30.0,
                 probe_timeout_s: float = 30.0,
                 now=time.monotonic) -> None:
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self.backoff_factor = float(backoff_factor)
        self.max_reset_timeout_s = float(max_reset_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._now = now
        self.state = self.CLOSED
        self.failures = 0        # consecutive failures while closed
        self.trips = 0           # opens since the last full close
        self.opened_at = 0.0
        self.timeout_s = self.reset_timeout_s
        self.probe_inflight = False
        self.probe_started = 0.0

    def _trip(self) -> None:
        self.trips += 1
        self.timeout_s = min(
            self.reset_timeout_s
            * self.backoff_factor ** (self.trips - 1),
            self.max_reset_timeout_s,
        )
        self.opened_at = self._now()
        self.state = self.OPEN
        self.probe_inflight = False

    def allow(self) -> bool:
        """May a request be routed here now? Open breakers refuse until
        their timeout, then transition to half-open and admit exactly
        one probe (this call claims the probe slot -- the caller MUST
        report the outcome via record_success/record_failure; a probe
        with no outcome frees after probe_timeout_s)."""
        if self.state == self.CLOSED:
            return True
        now = self._now()
        if self.state == self.OPEN:
            if now < self.opened_at + self.timeout_s:
                return False
            self.state = self.HALF_OPEN
            self.probe_inflight = False
        # half-open: one probe slot.
        if self.probe_inflight:
            if now - self.probe_started > self.probe_timeout_s:
                self.probe_inflight = False  # lost outcome: free the slot
            else:
                return False
        self.probe_inflight = True
        self.probe_started = self._now()
        return True

    def record_success(self) -> None:
        self.failures = 0
        if self.state != self.CLOSED:
            self.state = self.CLOSED
            self.trips = 0
            self.timeout_s = self.reset_timeout_s
            self.probe_inflight = False

    def record_failure(self) -> None:
        if self.state == self.HALF_OPEN:
            self._trip()
            return
        if self.state == self.OPEN:
            return  # already ejected; don't extend the window
        self.failures += 1
        if self.failures >= self.failure_threshold:
            self._trip()


@dataclasses.dataclass
class ReplicaLoad:
    """Router-side view of one replica (fed by /healthz ``load`` or by
    the fleet bench's worker stats; ``in_flight`` is the router's own
    routed-not-finished count, covering the window before a request
    shows up in the replica's queue gauges)."""

    rid: str
    role: str = "mixed"  # mixed | prefill | decode
    max_slots: int = 8
    queue_depth: int = 0
    slots_active: int = 0
    in_flight: int = 0
    # Free slots on an engine that admits prompts chunk-at-a-time
    # inside decode blocks (continuous chunked prefill); 0 when the
    # engine runs the prefill barrier or never reported the gauge.
    # Long-prompt steering only fires when the affinity home lacks
    # chunk headroom -- a chunked engine absorbs the prompt without
    # stalling decode, so steering away is pure affinity loss.
    chunk_headroom: int = 0
    ttft_ema_ms: Optional[float] = None
    healthy: bool = True
    last_load_t: float = 0.0
    breaker: Optional[CircuitBreaker] = None

    def pressure(self) -> float:
        """Demand over capacity, in units of 'full engines'. 0 = idle,
        1.0 = every slot busy, >1 = queueing. The router-side in_flight
        floor covers stale gauges (burst routed between load polls)."""
        demand = max(self.queue_depth + self.slots_active, self.in_flight)
        return demand / max(1, self.max_slots)

    def est_ttft_ms(self, default_ms: float = 50.0) -> float:
        """TTFT estimate for one MORE request on this replica: the
        observed EMA scaled by queueing pressure (a request landing on a
        replica with a full queue waits ~pressure engine-drains)."""
        base = self.ttft_ema_ms if self.ttft_ema_ms else default_ms
        return base * (1.0 + max(0.0, self.pressure()))


@dataclasses.dataclass
class RouterConfig:
    block: int = DEFAULT_BLOCK
    vnodes: int = 64
    # Second choice engages only past this pressure on the primary AND
    # when the spill target is at least spill_margin less loaded --
    # affinity is worth a bounded amount of queueing, not unbounded.
    spill_threshold: float = 1.0
    spill_margin: float = 0.5
    # TTFT SLO: None disables shedding. Shed only when EVERY candidate's
    # estimate exceeds it (a loaded primary with a healthy second choice
    # spills instead of shedding).
    slo_ttft_ms: Optional[float] = None
    # Under an active SLO burn-rate alert (``set_slo_pressure(True)``
    # from the telemetry plane) the shed threshold tightens to
    # ``slo_ttft_ms * slo_pressure_factor``: once the error budget is
    # burning at alert rate, shedding earlier protects the budget of
    # the requests that ARE admitted.
    slo_pressure_factor: float = 0.5
    default_ttft_ms: float = 50.0
    # Long-prompt steering: prompts at/over this many tokens (or chars
    # for byte keys) bypass affinity -- to the prefill pool when one
    # exists, else to the least-pressured candidate. None disables.
    long_prompt_threshold: Optional[int] = None
    # Retry-After clamp (seconds) for shed responses.
    retry_after_min_s: float = 0.25
    retry_after_max_s: float = 8.0
    # Failure-driven ejection (CircuitBreaker): this many CONSECUTIVE
    # probe/request failures trip the replica out of the ring; re-entry
    # goes through exponential-backoff half-open probes.
    breaker_threshold: int = 3
    breaker_reset_s: float = 1.0
    breaker_backoff: float = 2.0
    breaker_max_reset_s: float = 30.0
    # Empty candidate set (every replica ejected/dead): shed with a
    # JITTERED Retry-After inside the clamp window so synchronized
    # clients don't thundering-herd the recovering fleet. False falls
    # back to the legacy kind="none" abstention.
    shed_on_empty: bool = True


@dataclasses.dataclass
class RouteDecision:
    kind: str                      # "direct" | "disagg" | "shed" | "none"
    replica: Optional[str] = None          # decode/serving target
    prefill_replica: Optional[str] = None  # disagg only
    spilled: bool = False          # second choice taken
    steered: bool = False          # long-prompt steering taken
    probed: bool = False           # half-open breaker probe admission
    est_ttft_ms: float = 0.0
    retry_after_s: float = 0.0     # shed only


class Router:
    """Prefix-affinity, load-aware request router over N replicas.

    Pure host-side policy: feed it replica membership (``add_replica`` /
    ``remove_replica``), load snapshots (``update_load``), and observed
    TTFTs (``observe_ttft``); ask it ``route(key, prompt_len)``. The
    caller owns transport. Thread-compatible the way the engine's stats
    are: dict/attribute ops only, no invariants spanning statements.
    """

    def __init__(self, config: Optional[RouterConfig] = None,
                 name: str = "default", now=time.monotonic) -> None:
        self.cfg = config or RouterConfig()
        self.name = name
        self._now = now
        self.ring = ConsistentHashRing(self.cfg.vnodes)
        self.replicas: Dict[str, ReplicaLoad] = {}
        self._shed_seq = 0  # jitter sequence for empty-ring sheds
        self._slo_pressure = False
        reg = obs_registry.REGISTRY
        lab = {"router": name}
        self.c_requests = reg.counter("kftpu_router_requests_total", lab)
        self.c_spilled = reg.counter("kftpu_router_spilled_total", lab)
        self.c_steered = reg.counter("kftpu_router_steered_total", lab)
        self.c_shed = reg.counter("kftpu_router_shed_total", lab)
        self.c_disagg = reg.counter("kftpu_router_disagg_total", lab)
        self.c_ejected = reg.counter("kftpu_router_ejected_total", lab)
        self.c_readmit = reg.counter("kftpu_router_readmitted_total", lab)
        self.c_probes = reg.counter("kftpu_router_probes_total", lab)
        self.g_pressure = reg.gauge("kftpu_router_slo_pressure", lab)

    # -- SLO pressure ----------------------------------------------------

    def set_slo_pressure(self, active: bool) -> None:
        """Telemetry-plane hook: an active burn-rate alert tightens the
        shed threshold; resolution restores it."""
        self._slo_pressure = bool(active)
        self.g_pressure.set(1 if self._slo_pressure else 0)

    def effective_slo_ttft_ms(self) -> Optional[float]:
        """The shed threshold route() actually applies right now."""
        if self.cfg.slo_ttft_ms is None:
            return None
        if self._slo_pressure:
            return self.cfg.slo_ttft_ms * self.cfg.slo_pressure_factor
        return self.cfg.slo_ttft_ms

    # -- membership ------------------------------------------------------

    def add_replica(self, rid: str, role: str = "mixed",
                    max_slots: int = 8) -> None:
        """Prefill-role replicas serve handoffs only: they take load
        queries but never join the ring (no decode traffic lands there
        by hash)."""
        rid = str(rid)
        cfg = self.cfg
        self.replicas[rid] = ReplicaLoad(
            rid=rid, role=role, max_slots=max(1, int(max_slots)),
            breaker=CircuitBreaker(
                failure_threshold=cfg.breaker_threshold,
                reset_timeout_s=cfg.breaker_reset_s,
                backoff_factor=cfg.breaker_backoff,
                max_reset_timeout_s=cfg.breaker_max_reset_s,
                now=self._now,
            ),
        )
        if role != "prefill":
            self.ring.add(rid)

    def remove_replica(self, rid: str) -> None:
        rid = str(rid)
        self.replicas.pop(rid, None)
        self.ring.remove(rid)

    def sync_replicas(self, live: Dict[str, dict]) -> None:
        """Reconcile membership to ``{rid: {"role", "max_slots"}}`` --
        the activator calls this with the ready-replica set before each
        route so scale events never leave the ring stale."""
        for rid in list(self.replicas):
            if rid not in live:
                self.remove_replica(rid)
        for rid, meta in live.items():
            if rid not in self.replicas:
                self.add_replica(rid, role=meta.get("role", "mixed"),
                                 max_slots=meta.get("max_slots", 8))

    # -- load signals ----------------------------------------------------

    def update_load(self, rid: str, stats: Dict[str, Any]) -> None:
        """Ingest an engine load snapshot (the ``load`` section of
        /healthz, or engine.stats() directly)."""
        rep = self.replicas.get(str(rid))
        if rep is None:
            return
        rep.queue_depth = int(stats.get("queue_depth", rep.queue_depth))
        rep.slots_active = int(stats.get("slots_active", rep.slots_active))
        rep.chunk_headroom = int(stats.get("chunk_headroom",
                                           rep.chunk_headroom))
        if stats.get("max_slots"):
            rep.max_slots = int(stats["max_slots"])
        ema = stats.get("ttft_ema_ms")
        if ema:
            rep.ttft_ema_ms = float(ema)
        rep.healthy = bool(stats.get("healthy", True))
        rep.last_load_t = time.monotonic()

    def observe_ttft(self, rid: str, ttft_ms: float,
                     alpha: float = 0.2) -> None:
        """Client-side TTFT EMA update -- keeps estimates live between
        load polls (same alpha as the engine's own ttft_ema_ms)."""
        rep = self.replicas.get(str(rid))
        if rep is None:
            return
        rep.ttft_ema_ms = (
            ttft_ms if rep.ttft_ema_ms is None
            else alpha * ttft_ms + (1 - alpha) * rep.ttft_ema_ms
        )
        # Feed the telemetry plane: the burn-rate evaluator windows
        # raw per-request TTFTs (router name == job key) against the
        # job's SLOSpec ceiling.
        obs_timeseries.STORE.add(
            "serving.ttft_ms", {"job": self.name}, float(ttft_ms))

    def start_request(self, rid: str) -> None:
        rep = self.replicas.get(str(rid))
        if rep is not None:
            rep.in_flight += 1

    def finish_request(self, rid: str,
                       ttft_ms: Optional[float] = None) -> None:
        rep = self.replicas.get(str(rid))
        if rep is not None:
            rep.in_flight = max(0, rep.in_flight - 1)
        if ttft_ms is not None:
            self.observe_ttft(rid, ttft_ms)

    # -- failure-driven ejection (CircuitBreaker) ------------------------

    def record_failure(self, rid: str) -> None:
        """One probe/request failure against ``rid``. Consecutive
        failures trip the replica's breaker; tripping removes it from
        the ring (ring re-sync: its keyspace rehomes onto survivors,
        and only its keys move -- tested ConsistentHashRing property),
        so retries and new traffic land elsewhere immediately."""
        rep = self.replicas.get(str(rid))
        if rep is None or rep.breaker is None:
            return
        was_open = rep.breaker.state == CircuitBreaker.OPEN
        rep.breaker.record_failure()
        if rep.breaker.state == CircuitBreaker.OPEN and not was_open:
            self.ring.remove(rep.rid)
            self.c_ejected.inc()
            if trace.enabled():
                trace.instant(
                    "breaker-open", plane="serving", track="router",
                    replica=rep.rid, trips=rep.breaker.trips,
                    timeout_s=round(rep.breaker.timeout_s, 3),
                )

    def record_success(self, rid: str) -> None:
        """One successful exchange with ``rid``: resets the consecutive
        failure count; a half-open probe's success closes the breaker
        fully and re-adds the replica to the ring."""
        rep = self.replicas.get(str(rid))
        if rep is None or rep.breaker is None:
            return
        was = rep.breaker.state
        rep.breaker.record_success()
        if was != CircuitBreaker.CLOSED:
            if rep.role != "prefill":
                self.ring.add(rep.rid)
            self.c_readmit.inc()
            if trace.enabled():
                trace.instant("breaker-close", plane="serving",
                              track="router", replica=rep.rid)

    def note_poll(self, rid: str, ok: bool) -> None:
        """Health-poll outcome. Failures count toward ejection exactly
        like request errors; successes only reset the consecutive count
        while the breaker is CLOSED -- a wedged engine still answers
        /healthz, so a poll success must never close an open breaker
        (only a real request's success, the half-open probe, does)."""
        rep = self.replicas.get(str(rid))
        if rep is None or rep.breaker is None:
            return
        if ok:
            if rep.breaker.state == CircuitBreaker.CLOSED:
                rep.breaker.record_success()
        else:
            self.record_failure(rid)

    def _half_open_probe(self) -> Optional[ReplicaLoad]:
        """A replica whose breaker is due for (and wins) its single
        half-open probe admission, or None. Claiming is the one-probe
        gate: a second concurrent route() gets False from allow()."""
        for rep in self.replicas.values():
            b = rep.breaker
            if (b is not None and rep.healthy and rep.role != "prefill"
                    and b.state != CircuitBreaker.CLOSED and b.allow()):
                return rep
        return None

    def _empty_shed(self) -> RouteDecision:
        """Every candidate ejected/dead: a clean shed with a Retry-After
        jittered deterministically (per-router shed sequence) across the
        clamp window -- synchronized clients get spread retry times, and
        a chaos replay still sees identical decisions."""
        cfg = self.cfg
        self._shed_seq += 1
        d = hashlib.blake2b(
            f"{self.name}|shed|{self._shed_seq}".encode(), digest_size=8
        ).digest()
        frac = int.from_bytes(d, "big") / float(1 << 64)
        retry = (cfg.retry_after_min_s
                 + frac * (cfg.retry_after_max_s - cfg.retry_after_min_s))
        self.c_shed.inc()
        return RouteDecision(kind="shed", retry_after_s=round(retry, 3))

    # -- policy ----------------------------------------------------------

    def route(self, key: bytes, prompt_len: int = 0) -> RouteDecision:
        """One routing decision; no state change beyond counters (the
        caller pairs start_request/finish_request around transport)."""
        cfg = self.cfg
        self.c_requests.inc()
        # Recovery first: a breaker due for its half-open probe gets
        # this request (exactly one -- allow() claims the single slot;
        # concurrent routes fall through to the normal candidates).
        probe = self._half_open_probe()
        if probe is not None:
            self.c_probes.inc()
            decision = RouteDecision(
                kind="direct", replica=probe.rid, probed=True,
                est_ttft_ms=probe.est_ttft_ms(cfg.default_ttft_ms),
            )
            if trace.enabled():
                trace.instant("route", plane="serving", track="router",
                              kind="direct", replica=probe.rid,
                              probed=True, spilled=False, steered=False,
                              est_ttft_ms=round(decision.est_ttft_ms, 2))
            return decision
        # Walk past unhealthy/ejected entries: the ring may momentarily
        # hold replicas whose breaker just opened (trip removes them,
        # but the breaker state is the authority), and candidates() caps
        # at the distinct-replica count anyway.
        cands = []
        for r in self.ring.candidates(key, max(2, len(self.ring))):
            rep = self.replicas.get(r)
            if (rep is not None and rep.healthy
                    and (rep.breaker is None
                         or rep.breaker.state == CircuitBreaker.CLOSED)):
                cands.append(rep)
                if len(cands) >= 2:
                    break
        if not cands:
            if not cfg.shed_on_empty:
                return RouteDecision(kind="none")
            decision = self._empty_shed()
            if trace.enabled():
                trace.instant("route", plane="serving", track="router",
                              kind="shed", replica="", spilled=False,
                              steered=False, est_ttft_ms=0.0)
            return decision
        long_prompt = (
            cfg.long_prompt_threshold is not None
            and prompt_len >= cfg.long_prompt_threshold
            # Continuous chunked prefill makes long-prompt admission
            # non-blocking: when the affinity home reports chunk
            # headroom it folds the prompt into its decode blocks a
            # chunk at a time, so the 386-tok/s stall this steering
            # guards against can't happen there -- keep the affinity
            # hit instead of shipping the request (or its KV) across
            # the fleet. Replicas that never report the gauge (barrier
            # engines, stale fleets) read 0 and steer as before.
            and cands[0].chunk_headroom <= 0
        )
        prefill_pool = [
            r for r in self.replicas.values()
            if r.role == "prefill" and r.healthy
        ]
        decision: RouteDecision
        if long_prompt and prefill_pool:
            # Disaggregated: the prompt prefills on a dedicated replica
            # (chosen by least pressure -- prefill work has no affinity
            # value, its KV ships out) and decodes on the affinity home,
            # which receives the KV packet and keeps its interactive
            # traffic's TTFT out of the long prefill's shadow.
            pre = min(prefill_pool, key=lambda r: r.pressure())
            decision = RouteDecision(
                kind="disagg", replica=cands[0].rid,
                prefill_replica=pre.rid, steered=True,
                est_ttft_ms=cands[0].est_ttft_ms(cfg.default_ttft_ms),
            )
            self.c_steered.inc()
            self.c_disagg.inc()
        elif long_prompt:
            # No prefill pool: steer the long prompt to the least-
            # pressured candidate instead of its affinity home -- a long
            # prefill monopolizes admission, and parking it on the
            # busiest replica is exactly the 386 tok/s mixed-workload
            # failure mode (SERVING_BENCH.json).
            tgt = min(cands, key=lambda r: r.pressure())
            decision = RouteDecision(
                kind="direct", replica=tgt.rid,
                steered=tgt.rid != cands[0].rid,
                est_ttft_ms=tgt.est_ttft_ms(cfg.default_ttft_ms),
            )
            if decision.steered:
                self.c_steered.inc()
        else:
            primary = cands[0]
            chosen, spilled = primary, False
            if (len(cands) > 1
                    and primary.pressure() >= cfg.spill_threshold
                    and cands[1].pressure()
                    <= primary.pressure() - cfg.spill_margin):
                chosen, spilled = cands[1], True
            decision = RouteDecision(
                kind="direct", replica=chosen.rid, spilled=spilled,
                est_ttft_ms=chosen.est_ttft_ms(cfg.default_ttft_ms),
            )
            if spilled:
                self.c_spilled.inc()
        slo_ms = self.effective_slo_ttft_ms()
        if slo_ms is not None:
            ests = [r.est_ttft_ms(cfg.default_ttft_ms) for r in cands]
            if min(ests) > slo_ms:
                # Overload everywhere the key may go: shed with a
                # Retry-After sized to the estimated excess (how long
                # the backlog needs to drain back under the SLO).
                retry = min(
                    max((min(ests) - slo_ms) / 1000.0,
                        cfg.retry_after_min_s),
                    cfg.retry_after_max_s,
                )
                self.c_shed.inc()
                decision = RouteDecision(
                    kind="shed", est_ttft_ms=min(ests),
                    retry_after_s=round(retry, 3),
                )
        if trace.enabled():
            trace.instant(
                "route", plane="serving", track="router",
                kind=decision.kind, replica=decision.replica or "",
                spilled=decision.spilled, steered=decision.steered,
                est_ttft_ms=round(decision.est_ttft_ms, 2),
            )
        return decision

    def stats(self) -> dict:
        return {
            "replicas": {
                r.rid: {
                    "role": r.role,
                    "pressure": round(r.pressure(), 3),
                    "queue_depth": r.queue_depth,
                    "slots_active": r.slots_active,
                    "in_flight": r.in_flight,
                    "ttft_ema_ms": (
                        round(r.ttft_ema_ms, 3) if r.ttft_ema_ms else 0.0
                    ),
                    "breaker": (r.breaker.state if r.breaker is not None
                                else "closed"),
                }
                for r in self.replicas.values()
            },
            "requests": self.c_requests.value,
            "spilled": self.c_spilled.value,
            "steered": self.c_steered.value,
            "shed": self.c_shed.value,
            "disagg": self.c_disagg.value,
            "ejected": self.c_ejected.value,
            "readmitted": self.c_readmit.value,
            "probes": self.c_probes.value,
        }


# ---------------------------------------------------------------------------
# Disaggregation wire format (KV handoff packets)
# ---------------------------------------------------------------------------

PACKET_MAGIC = b"KFTPKV1\n"
_HDR_LEN = struct.Struct("<I")


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 et al register through ml_dtypes (a jax dependency,
        # importable without pulling jax itself).
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def pack_kv_packet(tokens: Sequence[int], k_rows: Any, v_rows: Any, *,
                   block: int = DEFAULT_BLOCK,
                   trace_id: Optional[str] = None,
                   extra: Optional[dict] = None) -> bytes:
    """Serialize one prefix-cache entry for transport.

    ``tokens`` are the covered prompt tokens (a block multiple);
    ``k_rows``/``v_rows`` are HOST arrays exactly as the engine stores
    them -- bf16 [L, P, KV, D], or for int8 kv_quant a dict of ``q``
    int8 [L, P, KV, D] and ``s`` f32 lane-aligned [L, KV, Smax] (the
    PR 1 layout; shipped raw, no transpose). Layout:

        magic | u32 header_len | header JSON | tensor bytes, in order

    The header carries the PrefixCache chain hash of ``tokens`` so the
    importer proves token-exact prefix identity before touching its
    cache, plus the propagated trace id for cross-process span
    stitching.
    """
    n_cov, h = chain_hash(tokens, block)
    if n_cov != len(tokens) or n_cov == 0:
        raise ValueError(
            f"tokens must be a nonzero multiple of block={block}, "
            f"got {len(tokens)}"
        )
    tensors: List[dict] = []
    blobs: List[bytes] = []

    def _add(tname: str, arr: Any) -> None:
        arr = np.ascontiguousarray(arr)
        tensors.append({"name": tname, "dtype": str(arr.dtype),
                        "shape": list(arr.shape)})
        blobs.append(arr.tobytes())

    _add("tokens", np.asarray(list(tokens), np.int32))
    quantized = isinstance(k_rows, dict)
    for prefix, rows in (("k", k_rows), ("v", v_rows)):
        if isinstance(rows, dict):
            _add(prefix + ".q", rows["q"])
            _add(prefix + ".s", rows["s"])
        else:
            _add(prefix, rows)
    payload = b"".join(blobs)
    header = {
        "version": 2,
        "block": block,
        "plen": len(tokens),
        "layout": ("int8-lane[L,KV,Smax]" if quantized
                   else "bf16[L,P,KV,D]"),
        "chain_hash": h.hex(),
        # Whole-payload checksum: the chain hash proves token identity,
        # this proves the TENSOR bytes arrived intact (a flipped KV byte
        # would otherwise import cleanly and poison every later hit).
        "payload_blake2b": hashlib.blake2b(
            payload, digest_size=16).hexdigest(),
        "trace_id": trace_id or trace.trace_id() or "",
        "tensors": tensors,
    }
    if extra:
        header.update(extra)
    hdr = json.dumps(header).encode()
    return b"".join([PACKET_MAGIC, _HDR_LEN.pack(len(hdr)), hdr, payload])


def unpack_kv_packet(buf: bytes) -> dict:
    """Inverse of pack_kv_packet. Fails CLOSED on anything short of a
    bit-exact packet -- bad magic, a header length pointing outside the
    buffer, truncated/oversized payload, a chain-hash mismatch on the
    tokens, or a payload-checksum mismatch on the tensor bytes (a wrong
    prefix or flipped KV byte in a decode replica's cache would
    silently poison every later hit). Raises before ANY array reaches
    the caller, so a partial cache insert is impossible."""
    if len(buf) < len(PACKET_MAGIC) + _HDR_LEN.size:
        raise ValueError("truncated KV handoff packet")
    if buf[:len(PACKET_MAGIC)] != PACKET_MAGIC:
        raise ValueError("not a KV handoff packet (bad magic)")
    off = len(PACKET_MAGIC)
    (hlen,) = _HDR_LEN.unpack_from(buf, off)
    off += _HDR_LEN.size
    if hlen <= 0 or off + hlen > len(buf):
        raise ValueError(
            f"KV packet header length {hlen} exceeds buffer ({len(buf)}B)"
        )
    try:
        header = json.loads(buf[off:off + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"KV packet header is not valid JSON: {e}")
    off += hlen
    payload_start = off
    # Validate declared sizes against the actual buffer BEFORE touching
    # any bytes: a lying header must not drive reads (or giant
    # allocations) past the payload.
    sizes: List[int] = []
    total = 0
    for t in header.get("tensors", []):
        dt = _np_dtype(t["dtype"])
        n = dt.itemsize
        for s in t["shape"]:
            if int(s) < 0:
                raise ValueError("KV packet tensor shape is negative")
            n *= int(s)
        sizes.append(n)
        total += n
    if payload_start + total != len(buf):
        raise ValueError(
            f"KV packet payload length mismatch: header declares "
            f"{total}B, buffer carries {len(buf) - payload_start}B"
        )
    arrays: Dict[str, np.ndarray] = {}
    for t, n in zip(header["tensors"], sizes):
        dt = _np_dtype(t["dtype"])
        arr = np.frombuffer(buf[off:off + n], dtype=dt)
        arrays[t["name"]] = arr.reshape(t["shape"])
        off += n
    tokens = arrays["tokens"].tolist()
    n_cov, h = chain_hash(tokens, header["block"])
    if n_cov != header["plen"] or h.hex() != header["chain_hash"]:
        raise ValueError("KV packet chain-hash mismatch")
    digest = hashlib.blake2b(buf[payload_start:], digest_size=16).hexdigest()
    if digest != header.get("payload_blake2b"):
        raise ValueError("KV packet payload checksum mismatch")
    if "k.q" in arrays:
        k_rows: Any = {"q": arrays["k.q"], "s": arrays["k.s"]}
        v_rows: Any = {"q": arrays["v.q"], "s": arrays["v.s"]}
    else:
        k_rows, v_rows = arrays["k"], arrays["v"]
    return {"tokens": tokens, "plen": header["plen"], "k": k_rows,
            "v": v_rows, "block": header["block"],
            "layout": header["layout"],
            "trace_id": header.get("trace_id") or None, "header": header}


def handoff_prefix(src_engine: Any, dst_engine: Any,
                   prompt: Sequence[int], *,
                   timeout: float = 120.0) -> Optional[dict]:
    """Prefill ``prompt`` on ``src_engine`` and hand its KV prefix to
    ``dst_engine`` through the wire format (full pack -> bytes ->
    unpack round trip, same path a cross-process transport takes).
    Returns {"plen", "bytes"} or None when the prompt is under one
    block (nothing to hand off -- the decode replica just prefills).
    """
    block = src_engine.prefix_cache.block
    with trace.span("kv-handoff", plane="serving", track="router",
                    prompt_len=len(prompt)):
        plen = src_engine.ensure_prefix(prompt, timeout=timeout)
        if not plen:
            return None
        pkt = src_engine.export_prefix(prompt)
        if pkt is None:
            return None
        buf = pack_kv_packet(pkt["tokens"], pkt["k"], pkt["v"],
                             block=block)
        got = unpack_kv_packet(buf)
        dst_engine.import_prefix(got)
        trace.instant("kv-handoff.bytes", plane="serving",
                      track="router", plen=plen, nbytes=len(buf))
        return {"plen": plen, "bytes": len(buf)}
