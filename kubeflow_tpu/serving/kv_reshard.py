"""Serving-plane live reshard: one elasticity story for both planes.

PR 8 made *training* resizes cheap by planning and executing in-memory
state movement (parallel/reshard.py); this module points the same
plan/execute/feasibility core at serving-plane state, in two moves:

1. **TP resplit** (`resplit_engine_tp`): a live engine's weights,
   in-place KV cache (incl. int8 lane-aligned scales), and resident
   prefix-cache entries move onto a different ``tensor``-axis mesh
   through `plan_reshard`/`execute_plan` -- same d2d/host/noop leaf
   modes, same `reshard_peak_bytes` feasibility gate. The decode loop
   is quiesced at a block boundary first and resumed after the jit
   dispatch closures are rebuilt, so generation continues bit-exactly:
   host scheduler state (slots, lengths, RNG chains, in-flight
   requests) never moves, only device buffers do.

2. **Prefix migration** (`plan_prefix_migration` / `migrate_prefixes`):
   when fleet membership changes, the router's `ring_diff` names
   exactly the affinity keys whose home moved; the hottest cache
   entries behind those keys ship donor -> new-home over the existing
   ``/v2/.../prefix/export|import`` wire (PR 7's pack/unpack_kv_packet
   format), so an autoscale event stops being a fleet-wide cold start.

The manifest format (one row per shipped entry)::

    {"key": <route-key hex>, "tokens": [...], "plen": int,
     "bytes": int, "src": rid, "dst": rid, "tick": int}

Every executed move emits a ``kv.migrate`` span whose open-args carry
(src, dst, bytes, plen) -- `obs.trace.plane_summaries` rolls these up
into the kv-migration row `kftpu trace dump` prints.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from kubeflow_tpu.obs import trace
from kubeflow_tpu.parallel.reshard import (
    InfeasibleReshardError,
    execute_plan,
    plan_reshard,
)
from kubeflow_tpu.serving.router import (
    DEFAULT_BLOCK,
    prefix_route_key,
    ring_diff,
)

__all__ = [
    "resplit_engine_tp",
    "plan_prefix_migration",
    "migrate_prefixes",
    "InfeasibleReshardError",
]


# ---------------------------------------------------------------------------
# (1) Live TP resplit of an engine's device state
# ---------------------------------------------------------------------------


def _prefix_entry_shardings(mesh, entry_kv: Any):
    """Dst shardings for one prefix entry's k or v rows.

    Entries store EXTRACTED rows: bf16 [L, plen, KV, D] (KV heads at
    axis 2), or under int8 kv_quant a {"q": [L, plen, KV, D] int8,
    "s": [L, KV, plen] f32} dict -- note the scale's KV axis sits at
    axis 1 in extracted (row) form, unlike the lane-aligned in-place
    cache slab. Heads shard over ``tensor`` exactly as the cache they
    restore into, so restore's scatter stays shard-local.
    """
    P = jax.sharding.PartitionSpec
    rows = jax.sharding.NamedSharding(mesh, P(None, None, "tensor", None))
    if isinstance(entry_kv, dict):
        scales = jax.sharding.NamedSharding(mesh, P(None, "tensor", None))
        return {"q": rows, "s": scales}
    return rows


def resplit_engine_tp(engine, tensor_parallel: int, *, devices=None,
                      hbm_bytes: Optional[int] = None) -> dict:
    """Move a live engine onto a ``tensor_parallel``-way mesh in place.

    Quiesces the decode loop at a block boundary, plans the transfer of
    {weights, cache_k, cache_v, prefix entries} onto the new mesh with
    `plan_reshard` (feasibility-gated by ``hbm_bytes``), executes it
    with donation (the old shards free as the new ones land), swaps the
    engine's device state, rebuilds the jit dispatch closures, and
    resumes. Raises InfeasibleReshardError -- with the engine resumed
    on its ORIGINAL mesh, untouched -- when the plan doesn't fit.

    Returns the plan summary plus resplit bookkeeping (tensor_parallel,
    prefix_entries moved, seconds).
    """
    from kubeflow_tpu.serving.engine import (  # circular-at-import-time
        _validate_tp,
        make_tp_mesh,
        tp_cache_sharding,
        tp_kv_scale_sharding,
        tp_weight_shardings,
    )

    cfg = engine.cfg
    _validate_tp(cfg, tensor_parallel)
    dst_mesh = make_tp_mesh(tensor_parallel, devices)

    t0 = time.perf_counter()
    was_running = engine.quiesce("tp-resplit")
    try:
        # State pytree: everything device-resident that must land on
        # the new mesh. Prefix entries ride along keyed by their full
        # chain hash so the moved buffers can be written back in place.
        pc = engine.prefix_cache
        prefix_state: Dict[str, dict] = {}
        if pc is not None:
            for full, entry in pc.entries.items():
                prefix_state[full.hex()] = {
                    "k": entry["k"], "v": entry["v"],
                }
        state = {
            "weights": engine.weights,
            "cache_k": engine.cache_k,
            "cache_v": engine.cache_v,
            "prefix": prefix_state,
        }

        cache_sh = tp_cache_sharding(dst_mesh)
        if isinstance(engine.cache_k, dict):  # int8 kv_quant slabs
            scale_sh = tp_kv_scale_sharding(dst_mesh)
            cache_shardings: Any = {"q": cache_sh, "s": scale_sh}
        else:
            cache_shardings = cache_sh
        shardings = {
            "weights": tp_weight_shardings(dst_mesh, engine.weights),
            "cache_k": cache_shardings,
            "cache_v": cache_shardings,
            "prefix": {
                hx: {"k": _prefix_entry_shardings(dst_mesh, kv["k"]),
                     "v": _prefix_entry_shardings(dst_mesh, kv["v"])}
                for hx, kv in prefix_state.items()
            },
        }

        with trace.span("kv.resplit", plane="serving", track="kv-reshard",
                        tensor_parallel=int(tensor_parallel)) as sp:
            plan = plan_reshard(state, dst_mesh, dst_shardings=shardings,
                                hbm_bytes=hbm_bytes)
            # Infeasible plans raise out of execute_plan before any
            # buffer moves; the finally below resumes on the old mesh.
            new_state = execute_plan(state, plan, donate=True)
            sp.annotate(bytes_moved=plan.bytes_moved,
                        transition=plan.transition)

        engine.mesh = dst_mesh
        engine.weights = new_state["weights"]
        engine.cache_k = new_state["cache_k"]
        engine.cache_v = new_state["cache_v"]
        if pc is not None:
            for full, entry in pc.entries.items():
                moved = new_state["prefix"][full.hex()]
                entry["k"] = moved["k"]
                entry["v"] = moved["v"]
        # Old compiled programs close over the old shardings; rebuild
        # every dispatch closure against the new mesh before resuming.
        engine._build_dispatch()
    finally:
        engine.resume(was_running)

    out = plan.summary()
    out.update({
        "tensor_parallel": int(tensor_parallel),
        "prefix_entries": len(prefix_state),
        "seconds": time.perf_counter() - t0,
    })
    return out


# ---------------------------------------------------------------------------
# (2) Fleet prefix-cache migration on ring changes
# ---------------------------------------------------------------------------


def plan_prefix_migration(before: Sequence[str], after: Sequence[str],
                          inventories: Dict[str, List[dict]], *,
                          block: int = DEFAULT_BLOCK,
                          vnodes: int = 64,
                          top_k: int = 0,
                          pressures: Optional[Dict[str, float]] = None,
                          ) -> dict:
    """Turn a ring membership change into a migration manifest.

    ``inventories`` maps replica id -> that replica's hottest-first
    prefix inventory (engine.prefix_inventory rows: hash/plen/bytes/
    tick/tokens). Only entries whose affinity key the ring ACTUALLY
    moved (router.ring_diff) and whose new home doesn't already hold
    them are shipped; when several replicas hold copies of one entry
    the least-pressured donor wins (``pressures``: rid -> load, lower
    is freer). ``top_k`` > 0 caps moves per recipient to its hottest K
    -- the respawn re-warm path uses this so a returning replica warms
    with its best entries first instead of a full cache transfer.

    Returns ``{"moves": [manifest rows], "moved_keys": n,
    "total_bytes": n}`` with moves ordered hottest-first.
    """
    # Route key per candidate entry: hottest row wins for ordering,
    # but every replica holding a copy stays a donor candidate. Entries
    # without tokens (pre-PR-14 inventories) can't be re-keyed -> skip.
    hottest: Dict[bytes, dict] = {}  # route key -> hottest inventory row
    holders: Dict[bytes, Dict[str, dict]] = {}  # key -> rid -> row
    for rid, rows in inventories.items():
        for row in rows:
            toks = row.get("tokens") or []
            if len(toks) < block:
                continue  # under one block: never cached, never routed
            key = prefix_route_key(toks, block)
            holders.setdefault(key, {})[rid] = row
            best = hottest.get(key)
            if best is None or row.get("tick", 0) > best.get("tick", 0):
                hottest[key] = row

    moved = ring_diff(before, after, list(hottest.keys()), vnodes)

    per_dst: Dict[str, int] = {}
    moves: List[dict] = []
    ordered = sorted(hottest.items(),
                     key=lambda kv: -kv[1].get("tick", 0))
    for key, row in ordered:
        if key not in moved:
            continue
        _, new_home = moved[key]
        who = holders[key]
        if new_home is None or new_home in who:
            continue  # nowhere to go / recipient already holds a copy
        if top_k > 0 and per_dst.get(new_home, 0) >= top_k:
            continue
        # Donor: least-pressured replica holding the entry (any holder
        # serves identical bytes -- a hit implies token-exact equality).
        if pressures:
            src = min(who, key=lambda r: pressures.get(r, float("inf")))
        else:
            src = next(iter(sorted(who)))
        per_dst[new_home] = per_dst.get(new_home, 0) + 1
        moves.append({
            "key": key.hex(),
            "tokens": list(row.get("tokens", ())),
            "plen": int(row.get("plen", 0)),
            "bytes": int(row.get("bytes", 0)),
            "tick": int(row.get("tick", 0)),
            "src": src,
            "dst": new_home,
        })
    return {
        "moves": moves,
        "moved_keys": len(moved),
        "total_bytes": sum(m["bytes"] for m in moves),
    }


def migrate_prefixes(manifest: dict,
                     export_fn: Callable[[str, List[int]], Optional[bytes]],
                     import_fn: Callable[[str, bytes], int]) -> dict:
    """Execute a migration manifest over caller-supplied transports.

    ``export_fn(src_rid, tokens)`` returns the packed KV packet bytes
    (router wire format) or None on a donor-side miss; ``import_fn(
    dst_rid, packet)`` lands it and returns the covered length. Each
    shipped entry runs under a ``kv.migrate`` span carrying src/dst/
    bytes/plen, which the trace plane summary aggregates. A failed or
    missing export skips that entry (counted), never aborts the batch:
    migration is an optimization, the cold path stays correct.
    """
    t0 = time.perf_counter()
    shipped = 0
    failed = 0
    total_bytes = 0
    pairs: Dict[str, int] = {}
    for move in manifest.get("moves", ()):
        src, dst = move["src"], move["dst"]
        with trace.span("kv.migrate", plane="serving", track="kv-migrate",
                        src=str(src), dst=str(dst),
                        bytes=int(move.get("bytes", 0)),
                        plen=int(move.get("plen", 0))) as sp:
            try:
                packet = export_fn(src, list(move.get("tokens", ())))
                if not packet:
                    failed += 1
                    sp.annotate(outcome="miss")
                    continue
                covered = import_fn(dst, packet)
            except Exception as exc:  # transport errors skip, not abort
                failed += 1
                sp.annotate(outcome="error", error=type(exc).__name__)
                continue
            shipped += 1
            total_bytes += int(move.get("bytes", 0)) or len(packet)
            pair = f"{src}->{dst}"
            pairs[pair] = pairs.get(pair, 0) + 1
            sp.annotate(outcome="ok", covered=int(covered or 0))
    return {
        "shipped": shipped,
        "failed": failed,
        "bytes": total_bytes,
        "pairs": pairs,
        "seconds": time.perf_counter() - t0,
    }
