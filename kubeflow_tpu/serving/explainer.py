"""Explainer component base (KServe explainer equivalent, S1/S2).

The third ISVC component: its replicas receive ``:explain`` requests,
call the PREDICTOR through the activator (so predictor scale-from-zero
still applies), and return attributions. Subclass and override
``explain_instance`` for custom explainers:

    from kubeflow_tpu.serving.explainer import ExplainerModel
    from kubeflow_tpu.serving.runtimes.common import serve_main

    class MyExplainer(ExplainerModel):
        def explain_instance(self, instance):
            preds = self.predict([instance])       # predictor call
            return {"attributions": my_method(instance, preds[0])}

    if __name__ == "__main__":
        raise SystemExit(serve_main(
            lambda name, path, opts: MyExplainer(name, options=opts)))

The controller injects ``KFTPU_PREDICTOR_URL``/``KFTPU_PREDICTOR_MODEL``
into explainer replicas, exactly as for transformers
(serving.transformer.TransformerModel supplies the proxying ``predict``).
"""

from __future__ import annotations

from typing import Any, List, Sequence

from kubeflow_tpu.serving.model import InferenceError
from kubeflow_tpu.serving.transformer import TransformerModel


class ExplainerModel(TransformerModel):
    """Base explainer: predictor proxying inherited from TransformerModel
    (its ``predict`` forwards a batch to the predictor component)."""

    def explain(self, instances: Sequence[Any]) -> List[Any]:
        return [self.explain_instance(i) for i in instances]

    def explain_instance(self, instance: Any) -> Any:
        raise InferenceError(
            f"explainer {self.name} does not implement explain_instance",
            501,
        )


def _scalar(pred: Any) -> float:
    """Reduce one prediction to a scalar score for attribution math."""
    if isinstance(pred, bool):
        return float(pred)
    if isinstance(pred, (int, float)):
        return float(pred)
    if isinstance(pred, list) and pred:
        # Probability vector / multi-output: score = first component
        # unless a binary-proba pair, where index 1 (positive class) is
        # conventional.
        vals = [v for v in pred if isinstance(v, (int, float))]
        if len(vals) == 2:
            return float(vals[1])
        if vals:
            return float(vals[0])
    if isinstance(pred, dict):
        for k in ("score", "probability", "value", "prediction"):
            if isinstance(pred.get(k), (int, float)):
                return float(pred[k])
    raise InferenceError(
        "ablation explainer needs scalar-reducible predictions "
        f"(number, vector, or dict with score/probability), got "
        f"{type(pred).__name__}", 400,
    )


class AblationExplainer(ExplainerModel):
    """Bundled feature-ablation explainer (the default when an ISVC's
    explainer has no custom process).

    For a numeric feature-vector instance, attribution of feature i =
    score(x) - score(x with feature i set to the baseline value). All
    ablations go to the predictor in ONE batch per instance. Model
    agnostic -- works over any predictor whose outputs reduce to a
    scalar (sklearn/xgboost/lightgbm regressors and classifiers, custom
    numeric models).

    The bundled spawn (explainer: {} in an ISVC) runs with the default
    baseline 0.0; to configure options, run this runtime as a custom
    process instead:
        explainer:
          custom:
            entrypoint: kubeflow_tpu.serving.runtimes.explainer_server
            args: ["--model-name", "m", "--options-json",
                   '{"baseline": 1.0}']
    """

    def __init__(self, name, path=None, options=None) -> None:
        super().__init__(name, path, options)
        self.baseline = float(self.options.get("baseline", 0.0))

    def explain_instance(self, instance: Any) -> Any:
        feats = instance
        if isinstance(instance, dict) and "features" in instance:
            feats = instance["features"]
        if not (isinstance(feats, list) and feats
                and all(isinstance(v, (int, float)) for v in feats)):
            raise InferenceError(
                "ablation explainer expects a numeric feature vector "
                '(instance = [..] or {"features": [..]})', 400,
            )
        batch = [list(feats)]
        for i in range(len(feats)):
            ablated = list(feats)
            ablated[i] = self.baseline
            batch.append(ablated)
        scores = [_scalar(p) for p in self.predict(batch)]
        base = scores[0]
        return {
            "base_value": base,
            "attributions": [base - s for s in scores[1:]],
        }
