"""Transformer component base (KServe transformer equivalent, S2/S4).

A transformer is its own server process fronting the predictor: it
receives the inference request, applies ``preprocess`` per instance,
forwards the batch to the predictor THROUGH the activator (so predictor
scale-from-zero still works), and applies ``postprocess`` per output.

Write one by subclassing and serving it as the ISVC's transformer
``custom`` process:

    from kubeflow_tpu.serving.transformer import TransformerModel
    from kubeflow_tpu.serving.runtimes.common import serve_main

    class MyTransformer(TransformerModel):
        def preprocess(self, instance):
            return instance["text"].lower()
        def postprocess(self, output):
            return {"clean": output}

    if __name__ == "__main__":
        raise SystemExit(serve_main(
            lambda name, path, opts: MyTransformer(name, options=opts)))

The controller injects ``KFTPU_PREDICTOR_URL`` (activator ingress) and
``KFTPU_PREDICTOR_MODEL`` into transformer replicas.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.serving.model import InferenceError, Model


class TransformerModel(Model):
    def __init__(self, name: str, path: Optional[str] = None,
                 options: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(name)
        self.options = options or {}
        self.predictor_url = (
            self.options.get("predictor_url")
            or os.environ.get("KFTPU_PREDICTOR_URL")
        )
        self.predictor_model = (
            self.options.get("predictor_model")
            or os.environ.get("KFTPU_PREDICTOR_MODEL")
            or name
        )
        self.timeout = float(self.options.get("predictor_timeout", 300.0))

    def load(self) -> None:
        if not self.predictor_url:
            raise InferenceError(
                "transformer needs KFTPU_PREDICTOR_URL (set by the ISVC "
                "controller) or options.predictor_url", 500,
            )
        self.ready = True

    def unload(self) -> None:
        self.ready = False

    # predict == proxy the (already preprocessed) batch to the predictor.
    # Runs in the batcher's executor thread, so sync urllib is fine.
    def predict(self, instances: Sequence[Any]) -> List[Any]:
        url = (
            f"{self.predictor_url}/v1/models/"
            f"{self.predictor_model}:predict"
        )
        req = urllib.request.Request(
            url,
            data=json.dumps({"instances": list(instances)}).encode(),
            headers={
                "Content-Type": "application/json",
                # Pin to the predictor component or the activator would
                # route us back to the transformer (a loop).
                "X-Kftpu-Component": "predictor",
            },
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                body = json.loads(r.read())
        except urllib.error.HTTPError as e:
            raise InferenceError(
                f"predictor returned {e.code}: {e.read()[:200]!r}", 502
            )
        except OSError as e:
            raise InferenceError(f"predictor unreachable: {e}", 502)
        preds = body.get("predictions")
        if not isinstance(preds, list) or len(preds) != len(instances):
            got = (len(preds) if isinstance(preds, list)
                   else type(preds).__name__)
            raise InferenceError(
                f"predictor returned {got} predictions for "
                f"{len(instances)} instances", 502,
            )
        return preds
