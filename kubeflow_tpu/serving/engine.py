"""TPU generation engine: jitted prefill/decode with continuous batching.

The serving-side counterpart of models/llama.py (which owns the training
forward). The reference's GPU LLM path is huggingfaceserver+vLLM (SURVEY.md
3.3 S5); the TPU-native replacement is built around what XLA wants:

- **Static shapes everywhere.** The KV cache is a fixed [L, B, Smax, KV, D]
  buffer; prompts pad to a small set of prefill buckets, so there are
  O(#buckets) compiles, not O(#lengths). Decode is one fixed-shape program.
- **Slot-based continuous batching.** New requests prefill into a free
  cache slot while other slots keep decoding; one decode step advances all
  active slots (vLLM's iteration-level scheduling, minus paging -- slab
  slots beat paged KV under XLA because dynamic gather/scatter of pages
  defeats fusion; Smax bounds the slab).
- **Donated cache buffers.** decode/insert donate the cache so XLA updates
  it in place in HBM -- no per-token cache copies.
- **Layer-stacked params + lax.scan** over layers: mirrors the training
  model's nn.scan layout, so orbax training checkpoints drop straight in;
  one compiled layer body.

Weight math reimplements the Llama forward as pure functions over the
training param pytree (scan layout) rather than threading a cache through
linen -- inference wants explicit state, not module state.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from concurrent.futures import Future
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.llama import (
    LlamaConfig,
    PRESETS,
    Llama,
    rope_frequencies,
)

logger = logging.getLogger(__name__)


def default_buckets(max_seq: int) -> tuple[int, ...]:
    out, b = [], 32
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


def _pow2_bucket(n: int) -> int:
    """Smallest power of 2 >= n (jit-compile key bucketing for row counts)."""
    b = 1
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Pure forward math over the training param pytree (scan layout).
# ---------------------------------------------------------------------------


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), -1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def _rope(x, freqs, positions):
    # x [B,S,H,D]; positions [B,S]; freqs [Smax, D/2] fp32.
    f = freqs[positions]  # [B,S,D/2]
    cos = jnp.cos(f)[:, :, None, :]
    sin = jnp.sin(f)[:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., 0::2], x32[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _gqa_attend(q, k, v, mask):
    """q [B,S,N,D] over k/v [B,T,KV,D]; mask [B,S,T] True=visible."""
    b, s, n, d = q.shape
    kv = k.shape[2]
    q = q.reshape(b, s, kv, n // kv, d)
    scores = jnp.einsum("bskgd,btkd->bkgst", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(d)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, n, d)


def _cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )


def pack_weights(params: dict, cfg: LlamaConfig, cast: bool = True) -> dict:
    """params: the ``{"params": ...}`` pytree from Llama.init / orbax
    restore (scan layout required), flax metadata already unboxed.

    Returns a plain-dict pytree so it can be a jit *argument* -- closing
    over multi-GB weights would bake them into the jaxpr as constants.

    ``cast=False`` returns the reorganized tree with leaves UNTOUCHED (no
    device ops): the tensor-parallel path places each leaf sharded first
    and casts on-mesh, so the full tree is never materialized on one
    device (config #5's 8B on a 16 GiB v5e-4 would OOM otherwise).
    """

    p = params["params"] if "params" in params else params
    if "layers" not in p:
        raise ValueError("engine requires scan_layers=True checkpoints")
    out = {
        "embed": p["embed"]["embedding"],                      # [V, H]
        "final_scale": p["final_norm"]["scale"],
        "lm_head": p["lm_head"]["kernel"],                     # [H, V]
        "layers": p["layers"]["layer"],                        # leaves [L, ...]
    }
    return _cast_packed(out, cfg) if cast else out


def _cast_packed(w: dict, cfg: LlamaConfig) -> dict:
    """Serving dtypes for a packed tree: activations-dtype everywhere,
    except norm scales and the MoE router in f32. Router weights route
    DISCRETELY (top-k): a bf16 rounding can flip a near-tie to a
    different expert than training chose, an O(1) output change; the
    [L, H, E] router is tiny, so f32 costs nothing."""
    dtype = jnp.dtype(cfg.dtype)
    layers = _cast(w["layers"], dtype)
    if "moe" in layers:
        layers = dict(layers)
        layers["moe"] = dict(layers["moe"])
        layers["moe"]["router"] = w["layers"]["moe"]["router"].astype(
            jnp.float32
        )
    return {
        "embed": _cast(w["embed"], dtype),
        "final_scale": w["final_scale"].astype(jnp.float32),
        "lm_head": _cast(w["lm_head"], dtype),
        "layers": layers,
    }


def _moe_ffn(cfg: LlamaConfig, m: dict, h):
    """MoE FFN for inference: compute every expert densely, weight by the
    renormalized top-k router probabilities.

    No capacity, no drops -- capacity is a training-throughput artifact;
    at serving batch sizes the E/k extra FFN FLOPs are cheaper than
    gather/scatter of per-token expert weights, and the result is exact
    (matches the training layer whenever training dropped nothing).
    """
    e, k = cfg.n_experts, cfg.experts_per_token
    logits = jnp.einsum(
        "bsh,he->bse", h.astype(jnp.float32),
        m["router"].astype(jnp.float32),
    )
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                    # [B,S,k]
    topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    w_e = jnp.zeros_like(probs)                             # [B,S,E]
    for j in range(k):
        w_e = w_e + jax.nn.one_hot(topi[..., j], e) * topv[..., j:j + 1]
    gate = jnp.einsum("bsh,ehi->bsei", h, m["gate_proj"])
    up = jnp.einsum("bsh,ehi->bsei", h, m["up_proj"])
    act = jax.nn.silu(gate) * up
    out = jnp.einsum("bsei,eih->bseh", act, m["down_proj"])
    return jnp.einsum("bse,bseh->bsh", w_e.astype(h.dtype), out)


def _ffn(cfg: LlamaConfig, lp: dict, h):
    if "moe" in lp:
        return _moe_ffn(cfg, lp["moe"], h)
    mlp = lp["mlp"]
    gate = jnp.einsum("bsh,hi->bsi", h, mlp["gate_proj"]["kernel"])
    up = jnp.einsum("bsh,hi->bsi", h, mlp["up_proj"]["kernel"])
    return jnp.einsum("bsi,ih->bsh", jax.nn.silu(gate) * up,
                      mlp["down_proj"]["kernel"])


def _layer_forward(cfg: LlamaConfig, lp: dict, x, freqs, positions, mask):
    """One decoder layer, self-attention over the current tokens only (the
    prefill path; decode attends over the cache, see _decode). Returns
    (x, k, v) with k/v the current tokens' cache rows."""

    attn = lp["attn"]
    h = _rms(x, lp["attn_norm"]["scale"], cfg.norm_eps)
    q = jnp.einsum("bsh,hnd->bsnd", h, attn["q_proj"]["kernel"])
    k = jnp.einsum("bsh,hnd->bsnd", h, attn["k_proj"]["kernel"])
    v = jnp.einsum("bsh,hnd->bsnd", h, attn["v_proj"]["kernel"])
    q = _rope(q, freqs, positions)
    k = _rope(k, freqs, positions)
    out = _gqa_attend(q, k, v, mask)
    out = jnp.einsum("bsnd,ndh->bsh", out, attn["o_proj"]["kernel"])
    x = x + out
    h = _rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps)
    return x + _ffn(cfg, lp, h), k, v


def _prefill(cfg: LlamaConfig, w: dict, tokens, lengths):
    """Causal self-attention over a BATCH of padded prompts [K, S].

    Prefilling K admitted requests in one program amortizes both the
    per-dispatch host->device roundtrip and the MXU's preference for
    bigger batches over the serial [1, S] case. Returns
    (next_token_logits [K, V], k_seq, v_seq [L, K, S, KV, D]).
    """

    k_rows, s = tokens.shape
    positions = jnp.arange(s)[None, :]
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = w["embed"][tokens]
    causal = jnp.tril(jnp.ones((s, s), bool))[None]

    def body(x, lp):
        x, k, v = _layer_forward(cfg, lp, x, freqs, positions, causal)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, w["layers"])
    x = _rms(x, w["final_scale"], cfg.norm_eps)
    # Logits only for each row's last real token (lengths[k]-1).
    last = x[jnp.arange(k_rows), lengths - 1]  # [K, H]
    logits = (last.astype(jnp.float32) @ w["lm_head"].astype(jnp.float32))
    return logits, ks, vs


def _insert(cache_k, cache_v, k_seq, v_seq, slots):
    """Write K prefilled sequences into cache slots ``slots`` [K].

    cache [L,B,Smax,KV,D]; k_seq [L,K,S,KV,D] with S <= Smax (the
    prefill bucket). Donated buffers; one scatter per cache instead of
    K dynamic-update dispatches. Dummy rows (K padded up to its bucket)
    carry an out-of-range slot index and are DROPPED by the scatter, so
    every input keeps its bucketed shape — compile count stays
    O(K-buckets x len-buckets), not O(max_slots x len-buckets)."""

    s = k_seq.shape[2]
    return (
        cache_k.at[:, slots, :s].set(k_seq, mode="drop"),
        cache_v.at[:, slots, :s].set(v_seq, mode="drop"),
    )


def _decode(cfg: LlamaConfig, w: dict, cache_k, cache_v, tokens, lengths):
    """One decode step for all slots.

    tokens [B] (last sampled token per slot), lengths [B] (tokens already
    in cache; the new token's position). Returns (logits [B, V], caches).
    """

    # NOTE (measured 2026-07-30): bounding the attended span to a bucket
    # of the longest active length (attend ck[:, :klen]) REGRESSES ~5x on
    # v5e -- the slice of the scan-carried cache materializes as a copy
    # per layer per step instead of fusing into the attention reads,
    # dwarfing the bandwidth it saves. Full-span attention + mask is the
    # fast path under XLA; don't re-try without a Pallas decode kernel
    # that indexes the cache directly.
    b = tokens.shape[0]
    smax = cache_k.shape[2]
    positions = lengths[:, None]  # [B,1]
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = w["embed"][tokens][:, None, :]  # [B,1,H]
    # Visible: key position <= query position. Everything earlier in the
    # slot was written by the current occupant, so this is exact.
    mask = jnp.arange(smax)[None, None, :] <= positions[:, :, None]  # [B,1,Smax]
    batch_idx = jnp.arange(b)[:, None]

    def body(carry, layer):
        x = carry
        lp, ck, cv = layer
        # Write current k/v into the cache *then* attend over it.
        h = _rms(x, lp["attn_norm"]["scale"], cfg.norm_eps)
        q = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["q_proj"]["kernel"])
        k = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["k_proj"]["kernel"])
        v = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["v_proj"]["kernel"])
        q = _rope(q, freqs, positions)
        k = _rope(k, freqs, positions)
        ck = ck.at[batch_idx, positions].set(k)
        cv = cv.at[batch_idx, positions].set(v)
        out = _gqa_attend(q, ck, cv, mask)
        out = jnp.einsum("bsnd,ndh->bsh", out, lp["attn"]["o_proj"]["kernel"])
        x = x + out
        h = _rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps)
        x = x + _ffn(cfg, lp, h)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x, (w["layers"], cache_k, cache_v))
    x = _rms(x, w["final_scale"], cfg.norm_eps)
    logits = (x[:, 0].astype(jnp.float32) @ w["lm_head"].astype(jnp.float32))
    return logits, new_k, new_v


def _decode_block(cfg: LlamaConfig, n_steps: int, filtered: bool, w: dict,
                  cache_k, cache_v, tokens, lengths, rng, temps, top_ks,
                  top_ps):
    """n_steps decode+sample iterations in ONE device program.

    Amortizes the host<->device dispatch roundtrip (dominant on remote
    tunnels, still material on direct-attached chips) over n_steps
    tokens. Slots that hit EOS mid-block keep decoding; the host
    discards their overshoot -- rows past a slot's accepted length are
    never attended (the decode mask is position-bounded) and prefill
    overwrites them on slot reuse.
    """

    def body(carry, step_rng):
        ck, cv, toks, lens = carry
        logits, ck, cv = _decode(cfg, w, ck, cv, toks, lens)
        # ``filtered`` is STATIC: the all-greedy/unfiltered batch (the
        # common case) must not pay the double [B, V] argsort + cumsum
        # of top-k/top-p -- measured 5x decode throughput on the 8B
        # proxy (128k vocab) when the filter ran unconditionally.
        nxt = _sample(logits, step_rng, temps,
                      top_ks if filtered else None,
                      top_ps if filtered else None)
        return (ck, cv, nxt, lens + 1), nxt

    rngs = jax.random.split(rng, n_steps)
    (ck, cv, _, _), outs = jax.lax.scan(
        body, (cache_k, cache_v, tokens, lengths), rngs
    )
    return outs, ck, cv  # outs [n_steps, B]


def _sample(logits, rng, temps, top_ks=None, top_ps=None):
    """Per-slot sampling: temp<=0 means greedy; optional per-slot top-k
    (0 = off) and top-p/nucleus (>=1.0 = off) truncation applied before
    the categorical draw. logits [B,V]; temps/top_ks/top_ps [B].

    Both filters are rank-based masks over the full vocab (sorted once),
    so the program stays one fixed-shape fusion -- no dynamic gather of
    a variable candidate set.
    """

    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if top_ks is not None or top_ps is not None:
        order = jnp.argsort(-scaled, axis=-1)
        ranks = jnp.argsort(order, axis=-1)  # rank of each vocab entry
        neg = jnp.float32(-1e30)
        if top_ks is not None:
            k = jnp.where(top_ks > 0, top_ks, scaled.shape[-1])[:, None]
            scaled = jnp.where(ranks < k, scaled, neg)
        if top_ps is not None:
            sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)
            probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), -1)
            cum = jnp.cumsum(probs, axis=-1)
            # Keep tokens whose CUMULATIVE mass before them is < p (the
            # top token always survives).
            keep_sorted = (cum - probs) < top_ps[:, None]
            keep = jnp.take_along_axis(keep_sorted, ranks, axis=-1)
            scaled = jnp.where(keep, scaled, neg)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def _prefill_chunk(cfg: LlamaConfig, klen: int, w: dict, cache_k, cache_v,
                   tokens, offsets, chunk_lens, slots):
    """One CHUNK of prefill for K mid-prefill rows, written straight into
    the cache (chunked prefill: admission must never stall decoding slots
    for a whole long-prompt prefill).

    tokens [K, C]: the next C prompt tokens per row, zero-padded past
    chunk_lens. offsets [K]: tokens already in the cache per row.
    chunk_lens [K]: real tokens this chunk. slots [K]: cache slot per row
    (out-of-range = dummy row; its scatter drops). klen: STATIC key bound
    covering max(offsets)+C, bucketed by the caller so the compile count
    stays O(K-buckets x klen-buckets).

    Unlike _prefill (fresh [K,S] self-attention), each chunk attends over
    the cache prefix it and earlier chunks wrote, so cost is C x klen per
    chunk -- the price of interleaving. Padding garbage written past a
    row's real length is safe by the same invariant as _insert padding:
    a position >= the row's length is masked until the decode step that
    overwrites it.

    NOTE: the scan body below is the layer forward a third time
    (_layer_forward is the fresh-sequence case, _decode's body the C=1
    cached case) -- kept separate because _decode is THE hot loop and
    must index the cache by batch row, not gather by slot. Any change to
    the shared math (RoPE, GQA reshape, write-then-attend order, norm
    placement) must land in all three.

    Returns (logits [K, V] at each row's last real chunk token, caches).
    """

    k_rows, c = tokens.shape
    positions = offsets[:, None] + jnp.arange(c)[None, :]          # [K,C]
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = w["embed"][tokens]
    mask = jnp.arange(klen)[None, None, :] <= positions[:, :, None]  # [K,C,klen]
    row = slots[:, None]

    def body(x, layer):
        lp, ck, cv = layer
        h = _rms(x, lp["attn_norm"]["scale"], cfg.norm_eps)
        q = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["q_proj"]["kernel"])
        k = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["k_proj"]["kernel"])
        v = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["v_proj"]["kernel"])
        q = _rope(q, freqs, positions)
        k = _rope(k, freqs, positions)
        # Write the chunk's K/V, then attend over the cache prefix --
        # within-chunk causality rides the position mask.
        ck = ck.at[row, positions].set(k, mode="drop")
        cv = cv.at[row, positions].set(v, mode="drop")
        keys = ck[slots, :klen]                                    # [K,klen,KV,D]
        vals = cv[slots, :klen]
        out = _gqa_attend(q, keys, vals, mask)
        out = jnp.einsum("bsnd,ndh->bsh", out, lp["attn"]["o_proj"]["kernel"])
        x = x + out
        h = _rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps)
        x = x + _ffn(cfg, lp, h)
        return x, (ck, cv)

    x, (ck, cv) = jax.lax.scan(body, x, (w["layers"], cache_k, cache_v))
    x = _rms(x, w["final_scale"], cfg.norm_eps)
    last = x[jnp.arange(k_rows), jnp.maximum(chunk_lens - 1, 0)]
    logits = last.astype(jnp.float32) @ w["lm_head"].astype(jnp.float32)
    return logits, ck, cv


# ---------------------------------------------------------------------------
# Tensor-parallel serving (SURVEY.md 3.3 S5 delta: config #5 is v5e-4).
# ---------------------------------------------------------------------------


def make_tp_mesh(tensor_parallel: int, devices=None):
    """One-axis ``tensor`` mesh over the first N local devices. Serving TP
    is pure Megatron-style within-layer parallelism riding ICI; the slot
    scheduler stays host-side and mesh-unaware."""
    devices = list(devices if devices is not None else jax.devices())
    if tensor_parallel > len(devices):
        raise ValueError(
            f"tensor_parallel={tensor_parallel} > {len(devices)} devices"
        )
    return jax.sharding.Mesh(
        np.array(devices[:tensor_parallel]), ("tensor",)
    )


def _validate_tp(cfg: LlamaConfig, tp: int) -> None:
    for name, dim in (
        ("n_heads", cfg.n_heads),
        ("n_kv_heads", cfg.n_kv_heads),
        ("intermediate", cfg.intermediate),
        ("vocab_size", cfg.vocab_size),
    ):
        if dim % tp != 0:
            raise ValueError(
                f"tensor_parallel={tp} must divide {name}={dim}"
            )


def tp_weight_shardings(mesh, weights: dict):
    """NamedSharding pytree for the packed-weight tree: attention heads,
    MLP intermediate, and the lm_head vocab dim shard over ``tensor``;
    embeddings/norms/router replicate. XLA's SPMD partitioner inserts the
    (two per layer) all-reduces from these placements alone -- no manual
    collectives in the forward math."""
    P = jax.sharding.PartitionSpec

    def spec_for(path, leaf) -> "jax.sharding.NamedSharding":
        ks = "/".join(str(getattr(k, "key", k)) for k in path)
        if "lm_head" in ks:
            spec = P(None, "tensor")                  # [H, V]
        elif any(p in ks for p in ("q_proj", "k_proj", "v_proj")):
            spec = P(None, None, "tensor", None)      # [L, H, N, D]
        elif "o_proj" in ks:
            spec = P(None, "tensor", None, None)      # [L, N, D, H]
        elif "moe" in ks:
            if "router" in ks:
                spec = P()                            # [L, H, E] tiny, f32
            elif "down_proj" in ks:
                spec = P(None, None, "tensor", None)  # [L, E, I, H]
            else:
                spec = P(None, None, None, "tensor")  # [L, E, H, I]
        elif "down_proj" in ks:
            spec = P(None, "tensor", None)            # [L, I, H]
        elif any(p in ks for p in ("gate_proj", "up_proj")):
            spec = P(None, None, "tensor")            # [L, H, I]
        else:
            spec = P()  # embed, norm scales
        if len(spec) > getattr(leaf, "ndim", 0):
            # Name matched but rank didn't (e.g. a scalar in an aux
            # collection whose path contains "moe"): replicate.
            spec = P()
        return jax.sharding.NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(spec_for, weights)


def abstract_param_targets(cfg: LlamaConfig, mesh):
    """(abstract_tree, shardings) for the MODEL param tree ``{"params":
    ...}`` under tensor parallelism — the shape/dtype/placement targets
    for sharded checkpoint restore and sharded random init. One home so
    the restore path and the engine can never disagree on placements."""
    import dataclasses

    from flax import linen as nn

    model = Llama(dataclasses.replace(cfg, remat=False))

    def init_fn(key):
        variables = model.init(key, jnp.zeros((1, 8), jnp.int32))
        # Params only: init also sows aux collections (MoE losses)
        # that serving never touches.
        return {"params": nn.meta.unbox(variables)["params"]}

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    return abstract, tp_weight_shardings(mesh, abstract), init_fn


def tp_cache_sharding(mesh):
    """KV cache [L, B, Smax, KV, D]: KV heads over ``tensor`` -- each
    device holds its heads' cache for every slot, so decode is fully
    local until the output projection's all-reduce."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, None, None, "tensor", None)
    )


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Request:
    """One in-flight generation."""

    prompt: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0        # 0 = no top-k truncation
    top_p: float = 1.0    # >= 1.0 = no nucleus truncation
    eos_id: Optional[int] = None
    future: Optional[Future] = None
    # Streaming: called with each generated token id, FROM THE ENGINE
    # THREAD, in emission order (the final token included -- the future
    # resolving is the end-of-stream signal). Callbacks must be cheap and
    # thread-safe; server handlers bridge into asyncio via
    # loop.call_soon_threadsafe.
    on_token: Optional[Any] = None
    # Filled by the scheduler:
    slot: int = -1
    prefilled: int = 0  # prompt tokens already in the cache (chunked path)
    generated: List[int] = dataclasses.field(default_factory=list)


class GenerationEngine:
    """Slot-based continuous-batching generation over a Llama checkpoint.

    Synchronous core (``submit`` + ``step``) driven by a scheduler thread
    (``start``); jit dispatch blocks, so the thread model matches JAX's
    execution model rather than fighting asyncio.
    """

    def __init__(
        self,
        preset: str = "llama-tiny",
        params: Optional[dict] = None,
        max_slots: int = 8,
        max_seq: Optional[int] = None,
        seed: int = 0,
        config: Optional[LlamaConfig] = None,
        decode_block: int = 8,
        mesh: Optional[jax.sharding.Mesh] = None,
        tensor_parallel: int = 1,
        prefill_chunk: int = 0,
        max_prefill_tokens: int = 8192,
    ) -> None:
        # Max decode steps fused into one device program (power-of-2
        # sub-blocks keep the compile count bounded); 1 = per-token
        # dispatch.
        self.decode_block = max(1, decode_block)
        # Chunked prefill: prompts longer than this are admitted into a
        # slot immediately and prefilled prefill_chunk tokens per step,
        # interleaved with decode blocks -- one long admission can then
        # stall active decoders for at most one chunk's duration instead
        # of the whole prompt. 0 disables (whole-prompt batched prefill).
        self.prefill_chunk = max(0, int(prefill_chunk))
        # Admission budget for one batched prefill program, in PADDED
        # tokens (K-bucket x len-bucket). The prefill's fp32 attention
        # scores are K*heads*S^2 -- a 16-request burst of 2048-token
        # prompts would materialize ~8 GB of scores and OOM the chip.
        # Overflow waits in a backlog and prefills next step (vLLM's
        # max_num_batched_tokens). A single over-budget prompt still
        # admits alone.
        self.max_prefill_tokens = max(0, int(max_prefill_tokens))
        self._backlog: List[Request] = []  # engine-thread only
        cfg = config or PRESETS[preset]
        if max_seq is not None:
            cfg = dataclasses.replace(cfg, max_seq=max_seq)
        self.cfg = cfg
        self.max_slots = max_slots
        self.buckets = default_buckets(cfg.max_seq)
        # Tensor-parallel serving: a ``tensor``-axis mesh shards weights
        # and KV cache; the host-side scheduler below is unchanged.
        if mesh is None and tensor_parallel > 1:
            mesh = make_tp_mesh(tensor_parallel)
        self.mesh = mesh
        if mesh is not None:
            if "tensor" not in mesh.shape:
                raise ValueError(
                    "serving mesh needs a 'tensor' axis, got "
                    f"{tuple(mesh.axis_names)}"
                )
            _validate_tp(cfg, mesh.shape["tensor"])
        if params is None:
            # Demo mode: random init (serving tests; real use loads
            # orbax). With a mesh, init sharded from birth — the full
            # tree never exists on one device.
            if mesh is not None:
                _, msh, init_fn = abstract_param_targets(cfg, mesh)
                params = jax.jit(init_fn, out_shardings=msh)(
                    jax.random.PRNGKey(seed)
                )
            else:
                import flax.linen as nn

                model = Llama(dataclasses.replace(cfg, remat=False))
                raw = jax.jit(model.init)(
                    jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32)
                )
                params = nn.meta.unbox(raw)
        if mesh is None:
            self.weights = pack_weights(params, cfg)
        else:
            # Shard-first, cast-on-mesh: each leaf goes to its devices in
            # checkpoint dtype (a no-op for leaves orbax already restored
            # sharded), then one donated jit casts shard-locally. The
            # full serving-dtype tree never exists on a single device.
            raw = pack_weights(params, cfg, cast=False)
            wsh = tp_weight_shardings(mesh, raw)
            placed = jax.tree.map(jax.device_put, raw, wsh)
            self.weights = jax.jit(
                partial(_cast_packed, cfg=cfg),
                donate_argnums=0, out_shardings=wsh,
            )(placed)

        kvshape = (cfg.n_layers, max_slots, cfg.max_seq, cfg.n_kv_heads,
                   cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)
        if mesh is not None:
            self.cache_k = jnp.zeros(
                kvshape, dt, device=tp_cache_sharding(mesh)
            )
            self.cache_v = jnp.zeros(
                kvshape, dt, device=tp_cache_sharding(mesh)
            )
        else:
            self.cache_k = jnp.zeros(kvshape, dt)
            self.cache_v = jnp.zeros(kvshape, dt)
        self.lengths = np.zeros(max_slots, np.int64)  # host-side bookkeeping
        self.free_slots = list(range(max_slots))
        self.active: Dict[int, Request] = {}
        self.prefilling: Dict[int, Request] = {}  # slot -> mid-prefill req
        self.pending: "queue.Queue[Request]" = queue.Queue()
        self._rng = jax.random.PRNGKey(seed + 1)

        # Pin cache outputs to the KV-head sharding under TP: without the
        # constraint GSPMD may pick a different (e.g. head-dim) layout for
        # the donated outputs, leaving the cache off its intended layout.
        if mesh is not None:
            csh = tp_cache_sharding(mesh)

            def _pin(t):
                return jax.lax.with_sharding_constraint(t, csh)
        else:
            def _pin(t):
                return t

        # cfg is a static closure (hashable primitives); weights are
        # ARGUMENTS so multi-GB params are buffers, not jaxpr constants.
        prefill_jit = jax.jit(partial(_prefill, cfg))
        block_jits = {}

        def _block_fn(n, filtered):
            def fn(w, ck, cv, toks, lens, rng, temps, top_ks, top_ps):
                outs, ck, cv = _decode_block(
                    cfg, n, filtered, w, ck, cv, toks, lens, rng, temps,
                    top_ks, top_ps,
                )
                return outs, _pin(ck), _pin(cv)
            return fn

        def decode_block_call(n, filtered, ck, cv, toks, lens, rng,
                              temps, top_ks, top_ps):
            key = (n, filtered)
            if key not in block_jits:
                block_jits[key] = jax.jit(
                    _block_fn(n, filtered), donate_argnums=(1, 2)
                )
            return block_jits[key](self.weights, ck, cv, toks, lens, rng,
                                   temps, top_ks, top_ps)

        self._decode_block_call = decode_block_call

        chunk_jits = {}

        def chunk_call(klen, ck, cv, toks, offs, clens, slots):
            key = (klen, toks.shape[0])
            if key not in chunk_jits:
                def fn(w, ck, cv, toks, offs, clens, slots):
                    logits, ck, cv = _prefill_chunk(
                        cfg, klen, w, ck, cv, toks, offs, clens, slots
                    )
                    return logits, _pin(ck), _pin(cv)
                chunk_jits[key] = jax.jit(fn, donate_argnums=(1, 2))
            return chunk_jits[key](self.weights, ck, cv, toks, offs,
                                   clens, slots)

        self._chunk_call = chunk_call

        def _insert_pinned(cache_k, cache_v, k_seq, v_seq, slots):
            ck, cv = _insert(cache_k, cache_v, k_seq, v_seq, slots)
            return _pin(ck), _pin(cv)

        insert_jit = jax.jit(_insert_pinned, donate_argnums=(0, 1))
        sample_plain = jax.jit(lambda lg, rng, t: _sample(lg, rng, t))
        sample_filtered = jax.jit(_sample)

        def sample_call(logits, rng, temps, top_ks, top_ps):
            # Host-side static dispatch, same rationale as the decode
            # block's ``filtered`` key.
            if (np.asarray(top_ks) > 0).any() or (
                np.asarray(top_ps) < 1.0
            ).any():
                return sample_filtered(logits, rng, temps, top_ks, top_ps)
            return sample_plain(logits, rng, temps)

        def _prefill_call(tokens, lengths):
            # Accept a scalar for the single-prompt case (tests/oracles).
            lengths = jnp.atleast_1d(jnp.asarray(lengths, jnp.int32))
            return prefill_jit(self.weights, tokens, lengths)

        self._prefill = _prefill_call
        self._insert = insert_jit
        self._sample = sample_call
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self.tokens_generated = 0

    # -- scheduling core ---------------------------------------------------

    def submit(self, req: Request) -> Future:
        req.future = req.future or Future()
        if not req.prompt:
            req.future.set_exception(ValueError("empty prompt"))
            return req.future
        if len(req.prompt) >= self.cfg.max_seq:
            req.future.set_exception(
                ValueError(
                    f"prompt length {len(req.prompt)} >= max_seq {self.cfg.max_seq}"
                )
            )
            return req.future
        self.pending.put(req)
        self._wake.set()
        return req.future

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _admit(self) -> None:
        """Admit pending requests into free slots, prefilling them in
        BATCHES: all admissible prompts pad to one (K-bucket x len-bucket)
        shape and run as a single device program, then one scatter writes
        every sequence's KV into its slot. Serial per-prompt prefill was
        the throughput bottleneck at high request rates (one dispatch +
        an underfilled MXU per prompt)."""
        while self.free_slots and (
            self._backlog or not self.pending.empty()
        ):
            reqs: List[Request] = []
            took_chunked = False
            deferred = False
            while len(reqs) < len(self.free_slots):
                if self._backlog:
                    req = self._backlog.pop(0)
                else:
                    try:
                        req = self.pending.get_nowait()
                    except queue.Empty:
                        break
                if req.future.cancelled():
                    continue
                if (self.prefill_chunk
                        and len(req.prompt) > self.prefill_chunk):
                    # Long prompt: claim a slot now, prefill chunk-by-
                    # chunk across steps (_prefill_step) so admission
                    # never stalls decoding slots for the whole prompt.
                    req.slot = self.free_slots.pop()
                    req.prefilled = 0
                    self.prefilling[req.slot] = req
                    took_chunked = True
                    continue
                if reqs and self.max_prefill_tokens:
                    # Padded-token budget for ONE prefill program (the
                    # fp32 scores scale with K x S^2). Over-budget: run
                    # what we have; the deferred request leads the next
                    # batch.
                    k = _pow2_bucket(len(reqs) + 1)
                    s = max(self._bucket(len(r.prompt))
                            for r in reqs + [req])
                    if k * s > self.max_prefill_tokens:
                        self._backlog.insert(0, req)
                        deferred = True
                        break
                reqs.append(req)
            if not reqs:
                if took_chunked or deferred:
                    continue
                return
            k_real = len(reqs)
            kbucket = _pow2_bucket(k_real)
            bucket = max(self._bucket(len(r.prompt)) for r in reqs)
            padded = np.zeros((kbucket, bucket), np.int32)
            lengths = np.ones(kbucket, np.int32)  # dummy rows: 1 token
            for j, r in enumerate(reqs):
                padded[j, : len(r.prompt)] = r.prompt
                lengths[j] = len(r.prompt)
            logits, ks, vs = self._prefill(jnp.asarray(padded), lengths)
            slots = [self.free_slots.pop() for _ in reqs]
            # Keep kbucket shapes end-to-end (bounded compile count):
            # dummy rows scatter to an out-of-range slot (dropped) and
            # sample greedily into a discarded lane.
            padded_slots = np.full(kbucket, self.max_slots, np.int32)
            padded_slots[:k_real] = slots
            self.cache_k, self.cache_v = self._insert(
                self.cache_k, self.cache_v, ks, vs,
                jnp.asarray(padded_slots),
            )
            temps = np.zeros(kbucket, np.float32)
            top_ks = np.zeros(kbucket, np.int32)
            top_ps = np.ones(kbucket, np.float32)
            for j, r in enumerate(reqs):
                temps[j] = r.temperature
                top_ks[j] = r.top_k
                top_ps[j] = r.top_p
            first = np.asarray(self._sample(
                logits, self._next_rng(), jnp.asarray(temps),
                top_ks, top_ps,
            ))
            for j, (req, slot) in enumerate(zip(reqs, slots)):
                req.slot = slot
                self.lengths[slot] = len(req.prompt)
                self.active[slot] = req
                self._emit(req, int(first[j]))

    def _prefill_step(self) -> None:
        """Advance every mid-prefill slot by one chunk, in ONE device
        program. Rows finishing their prompt this chunk sample their
        first token and join the decode batch the same step."""

        if not self.prefilling:
            return
        items = list(self.prefilling.items())
        c = self.prefill_chunk
        kbucket = _pow2_bucket(len(items))
        toks = np.zeros((kbucket, c), np.int32)
        offs = np.zeros(kbucket, np.int32)
        clens = np.ones(kbucket, np.int32)
        slots = np.full(kbucket, self.max_slots, np.int32)  # dummies drop
        temps = np.zeros(kbucket, np.float32)
        top_ks = np.zeros(kbucket, np.int32)
        top_ps = np.ones(kbucket, np.float32)
        max_end = 1
        for j, (slot, req) in enumerate(items):
            n = min(c, len(req.prompt) - req.prefilled)
            toks[j, :n] = req.prompt[req.prefilled:req.prefilled + n]
            offs[j] = req.prefilled
            clens[j] = n
            slots[j] = slot
            temps[j] = req.temperature
            top_ks[j] = req.top_k
            top_ps[j] = req.top_p
            # Real tokens bound klen; padding lanes past n attend garbage
            # that's discarded, so they don't need covering.
            max_end = max(max_end, req.prefilled + n)
        klen = self._bucket(max_end)
        logits, self.cache_k, self.cache_v = self._chunk_call(
            klen, self.cache_k, self.cache_v, jnp.asarray(toks),
            jnp.asarray(offs), jnp.asarray(clens), jnp.asarray(slots),
        )
        first = None  # sampled lazily: most chunks finish no row
        for j, (slot, req) in enumerate(items):
            req.prefilled += int(clens[j])
            if req.prefilled < len(req.prompt):
                continue
            if first is None:
                first = np.asarray(self._sample(
                    logits, self._next_rng(), jnp.asarray(temps),
                    top_ks, top_ps,
                ))
            del self.prefilling[slot]
            self.lengths[slot] = len(req.prompt)
            self.active[slot] = req
            self._emit(req, int(first[j]))

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(token)
        self.tokens_generated += 1
        if req.on_token is not None:
            try:
                req.on_token(token)
            except Exception:  # noqa: BLE001 - a bad stream sink must not
                logger.exception("on_token callback failed")  # kill the slot
        self.lengths[req.slot] += 1
        done = (
            (req.eos_id is not None and token == req.eos_id)
            or len(req.generated) >= req.max_new_tokens
            or self.lengths[req.slot] >= self.cfg.max_seq
        )
        if done:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        slot = req.slot
        self.active.pop(slot, None)
        self.lengths[slot] = 0
        self.free_slots.append(slot)
        if not req.future.done():
            req.future.set_result(req.generated)

    def step(self) -> bool:
        """Admit pending, advance prefill chunks, run one decode block.
        Returns True if work ran. The chunk-then-block interleave is the
        point: an active decoder waits at most one chunk per step."""

        self._admit()
        ran = bool(self.prefilling)
        self._prefill_step()
        if not self.active:
            return ran
        # Block size: largest power-of-2 <= decode_block within every
        # slot's CACHE headroom (an out-of-range write must not happen).
        # The MIN token budget is deliberately NOT a bound: a single
        # nearly-done slot would otherwise convoy the whole batch down to
        # per-token dispatch; its overshoot is discarded host-side like
        # EOS. The MAX budget IS a bound: when every active slot is nearly
        # done, fused steps past the longest budget are pure waste.
        remaining = min(
            self.cfg.max_seq - int(self.lengths[slot])
            for slot in self.active
        )
        budget = max(
            req.max_new_tokens - len(req.generated)
            for req in self.active.values()
        )
        n = 1
        while n * 2 <= min(self.decode_block, max(remaining, 1), max(budget, 1)):
            n *= 2
        tokens = np.zeros(self.max_slots, np.int32)
        temps = np.zeros(self.max_slots, np.float32)
        top_ks = np.zeros(self.max_slots, np.int32)
        top_ps = np.ones(self.max_slots, np.float32)
        # Non-active slots park at Smax-1: decode writes dummy K/V for
        # EVERY row, and position 0 of a mid-prefill slot already holds
        # real chunked-prefill state. Smax-1 garbage is safe for any
        # future occupant -- a row first becomes visible (mask: key <=
        # query position) in the very decode step that overwrites it.
        positions_np = np.full(self.max_slots, self.cfg.max_seq - 1,
                               np.int32)
        for slot, req in self.active.items():
            tokens[slot] = req.generated[-1]
            temps[slot] = req.temperature
            top_ks[slot] = req.top_k
            top_ps[slot] = req.top_p
            # lengths[slot] already counts the last generated token, whose
            # K/V is not in the cache yet: its position is lengths-1.
            positions_np[slot] = max(int(self.lengths[slot]) - 1, 0)
        positions = jnp.asarray(positions_np)
        filtered = any(
            req.top_k > 0 or req.top_p < 1.0
            for req in self.active.values()
        )
        outs, self.cache_k, self.cache_v = self._decode_block_call(
            n, filtered, self.cache_k, self.cache_v, jnp.asarray(tokens),
            positions, self._next_rng(), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps),
        )
        outs = np.asarray(outs)  # [n, B]
        for slot in list(self.active):
            req = self.active[slot]
            for j in range(n):
                self._emit(req, int(outs[j, slot]))
                if slot not in self.active:  # finished: drop overshoot
                    break
        return True

    # -- convenience / threaded driver ------------------------------------

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 top_k: int = 0, top_p: float = 1.0) -> List[int]:
        """Synchronous single-request generation (drives step() inline)."""

        req = Request(list(prompt), max_new_tokens, temperature,
                      top_k, top_p, eos_id)
        fut = self.submit(req)
        if self._thread is not None:
            return fut.result(timeout=600)
        while not fut.done():
            if not self.step():
                break
        return fut.result()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if not self.step():
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kftpu-engine")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._wake.set()
            self._thread.join(timeout=5)
            self._thread = None

    def close(self) -> None:
        """Release device memory (weights + KV cache) and the compiled
        calls that close over them. The jit closures reference the engine
        through ``self``, a reference CYCLE -- without an explicit break,
        a dropped engine waits for the cyclic GC while its multi-GB HBM
        buffers stay live, and the next engine OOMs. Unusable after."""
        self.stop()
        self.weights = None
        self.cache_k = None
        self.cache_v = None
        self._decode_block_call = None
        self._chunk_call = None
        self._prefill = None
        self._insert = None
        self._sample = None
