"""TPU generation engine: jitted prefill/decode with continuous batching.

The serving-side counterpart of models/llama.py (which owns the training
forward). The reference's GPU LLM path is huggingfaceserver+vLLM (SURVEY.md
3.3 S5); the TPU-native replacement is built around what XLA wants:

- **Static shapes everywhere.** The KV cache is a fixed [L, B, Smax, KV, D]
  buffer (int8 kv_quant adds f32 scales stored LANE-ALIGNED as
  [L, B, KV, Smax] -- Smax minor, so the TPU (8,128) tile pads ~1x
  instead of 16x; see _kv_set); prompts pad to a small set of prefill
  buckets, so there are O(#buckets) compiles, not O(#lengths). Decode is
  one fixed-shape program.
- **Slot-based continuous batching.** New requests prefill into a free
  cache slot while other slots keep decoding; one decode step advances all
  active slots (vLLM's iteration-level scheduling, minus paging -- slab
  slots beat paged KV under XLA because dynamic gather/scatter of pages
  defeats fusion; Smax bounds the slab).
- **Donated cache buffers.** decode/insert donate the cache so XLA updates
  it in place in HBM -- no per-token cache copies.
- **Depth-N dispatch pipeline.** The decode block hands back its final
  token/position carry as DEVICE arrays; the scheduler chains up to
  pipeline_depth successor blocks into a lane deque, starts their
  outputs streaming home with copy_to_host_async, and only then
  consumes the oldest block (EOS / stop detection, logprobs, stream
  callbacks) while the queued blocks run. Slots that finish mid-flight
  produce overshoot the host discards by design -- bounded per drain by
  drain_overshoot_bound -- and decode sampling keys are a pure function
  of (request nonce, position), so ANY pipeline_depth emits
  bit-identical streams to pipeline_depth=0. Admissions, constraint
  mode, and spec-decode drain the pipeline first (docs/SERVING.md).
- **Layer-stacked params + lax.scan** over layers: mirrors the training
  model's nn.scan layout, so orbax training checkpoints drop straight in;
  one compiled layer body.

Weight math reimplements the Llama forward as pure functions over the
training param pytree (scan layout) rather than threading a cache through
linen -- inference wants explicit state, not module state.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import queue
import threading
import time
from concurrent.futures import Future
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu import chaos
from kubeflow_tpu.models.llama import (
    LlamaConfig,
    PRESETS,
    Llama,
    rope_frequencies,
)
from kubeflow_tpu.obs import registry as obs_registry
from kubeflow_tpu.obs import trace

logger = logging.getLogger(__name__)


def default_buckets(max_seq: int) -> tuple[int, ...]:
    out, b = [], 32
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


def _pow2_bucket(n: int) -> int:
    """Smallest power of 2 >= n (jit-compile key bucketing for row counts)."""
    b = 1
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Pure forward math over the training param pytree (scan layout).
# ---------------------------------------------------------------------------


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), -1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def _rope(x, freqs, positions):
    # x [B,S,H,D]; positions [B,S]; freqs [Smax, D/2] fp32.
    f = freqs[positions]  # [B,S,D/2]
    cos = jnp.cos(f)[:, :, None, :]
    sin = jnp.sin(f)[:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., 0::2], x32[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _kv_quantize(x):
    """Per-(position, head) symmetric int8 over the last (D) axis:
    x [..., KV, D] -> {"q": int8 same shape, "s": f32 [..., KV]}.

    The KV-cache analog of quantize_packed: decode re-reads the whole
    live cache every step, so int8 rows halve the second-largest HBM
    stream after the weights (dominant at long contexts). Scales fold
    into the attention SCORES (k) and PROBS (v) -- the cache-side
    matmul operands stay int8 all the way to the MXU read.

    Scales here come back in the VALUE's own [..., S, KV] order; the
    cache STORES them lane-aligned, Smax minor ([..., KV, Smax]) -- see
    _kv_set for why and how the writer re-derives the placement."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / s[..., None]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def _scale_index(idx):
    """Map a q-cache index (leading axes up to and including the Smax
    selector, which comes LAST) onto the lane-aligned scale cache, whose
    Smax axis sits after KV: q [..., B, Smax, KV, D] -> s [..., B, KV,
    Smax]."""
    return idx[:-1] + (slice(None), idx[-1])


def _kv_set(cache, idx, val, mode=None):
    """cache.at[idx].set(val) for a plain bf16 cache or an int8-quantized
    {"q","s"} cache. ``idx`` addresses the q layout's leading axes up to
    and including Smax (its selector last).

    Scale storage is LANE-ALIGNED: [..., KV, Smax], Smax (a 128
    multiple) on the minor dim, so the f32 (8,128) HBM tile pads KV
    against 8 sublanes instead of 16x against 128 lanes (measured r5:
    64 MB of scales -> 1.00 GB allocated per cache under the old
    [..., Smax, KV] layout at 32 slots x Smax 2048), and the Pallas
    decode kernel DMAs scale rows without a per-step transpose. The
    scale write re-derives its index/value order from idx's Smax
    selector:

    - a slice (prefill insert / prefix restore): the KV axis slots in
      before it and the single advanced index (slots) stays in place,
      so the update window is [..., KV, S] and the fresh [..., S, KV]
      scales swap their last two axes to match;
    - an array (per-step decode / chunk scatter): batch and position
      arrays become SEPARATED advanced indices, which NumPy semantics
      move to the front -- the update window is [batch..., S, KV],
      exactly the quantizer's own output order."""
    kw = {"mode": mode} if mode else {}
    if isinstance(cache, dict):
        qs = _kv_quantize(val)
        s = qs["s"]
        if isinstance(idx[-1], slice):
            s = jnp.swapaxes(s, -1, -2)
        return {"q": cache["q"].at[idx].set(qs["q"], **kw),
                "s": cache["s"].at[_scale_index(idx)].set(s, **kw)}
    return cache.at[idx].set(val, **kw)


def _kv_index(cache, idx):
    """cache[idx] on both representations. idx's Smax selector (last)
    must be a slice; the returned scale rows keep the lane-aligned
    [..., KV, S] order -- _gqa_attend's native broadcast layout."""
    if isinstance(cache, dict):
        return {"q": cache["q"][idx], "s": cache["s"][_scale_index(idx)]}
    return cache[idx]


def _kv_layer(cache, li):
    """Layer ``li``'s slice of a full [L, ...] cache, both
    representations -- the per-layer read view inside the decode loops,
    which carry the FULL cache (see _decode) and index it here."""
    if isinstance(cache, dict):
        return {"q": cache["q"][li], "s": cache["s"][li]}
    return cache[li]


def _kv_nbytes(cache) -> int:
    return int(sum(x.size * x.dtype.itemsize
                   for x in jax.tree.leaves(cache)))


def _kv_smax(cache) -> int:
    """Cache sequence capacity on both representations."""
    return (cache["q"] if isinstance(cache, dict) else cache).shape[2]


def _kv_rows_len(rows) -> int:
    return int((rows["q"] if isinstance(rows, dict) else rows).shape[1])


def _gqa_attend(q, k, v, mask):
    """q [B,S,N,D] over k/v [B,T,KV,D] -- or int8-quantized {"q","s"}
    caches with lane-aligned scales [B,KV,T], whose scales are folded
    OUT of the big matmuls: k's scale multiplies the scores, v's scale
    pre-multiplies the probs, so both cache operands cross HBM as int8
    and the [B,KV,T] rows broadcast straight into the [B,KV,G,S,T]
    scores without a transpose. mask [B,S,T] True=visible."""
    b, s, n, d = q.shape
    kq, ks = (k["q"], k["s"]) if isinstance(k, dict) else (k, None)
    vq, vs = (v["q"], v["s"]) if isinstance(v, dict) else (v, None)
    kv = kq.shape[2]
    q = q.reshape(b, s, kv, n // kv, d)
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", q, kq.astype(q.dtype)
    ).astype(jnp.float32)
    if ks is not None:
        scores = scores * ks[:, :, None, None, :]
    scores = scores / np.sqrt(d)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if vs is not None:
        probs = probs * vs[:, :, None, None, :]
    out = jnp.einsum(
        "bkgst,btkd->bskgd", probs.astype(q.dtype), vq.astype(q.dtype)
    )
    return out.reshape(b, s, n, d)


def _cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )


def pack_weights(params: dict, cfg: LlamaConfig, cast: bool = True) -> dict:
    """params: the ``{"params": ...}`` pytree from Llama.init / orbax
    restore (scan layout required), flax metadata already unboxed.

    Returns a plain-dict pytree so it can be a jit *argument* -- closing
    over multi-GB weights would bake them into the jaxpr as constants.

    ``cast=False`` returns the reorganized tree with leaves UNTOUCHED (no
    device ops): the tensor-parallel path places each leaf sharded first
    and casts on-mesh, so the full tree is never materialized on one
    device (config #5's 8B on a 16 GiB v5e-4 would OOM otherwise).
    """

    p = params["params"] if "params" in params else params
    if "layers" not in p:
        raise ValueError("engine requires scan_layers=True checkpoints")
    out = {
        "embed": p["embed"]["embedding"],                      # [V, H]
        "final_scale": p["final_norm"]["scale"],
        "lm_head": p["lm_head"]["kernel"],                     # [H, V]
        "layers": p["layers"]["layer"],                        # leaves [L, ...]
    }
    return _cast_packed(out, cfg) if cast else out


def _cast_packed(w: dict, cfg: LlamaConfig) -> dict:
    """Serving dtypes for a packed tree: activations-dtype everywhere,
    except norm scales and the MoE router in f32. Router weights route
    DISCRETELY (top-k): a bf16 rounding can flip a near-tie to a
    different expert than training chose, an O(1) output change; the
    [L, H, E] router is tiny, so f32 costs nothing."""
    dtype = jnp.dtype(cfg.dtype)
    layers = _cast(w["layers"], dtype)
    if "moe" in layers:
        layers = dict(layers)
        layers["moe"] = dict(layers["moe"])
        layers["moe"]["router"] = w["layers"]["moe"]["router"].astype(
            jnp.float32
        )
    return {
        "embed": _cast(w["embed"], dtype),
        "final_scale": w["final_scale"].astype(jnp.float32),
        "lm_head": _cast(w["lm_head"], dtype),
        "layers": layers,
    }


def quantize_packed(w: dict) -> dict:
    """Weight-only symmetric int8 over a packed (serving-dtype) tree.

    Decode is HBM-bandwidth bound: every step streams the full weight
    set per token batch, so halving weight bytes is a direct throughput
    lever on v5e (and halves the HBM footprint, the binding constraint
    for 8B on a 16 GiB chip). Scheme chosen for XLA, not for the MXU:

    - **Per-output-channel symmetric scales** (`s = max|w|/127` over the
      contraction axes). Finer than per-tensor -- the error is ~0.4% per
      matmul -- while keeping the scale a rank-(out) vector applied to
      the matmul OUTPUT: ``y = einsum(x, q.astype(bf16)) * s``. The int8
      ->bf16 convert fuses into the dot's operand read (weights cross
      HBM as int8); the scale touches only the small activation output.
    - **Activations stay bf16** (no dynamic activation quant): the MXU
      runs the dot in bf16 either way, and serving's win is bandwidth,
      not FLOPs.
    - Norm scales and the MoE router stay f32 (routing is discrete; see
      _cast_packed); the embedding quantizes per-ROW (gathers read
      int8 rows, dequant after the gather costs B*H).

    Parity note: the reference's GPU serving path ships int8/quantized
    variants via vLLM/huggingfaceserver (SURVEY.md 3.3 S5 delta); this
    is the TPU-native equivalent.
    """

    def q8(arr, axes):
        a = arr.astype(jnp.float32)
        amax = jnp.max(jnp.abs(a), axis=axes)
        s = jnp.maximum(amax, 1e-8) / 127.0
        qq = jnp.clip(
            jnp.round(a / jnp.expand_dims(s, axes)), -127, 127
        ).astype(jnp.int8)
        return {"q": qq, "s": s}

    layers = w["layers"]
    attn = layers["attn"]
    qlayers = dict(layers)
    qlayers["attn"] = {
        "q_proj": {"kernel": q8(attn["q_proj"]["kernel"], (1,))},
        "k_proj": {"kernel": q8(attn["k_proj"]["kernel"], (1,))},
        "v_proj": {"kernel": q8(attn["v_proj"]["kernel"], (1,))},
        "o_proj": {"kernel": q8(attn["o_proj"]["kernel"], (1, 2))},
    }
    if "mlp" in layers:
        mlp = layers["mlp"]
        qlayers["mlp"] = {
            "gate_proj": {"kernel": q8(mlp["gate_proj"]["kernel"], (1,))},
            "up_proj": {"kernel": q8(mlp["up_proj"]["kernel"], (1,))},
            "down_proj": {"kernel": q8(mlp["down_proj"]["kernel"], (1,))},
        }
    if "moe" in layers:
        moe = layers["moe"]
        qlayers["moe"] = {
            "router": moe["router"],  # f32, discrete routing
            "gate_proj": q8(moe["gate_proj"], (2,)),
            "up_proj": q8(moe["up_proj"], (2,)),
            "down_proj": q8(moe["down_proj"], (2,)),
        }
    return {
        "embed": q8(w["embed"], (1,)),
        "final_scale": w["final_scale"],
        "lm_head": q8(w["lm_head"], (0,)),
        "layers": qlayers,
    }


def quantized_random_init(cfg: LlamaConfig, seed: int = 0) -> dict:
    """Random weights built DIRECTLY in the int8 serving representation.

    The real ``llama3-8b`` preset is 16 GB in bf16 -- more than one
    v5e's 15.75 GB HBM -- so the usual demo path (init bf16, then
    quantize) can never run on the chip it is meant to fit. This
    builder materializes each packed leaf already quantized: [L, ...]
    leaves stream layer-by-layer through a lax.scan (peak extra HBM =
    ONE layer's f32 temp, ~235 MB at 8B geometry), and the two
    vocab-sized leaves run first while nothing else is resident. Peak
    ~int8 total + 2 GB transient; final residency ~8.1 GB for 8B.

    Weight values are lecun-normal like Llama.init, then symmetric
    per-output-channel int8 exactly like quantize_packed -- the compute
    path (and therefore a perf measurement) is identical to loading and
    quantizing a real checkpoint; only the values are random. For real
    weights at this scale use load_params_from_checkpoint + the one-jit
    quantize load (its peak is checkpoint-dtype + int8, which fits for
    a bf16 checkpoint read leaf-by-leaf from host RAM).
    """
    if cfg.n_experts > 1:
        raise ValueError("quantized_random_init supports dense models "
                         "only (8B is dense; MoE serves via TP)")
    L, H = cfg.n_layers, cfg.hidden
    N, D, KV = cfg.n_heads, cfg.head_dim, cfg.n_kv_heads
    I, V = cfg.intermediate, cfg.vocab_size
    key = jax.random.PRNGKey(seed)
    keys = iter(jax.random.split(key, 16))

    def q8_flat(k, shape, axes, fan_in):
        """One non-stacked leaf (embed / lm_head), quantized in-jit so
        the f32 temp is program-internal."""
        def build(kk):
            w = jax.random.normal(kk, shape, jnp.float32) * (fan_in ** -0.5)
            amax = jnp.max(jnp.abs(w), axis=axes)
            sc = jnp.maximum(amax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(w / jnp.expand_dims(sc, axes)),
                         -127, 127).astype(jnp.int8)
            return {"q": q, "s": sc}
        return jax.jit(build)(k)

    def q8_stacked(k, shape, axes, fan_in):
        """One [L, *shape] leaf via scan: layer l's f32 temp is freed
        before layer l+1 materializes."""
        def body(carry, kk):
            w = jax.random.normal(kk, shape, jnp.float32) * (fan_in ** -0.5)
            amax = jnp.max(jnp.abs(w), axis=axes)
            sc = jnp.maximum(amax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(w / jnp.expand_dims(sc, axes)),
                         -127, 127).astype(jnp.int8)
            return carry, (q, sc)

        def build(kk):
            _, (qs, ss) = jax.lax.scan(body, 0, jax.random.split(kk, L))
            return {"q": qs, "s": ss}
        return jax.jit(build)(k)

    out = {
        # Vocab-sized leaves first: transient f32 temp (V*H*4 ~ 2 GB at
        # 8B) overlaps the SMALLEST resident footprint.
        "embed": q8_flat(next(keys), (V, H), (1,), H),
        "lm_head": q8_flat(next(keys), (H, V), (0,), H),
        "final_scale": jnp.ones((H,), jnp.float32),
        "layers": {
            "attn": {
                "q_proj": {"kernel": q8_stacked(
                    next(keys), (H, N, D), (0,), H)},
                "k_proj": {"kernel": q8_stacked(
                    next(keys), (H, KV, D), (0,), H)},
                "v_proj": {"kernel": q8_stacked(
                    next(keys), (H, KV, D), (0,), H)},
                "o_proj": {"kernel": q8_stacked(
                    next(keys), (N, D, H), (0, 1), N * D)},
            },
            "mlp": {
                "gate_proj": {"kernel": q8_stacked(
                    next(keys), (H, I), (0,), H)},
                "up_proj": {"kernel": q8_stacked(
                    next(keys), (H, I), (0,), H)},
                "down_proj": {"kernel": q8_stacked(
                    next(keys), (I, H), (0,), I)},
            },
            # Serving dtype, matching _cast_packed's output for a real
            # checkpoint (values are ones, so this is bitwise-neutral
            # through _rms's f32 upcast) -- the trees must be leaf-for-
            # leaf identical so perf runs compile the same program.
            "attn_norm": {"scale": jnp.ones((L, H), jnp.dtype(cfg.dtype))},
            "mlp_norm": {"scale": jnp.ones((L, H), jnp.dtype(cfg.dtype))},
        },
    }
    return out


def _pj(eqn, x, kern):
    """einsum against a possibly int8-quantized kernel leaf. Quantized
    leaves are ``{"q": int8, "s": f32 per-output-channel}``; the scale's
    shape is exactly the weight's output axes, so it broadcasts against
    the einsum output's trailing dims for every projection in this
    file."""
    if isinstance(kern, dict):
        y = jnp.einsum(eqn, x, kern["q"].astype(x.dtype))
        # Scale multiply in f32 (matching _lm_logits/_embed_rows): a
        # bf16 cast of the scale would add ~0.4% rounding on top of the
        # quantization error for free. The f32 temp is elementwise and
        # fuses into the dot's epilogue.
        return (y.astype(jnp.float32) * kern["s"]).astype(x.dtype)
    return jnp.einsum(eqn, x, kern)


def _embed_rows(w: dict, tokens, dtype):
    """Embedding gather with optional per-row int8 dequant (in f32 --
    the gathered rows are tiny next to the table read)."""
    e = w["embed"]
    if isinstance(e, dict):
        rows = e["q"][tokens].astype(jnp.float32)
        return (rows * e["s"][tokens][..., None]).astype(dtype)
    return e[tokens]


def _lm_logits(x32, lm):
    """f32 logits: x32 [..., H] @ lm_head [H, V] (possibly int8; the
    convert fuses into the dot read either way)."""
    if isinstance(lm, dict):
        return (x32 @ lm["q"].astype(jnp.float32)) * lm["s"]
    return x32 @ lm.astype(jnp.float32)


def _moe_ffn(cfg: LlamaConfig, m: dict, h):
    """MoE FFN for inference: compute every expert densely, weight by the
    renormalized top-k router probabilities.

    No capacity, no drops -- capacity is a training-throughput artifact;
    at serving batch sizes the E/k extra FFN FLOPs are cheaper than
    gather/scatter of per-token expert weights, and the result is exact
    (matches the training layer whenever training dropped nothing).
    """
    e, k = cfg.n_experts, cfg.experts_per_token
    logits = jnp.einsum(
        "bsh,he->bse", h.astype(jnp.float32),
        m["router"].astype(jnp.float32),
    )
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                    # [B,S,k]
    topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    w_e = jnp.zeros_like(probs)                             # [B,S,E]
    for j in range(k):
        w_e = w_e + jax.nn.one_hot(topi[..., j], e) * topv[..., j:j + 1]
    gate = _pj("bsh,ehi->bsei", h, m["gate_proj"])
    up = _pj("bsh,ehi->bsei", h, m["up_proj"])
    act = jax.nn.silu(gate) * up
    out = _pj("bsei,eih->bseh", act, m["down_proj"])
    return jnp.einsum("bse,bseh->bsh", w_e.astype(h.dtype), out)


def _ffn(cfg: LlamaConfig, lp: dict, h):
    if "moe" in lp:
        return _moe_ffn(cfg, lp["moe"], h)
    mlp = lp["mlp"]
    gate = _pj("bsh,hi->bsi", h, mlp["gate_proj"]["kernel"])
    up = _pj("bsh,hi->bsi", h, mlp["up_proj"]["kernel"])
    return _pj("bsi,ih->bsh", jax.nn.silu(gate) * up,
               mlp["down_proj"]["kernel"])


def _layer_forward(cfg: LlamaConfig, lp: dict, x, freqs, positions, mask):
    """One decoder layer, self-attention over the current tokens only (the
    prefill path; decode attends over the cache, see _decode). Returns
    (x, k, v) with k/v the current tokens' cache rows."""

    attn = lp["attn"]
    h = _rms(x, lp["attn_norm"]["scale"], cfg.norm_eps)
    q = _pj("bsh,hnd->bsnd", h, attn["q_proj"]["kernel"])
    k = _pj("bsh,hnd->bsnd", h, attn["k_proj"]["kernel"])
    v = _pj("bsh,hnd->bsnd", h, attn["v_proj"]["kernel"])
    q = _rope(q, freqs, positions)
    k = _rope(k, freqs, positions)
    out = _gqa_attend(q, k, v, mask)
    out = _pj("bsnd,ndh->bsh", out, attn["o_proj"]["kernel"])
    x = x + out
    h = _rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps)
    return x + _ffn(cfg, lp, h), k, v


def _prefill(cfg: LlamaConfig, w: dict, tokens, lengths):
    """Causal self-attention over a BATCH of padded prompts [K, S].

    Prefilling K admitted requests in one program amortizes both the
    per-dispatch host->device roundtrip and the MXU's preference for
    bigger batches over the serial [1, S] case. Returns
    (next_token_logits [K, V], k_seq, v_seq [L, K, S, KV, D]).
    """

    k_rows, s = tokens.shape
    positions = jnp.arange(s)[None, :]
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = _embed_rows(w, tokens, jnp.dtype(cfg.dtype))
    causal = jnp.tril(jnp.ones((s, s), bool))[None]

    def body(x, lp):
        x, k, v = _layer_forward(cfg, lp, x, freqs, positions, causal)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, w["layers"])
    x = _rms(x, w["final_scale"], cfg.norm_eps)
    # Logits only for each row's last real token (lengths[k]-1).
    last = x[jnp.arange(k_rows), lengths - 1]  # [K, H]
    logits = _lm_logits(last.astype(jnp.float32), w["lm_head"])
    return logits, ks, vs


def packed_forward_logits(cfg: LlamaConfig, w: dict, tokens):
    """Teacher-forced full-sequence logits [B, S, V] (f32) through the
    PACKED serving weights -- the same _pj projections the decode path
    uses, so int8-quantized leaves dequantize exactly as they do in
    serving. Exists for quality measurement (heldout perplexity, per-
    position top-1 agreement bf16 vs int8) on trained checkpoints;
    not a serving path."""
    b, sq = tokens.shape
    positions = jnp.arange(sq)[None, :]
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = _embed_rows(w, tokens, jnp.dtype(cfg.dtype))
    causal = jnp.tril(jnp.ones((sq, sq), bool))[None]

    def body(x, lp):
        x, _k, _v = _layer_forward(cfg, lp, x, freqs, positions, causal)
        return x, None

    x, _ = jax.lax.scan(body, x, w["layers"])
    x = _rms(x, w["final_scale"], cfg.norm_eps)
    return _lm_logits(x.astype(jnp.float32), w["lm_head"])


def _insert(cache_k, cache_v, k_seq, v_seq, slots):
    """Write K prefilled sequences into cache slots ``slots`` [K].

    cache [L,B,Smax,KV,D]; k_seq [L,K,S,KV,D] with S <= Smax (the
    prefill bucket). Donated buffers; one scatter per cache instead of
    K dynamic-update dispatches. Dummy rows (K padded up to its bucket)
    carry an out-of-range slot index and are DROPPED by the scatter, so
    every input keeps its bucketed shape — compile count stays
    O(K-buckets x len-buckets), not O(max_slots x len-buckets)."""

    s = k_seq.shape[2]
    idx = (slice(None), slots, slice(None, s))
    return (
        _kv_set(cache_k, idx, k_seq, mode="drop"),
        _kv_set(cache_v, idx, v_seq, mode="drop"),
    )


def _decode(cfg: LlamaConfig, w: dict, cache_k, cache_v, tokens, lengths,
            kernel: bool = False):
    """One decode step for all slots.

    tokens [B] (last sampled token per slot), lengths [B] (tokens already
    in cache; the new token's position). Returns (logits [B, V], caches).

    ``kernel`` routes attention through the Pallas bounded-span decode
    kernel (ops/decode_attention.py): HBM cache reads scale with each
    slot's live context instead of Smax.
    """

    # NOTE (measured 2026-07-30): bounding the attended span to a bucket
    # of the longest active length (attend ck[:, :klen]) REGRESSES ~5x on
    # v5e -- the slice of the scan-carried cache materializes as a copy
    # per layer per step instead of fusing into the attention reads,
    # dwarfing the bandwidth it saves. Full-span attention + mask is the
    # fast path under XLA; the Pallas kernel (``kernel=True``) DMAs only
    # the live rows out of the in-place HBM cache -- measured 2026-07-31
    # at parity (short contexts) to -9% (1024-token contexts) on the 8B
    # proxy, where cache reads are only ~19% of step bandwidth; see
    # ops/decode_attention.py for the full A/B. Default stays XLA.
    b = tokens.shape[0]
    smax = _kv_smax(cache_k)
    kblock = min(256, smax)
    if smax % kblock:
        kernel = False  # non-pow2 max_seq: kernel tiling can't cover it
    positions = lengths[:, None]  # [B,1]
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = _embed_rows(w, tokens, jnp.dtype(cfg.dtype))[:, None, :]  # [B,1,H]
    # Visible: key position <= query position. Everything earlier in the
    # slot was written by the current occupant, so this is exact.
    mask = jnp.arange(smax)[None, None, :] <= positions[:, :, None]  # [B,1,Smax]
    batch_idx = jnp.arange(b)[:, None]

    def body(carry, xs):
        # The FULL [L, ...] caches ride the CARRY (layer-indexed
        # scatter/slice) instead of the xs/ys streams: scanned ys would
        # make XLA stack a fresh full-size output cache per outer decode
        # step -- the measured r5 2x2.00 GB temps that pushed 32 real-8B
        # slots to 20.36 G. As a while-loop carry the donated buffers
        # update in place and the program holds exactly one copy
        # (regression-guarded by tests/test_serving_engine.py's
        # compiled-memory check).
        x, ck, cv = carry
        lp, li = xs
        # Write current k/v into the cache *then* attend over it.
        h = _rms(x, lp["attn_norm"]["scale"], cfg.norm_eps)
        q = _pj("bsh,hnd->bsnd", h, lp["attn"]["q_proj"]["kernel"])
        k = _pj("bsh,hnd->bsnd", h, lp["attn"]["k_proj"]["kernel"])
        v = _pj("bsh,hnd->bsnd", h, lp["attn"]["v_proj"]["kernel"])
        q = _rope(q, freqs, positions)
        k = _rope(k, freqs, positions)
        ck = _kv_set(ck, (li, batch_idx, positions), k)
        cv = _kv_set(cv, (li, batch_idx, positions), v)
        ck_l = _kv_layer(ck, li)
        cv_l = _kv_layer(cv, li)
        if kernel:
            from kubeflow_tpu.ops.decode_attention import (
                decode_attention,
                decode_attention_int8,
            )

            n = q.shape[2]
            kvh = cfg.n_kv_heads
            qg = q[:, 0].reshape(b, kvh, n // kvh, cfg.head_dim)
            interp = jax.default_backend() != "tpu"
            if isinstance(ck_l, dict):
                # Scales are STORED [B, KV, Smax] -- the kernel's
                # lane-aligned DMA layout -- so the rows feed straight
                # through (the per-step transpose this used to pay is
                # gone with the storage-layout change).
                out = decode_attention_int8(
                    qg, ck_l["q"], ck_l["s"], cv_l["q"], cv_l["s"],
                    lengths, block=kblock, interpret=interp,
                )
            else:
                out = decode_attention(
                    qg, ck_l, cv_l, lengths, block=kblock, interpret=interp,
                )
            out = out.reshape(b, 1, n, cfg.head_dim)
        else:
            out = _gqa_attend(q, ck_l, cv_l, mask)
        out = _pj("bsnd,ndh->bsh", out, lp["attn"]["o_proj"]["kernel"])
        x = x + out
        h = _rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps)
        x = x + _ffn(cfg, lp, h)
        return (x, ck, cv), None

    (x, new_k, new_v), _ = jax.lax.scan(
        body, (x, cache_k, cache_v),
        (w["layers"], jnp.arange(cfg.n_layers)),
    )
    x = _rms(x, w["final_scale"], cfg.norm_eps)
    logits = _lm_logits(x[:, 0].astype(jnp.float32), w["lm_head"])
    return logits, new_k, new_v


# Fixed top-k width of the device-side logprob outputs (OpenAI caps
# completions logprobs at 5, chat top_logprobs at 20; 8 covers the
# common case in one static shape -- per-request N trims host-side).
LOGPROBS_K = 8


def _logprob_outputs(logits, chosen):
    """(chosen_logprob [B], top_ids [B,K], top_logprobs [B,K]) from raw
    f32 logits -- log-softmax BEFORE temperature/filtering, the OpenAI
    logprobs contract."""
    lps = jax.nn.log_softmax(logits, axis=-1)
    sel = jnp.take_along_axis(lps, chosen[:, None], axis=-1)[:, 0]
    top_lps, top_ids = jax.lax.top_k(lps, LOGPROBS_K)
    return sel, top_ids, top_lps


def _decode_block(cfg: LlamaConfig, n_steps: int, filtered: bool,
                  want_lp: bool, w: dict, cache_k, cache_v, tokens,
                  lengths, rng, temps, top_ks, top_ps, nonces,
                  kernel: bool = False, mask=None):
    """n_steps decode+sample iterations in ONE device program.

    Amortizes the host<->device dispatch roundtrip (dominant on remote
    tunnels, still material on direct-attached chips) over n_steps
    tokens. Slots that hit EOS mid-block keep decoding; the host
    discards their overshoot -- rows past a slot's accepted length are
    never attended (the decode mask is position-bounded) and prefill
    overwrites them on slot reuse.

    Sampling keys are derived PER ROW and PER POSITION:
    fold_in(fold_in(rng, nonces[b]), position) with ``rng`` a fixed
    base key, ``nonces`` the per-request counter stamped at submit(),
    and ``position`` the scan-carried length. A token's draw therefore
    depends only on (request, position) -- NOT on which block it lands
    in or what else is in flight -- so the pipelined dispatcher
    (pipeline_depth=1) emits bit-identical streams to the sequential
    one (pipeline_depth=0), block partitioning included.

    ``want_lp`` (STATIC) additionally emits per-step logprob outputs --
    gated because the extra [B, V] log-softmax + top-k passes are pure
    waste for the no-logprobs common case.

    Returns (outs, ck, cv, last_tokens [B], last_positions [B]) -- the
    final carry rides back as DEVICE arrays so a chained next block can
    consume them without a host round trip.
    """

    def body(carry, _):
        ck, cv, toks, lens = carry
        logits, ck, cv = _decode(cfg, w, ck, cv, toks, lens, kernel)
        keys = jax.vmap(
            lambda nonce, pos: jax.random.fold_in(
                jax.random.fold_in(rng, nonce), pos
            )
        )(nonces, lens)
        # ``filtered`` is STATIC: the all-greedy/unfiltered batch (the
        # common case) must not pay the double [B, V] argsort + cumsum
        # of top-k/top-p -- measured 5x decode throughput on the 8B
        # proxy (128k vocab) when the filter ran unconditionally.
        # mask is only sound for the FIRST step of a block (the legal
        # set depends on each sampled token); constrained callers run
        # n_steps=1, so the whole block is that first step.
        nxt = _sample_rows(logits, keys, temps,
                           top_ks if filtered else None,
                           top_ps if filtered else None, mask)
        out = (nxt, *_logprob_outputs(logits, nxt)) if want_lp else nxt
        return (ck, cv, nxt, lens + 1), out

    (ck, cv, last, lens), outs = jax.lax.scan(
        body, (cache_k, cache_v, tokens, lengths), None, length=n_steps
    )
    # outs [n_steps, B] (or the logprob tuple)
    return outs, ck, cv, last, lens


def _host_logprobs(row: np.ndarray, token: int, n: int) -> dict:
    """Logprob record from one host-side f32 logits row (first tokens,
    whose prompt-end logits come back from prefill anyway; decode steps
    get theirs from the device program's gated outputs)."""
    m = float(row.max())
    lse = m + float(np.log(np.exp(row - m).sum()))
    k = min(max(n, 1), LOGPROBS_K)
    top = np.argpartition(-row, k - 1)[:k]
    top = top[np.argsort(-row[top])]
    return {
        "logprob": float(row[token]) - lse,
        "top_ids": top.tolist(),
        "top_logprobs": (row[top] - lse).tolist(),
    }


def _filter_scaled(logits, temps, top_ks=None, top_ps=None, mask=None):
    """Shared sampling front half: constraint mask, temperature scaling,
    and the rank-based top-k/top-p truncation. Returns (greedy [B],
    scaled [B,V]) ready for a categorical draw.

    Both filters are rank-based masks over the full vocab (sorted once),
    so the program stays one fixed-shape fusion -- no dynamic gather of
    a variable candidate set.

    ``mask`` [B, V] bool (optional): constrained decoding
    (serving.jsonmode) -- disallowed tokens drop to -inf BEFORE
    greedy/temperature/filtering, so the constraint composes with every
    sampling mode. All-False rows would sample token 0; the engine
    finishes such requests host-side instead.
    """

    if mask is not None:
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if top_ks is not None or top_ps is not None:
        order = jnp.argsort(-scaled, axis=-1)
        ranks = jnp.argsort(order, axis=-1)  # rank of each vocab entry
        neg = jnp.float32(-1e30)
        if top_ks is not None:
            k = jnp.where(top_ks > 0, top_ks, scaled.shape[-1])[:, None]
            scaled = jnp.where(ranks < k, scaled, neg)
        if top_ps is not None:
            sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)
            probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), -1)
            cum = jnp.cumsum(probs, axis=-1)
            # Keep tokens whose CUMULATIVE mass before them is < p (the
            # top token always survives).
            keep_sorted = (cum - probs) < top_ps[:, None]
            keep = jnp.take_along_axis(keep_sorted, ranks, axis=-1)
            scaled = jnp.where(keep, scaled, neg)
    return greedy, scaled


def _sample(logits, rng, temps, top_ks=None, top_ps=None, mask=None):
    """Per-slot sampling: temp<=0 means greedy; optional per-slot top-k
    (0 = off) and top-p/nucleus (>=1.0 = off) truncation applied before
    the categorical draw. logits [B,V]; temps/top_ks/top_ps [B].

    One batch-wide categorical from a single ``rng`` -- the right shape
    for host-chained call sites (admission first tokens, fused/spec
    paths) where a fresh key is split per dispatch.
    """

    greedy, scaled = _filter_scaled(logits, temps, top_ks, top_ps, mask)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def _sample_rows(logits, keys, temps, top_ks=None, top_ps=None,
                 mask=None):
    """Like ``_sample`` but with an independent PRNG key PER ROW
    (``keys`` [B, key_size]). Decode blocks derive each row's key from
    (request nonce, token position) so the draw for a given token is a
    pure function of the request and position -- invariant to how the
    engine partitions steps into blocks, which is what lets the
    pipelined dispatcher (pipeline_depth=1) stay bit-identical to the
    sequential one. Attention is slot-local, so rows are independent
    and the per-row draw loses nothing to the batch-wide one.
    """

    greedy, scaled = _filter_scaled(logits, temps, top_ks, top_ps, mask)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row)
    )(keys, scaled)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def _fused_block(cfg: LlamaConfig, n_steps: int, m_tail: int, c: int,
                 klen: int, filtered: bool, want_lp: bool, w: dict,
                 cache_k, cache_v, tokens, lengths, chunk_toks,
                 chunk_offs, chunk_clens, chunk_slots, rng, temps,
                 top_ks, top_ps, nonces, mask=None):
    """Mixed batch in ONE device program (vLLM's chunked prefill, shaped
    for XLA): n_steps decode steps each fused with one prefill chunk,
    then m_tail chunk-only steps that finish the prompts without
    dragging more decode work into the dispatch.

    The round-3 engine alternated a standalone chunk program with a full
    decode block, so a long prompt's first token waited
    ceil(prompt/c) x (chunk + decode-block) dispatches -- a measured 4x
    TTFT regression for the -26% ITL win. The first fused cut (chunks
    riding a full n=8 block) measured TTFT p50 711ms vs 248ms
    whole-prompt: the finishing dispatch still carried 8 decode steps,
    and scaled with prompt length. This shape fixes both ends:
    - the mixed scan keeps decoders advancing during every prefill
      dispatch (never a whole-prompt stall), with layer weights
      streamed from HBM once per layer per step for both lanes;
    - the tail scan runs the REST of the prompt's chunks chunk-only, so
      TTFT ~= wait + n_steps decode steps + the prefill itself, with
      n_steps capped small (engine default 2) instead of growing with
      the prompt;
    - the whole prompt still finishes inside ONE dispatch.

    tokens/lengths/temps/top_ks/top_ps are the [B] decode lanes (same
    contract as _decode_block). chunk_toks [n_steps + m_tail, K, C]
    holds the chunk scheduled for each step (zero rows once a prompt is
    finished); chunk_offs [K] the starting cache offsets; chunk_clens
    [n_steps + m_tail, K] real tokens per row per step; chunk_slots [K]
    the cache slot per row (out-of-range = dummy lane; its scatter
    drops). klen: STATIC key bound covering max(chunk_offs + scheduled
    tokens), bucketed by the caller.

    Chunk lanes attend over the cache prefix they and earlier chunks
    wrote (cost C x klen per step -- the price of interleaving); decode
    lanes attend full-span as in _decode. The two write disjoint cache
    regions: a slot is either prefilling (chunk rows, positions <
    prompt_len <= Smax-1 real, garbage past its prompt overwritten-
    before-visible by later decode steps) or decoding (its own positions;
    parked dummies at Smax-1) -- never both.

    Per-row first-token logits are latched into a carried [K, V] buffer
    on the last step where the row has real tokens (clens > 0), so the
    host samples first tokens once per dispatch and gets prompt-end
    logits for free (logprobs).

    NOTE: the layer bodies below are the layer forward a third time
    (_layer_forward is the fresh-sequence case, _decode's body the
    decode-only case) -- kept separate because each is a differently-
    shaped hot loop. Any change to the shared math (RoPE, GQA reshape,
    write-then-attend order, norm placement) must land in all three.

    Decode-lane sampling keys are derived per row and per position --
    fold_in(fold_in(rng, nonces[b]), position), the same scheme as
    _decode_block -- so a decode token's draw is a pure function of
    (request, position): identical whether the step ran in a pure
    decode block, a fused dispatch, or any chunk partitioning of the
    prompt stream. That invariance is what lets the continuous
    chunked-prefill scheduler chain fused dispatches through the lane
    deque while staying bit-identical to the sequential path.

    Returns (dec_outs [n_steps, B] or logprob tuple, chunk_logits
    [K, V] f32, caches, last_tokens [B], last_positions [B]); the
    final decode carry rides back as DEVICE arrays so a chained next
    block (fused or pure decode) consumes them without a host round
    trip.
    """

    b = tokens.shape[0]
    k_rows = chunk_toks.shape[1]
    smax = _kv_smax(cache_k)
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    batch_idx = jnp.arange(b)[:, None]
    row = chunk_slots[:, None]

    def chunk_layer(x_c, lp, li, ck, cv, c_pos, c_mask):
        """Chunk lanes through one layer ``li`` of the FULL carried
        caches: write this chunk's K/V into the row's slot, attend over
        the cache prefix (within-chunk causality rides the position
        mask)."""
        attn = lp["attn"]
        h = _rms(x_c, lp["attn_norm"]["scale"], cfg.norm_eps)
        q = _pj("bsh,hnd->bsnd", h, attn["q_proj"]["kernel"])
        k = _pj("bsh,hnd->bsnd", h, attn["k_proj"]["kernel"])
        v = _pj("bsh,hnd->bsnd", h, attn["v_proj"]["kernel"])
        q = _rope(q, freqs, c_pos)
        k = _rope(k, freqs, c_pos)
        ck = _kv_set(ck, (li, row, c_pos), k, mode="drop")
        cv = _kv_set(cv, (li, row, c_pos), v, mode="drop")
        sl = (li, chunk_slots, slice(None, klen))
        keys = _kv_index(ck, sl)                          # [K,klen,KV,D]
        vals = _kv_index(cv, sl)
        out = _gqa_attend(q, keys, vals, c_mask)
        out = _pj("bsnd,ndh->bsh", out, attn["o_proj"]["kernel"])
        x_c = x_c + out
        h = _rms(x_c, lp["mlp_norm"]["scale"], cfg.norm_eps)
        return x_c + _ffn(cfg, lp, h), ck, cv

    def chunk_logits_latch(x_c, cclens, fin_logits):
        x_c = _rms(x_c, w["final_scale"], cfg.norm_eps)
        last = x_c[jnp.arange(k_rows), jnp.maximum(cclens - 1, 0)]
        c_logits = _lm_logits(last.astype(jnp.float32), w["lm_head"])
        return jnp.where((cclens > 0)[:, None], c_logits, fin_logits)

    def mixed_step(carry, xs):
        ck0, cv0, toks, lens, offs, fin_logits = carry
        ctoks, cclens = xs
        dec_pos = lens[:, None]                                  # [B,1]
        dec_mask = jnp.arange(smax)[None, None, :] <= dec_pos[:, :, None]
        c_pos = offs[:, None] + jnp.arange(c)[None, :]           # [K,C]
        c_mask = jnp.arange(klen)[None, None, :] <= c_pos[:, :, None]
        x_d = _embed_rows(w, toks, jnp.dtype(cfg.dtype))[:, None, :]  # [B,1,H]
        x_c = _embed_rows(w, ctoks, jnp.dtype(cfg.dtype))             # [K,C,H]

        def layer_body(carry2, xs):
            # Full caches in the carry, not the xs/ys streams -- same
            # single-buffer rationale as _decode's body.
            x_d, x_c, ck, cv = carry2
            lp, li = xs
            x_c, ck, cv = chunk_layer(x_c, lp, li, ck, cv, c_pos, c_mask)
            # Decode lanes (same math as _decode's body).
            attn = lp["attn"]
            h = _rms(x_d, lp["attn_norm"]["scale"], cfg.norm_eps)
            q = _pj("bsh,hnd->bsnd", h, attn["q_proj"]["kernel"])
            k = _pj("bsh,hnd->bsnd", h, attn["k_proj"]["kernel"])
            v = _pj("bsh,hnd->bsnd", h, attn["v_proj"]["kernel"])
            q = _rope(q, freqs, dec_pos)
            k = _rope(k, freqs, dec_pos)
            ck = _kv_set(ck, (li, batch_idx, dec_pos), k)
            cv = _kv_set(cv, (li, batch_idx, dec_pos), v)
            out = _gqa_attend(q, _kv_layer(ck, li), _kv_layer(cv, li),
                              dec_mask)
            out = _pj("bsnd,ndh->bsh", out, attn["o_proj"]["kernel"])
            x_d = x_d + out
            h = _rms(x_d, lp["mlp_norm"]["scale"], cfg.norm_eps)
            x_d = x_d + _ffn(cfg, lp, h)
            return (x_d, x_c, ck, cv), None

        (x_d, x_c, ck1, cv1), _ = jax.lax.scan(
            layer_body, (x_d, x_c, ck0, cv0),
            (w["layers"], jnp.arange(cfg.n_layers)),
        )
        x_d = _rms(x_d, w["final_scale"], cfg.norm_eps)
        d_logits = _lm_logits(x_d[:, 0].astype(jnp.float32), w["lm_head"])
        keys = jax.vmap(
            lambda nonce, pos: jax.random.fold_in(
                jax.random.fold_in(rng, nonce), pos
            )
        )(nonces, lens)
        # Like _decode_block: mask only sound at n_steps=1 (caller
        # enforces when constrained lanes are active).
        nxt = _sample_rows(d_logits, keys, temps,
                           top_ks if filtered else None,
                           top_ps if filtered else None, mask)
        fin_logits = chunk_logits_latch(x_c, cclens, fin_logits)
        out = (nxt, *_logprob_outputs(d_logits, nxt)) if want_lp else nxt
        return (ck1, cv1, nxt, lens + 1, offs + cclens, fin_logits), out

    def tail_step(carry, xs):
        ck0, cv0, offs, fin_logits = carry
        ctoks, cclens = xs
        c_pos = offs[:, None] + jnp.arange(c)[None, :]
        c_mask = jnp.arange(klen)[None, None, :] <= c_pos[:, :, None]
        x_c = _embed_rows(w, ctoks, jnp.dtype(cfg.dtype))

        def layer_body(carry2, xs):
            x_c, ck, cv = carry2
            lp, li = xs
            x_c, ck, cv = chunk_layer(x_c, lp, li, ck, cv, c_pos, c_mask)
            return (x_c, ck, cv), None

        (x_c, ck1, cv1), _ = jax.lax.scan(
            layer_body, (x_c, ck0, cv0),
            (w["layers"], jnp.arange(cfg.n_layers)),
        )
        fin_logits = chunk_logits_latch(x_c, cclens, fin_logits)
        return (ck1, cv1, offs + cclens, fin_logits), None

    fin0 = jnp.zeros((k_rows, cfg.vocab_size), jnp.float32)
    (ck, cv, last, lens, offs, fin_logits), outs = jax.lax.scan(
        mixed_step,
        (cache_k, cache_v, tokens, lengths, chunk_offs, fin0),
        (chunk_toks[:n_steps], chunk_clens[:n_steps]),
    )
    if m_tail:
        (ck, cv, _, fin_logits), _ = jax.lax.scan(
            tail_step,
            (ck, cv, offs, fin_logits),
            (chunk_toks[n_steps:], chunk_clens[n_steps:]),
        )
    return outs, fin_logits, ck, cv, last, lens


# ---------------------------------------------------------------------------
# Tensor-parallel serving (SURVEY.md 3.3 S5 delta: config #5 is v5e-4).
# ---------------------------------------------------------------------------


def make_tp_mesh(tensor_parallel: int, devices=None):
    """One-axis ``tensor`` mesh over the first N local devices. Serving TP
    is pure Megatron-style within-layer parallelism riding ICI; the slot
    scheduler stays host-side and mesh-unaware."""
    devices = list(devices if devices is not None else jax.devices())
    if tensor_parallel > len(devices):
        raise ValueError(
            f"tensor_parallel={tensor_parallel} > {len(devices)} devices"
        )
    return jax.sharding.Mesh(
        np.array(devices[:tensor_parallel]), ("tensor",)
    )


def _validate_tp(cfg: LlamaConfig, tp: int) -> None:
    for name, dim in (
        ("n_heads", cfg.n_heads),
        ("n_kv_heads", cfg.n_kv_heads),
        ("intermediate", cfg.intermediate),
        ("vocab_size", cfg.vocab_size),
    ):
        if dim % tp != 0:
            raise ValueError(
                f"tensor_parallel={tp} must divide {name}={dim}"
            )


def tp_weight_shardings(mesh, weights: dict):
    """NamedSharding pytree for the packed-weight tree: attention heads,
    MLP intermediate, and the lm_head vocab dim shard over ``tensor``;
    embeddings/norms/router replicate. XLA's SPMD partitioner inserts the
    (two per layer) all-reduces from these placements alone -- no manual
    collectives in the forward math."""
    P = jax.sharding.PartitionSpec

    def spec_for(path, leaf) -> "jax.sharding.NamedSharding":
        ks = "/".join(str(getattr(k, "key", k)) for k in path)
        if "lm_head" in ks:
            spec = P(None, "tensor")                  # [H, V]
        elif any(p in ks for p in ("q_proj", "k_proj", "v_proj")):
            spec = P(None, None, "tensor", None)      # [L, H, N, D]
        elif "o_proj" in ks:
            spec = P(None, "tensor", None, None)      # [L, N, D, H]
        elif "moe" in ks:
            if "router" in ks:
                spec = P()                            # [L, H, E] tiny, f32
            elif "down_proj" in ks:
                spec = P(None, None, "tensor", None)  # [L, E, I, H]
            else:
                spec = P(None, None, None, "tensor")  # [L, E, H, I]
        elif "down_proj" in ks:
            spec = P(None, "tensor", None)            # [L, I, H]
        elif any(p in ks for p in ("gate_proj", "up_proj")):
            spec = P(None, None, "tensor")            # [L, H, I]
        else:
            spec = P()  # embed, norm scales
        if len(spec) > getattr(leaf, "ndim", 0):
            # Name matched but rank didn't (e.g. a scalar in an aux
            # collection whose path contains "moe"): replicate.
            spec = P()
        return jax.sharding.NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(spec_for, weights)


def abstract_param_targets(cfg: LlamaConfig, mesh):
    """(abstract_tree, shardings) for the MODEL param tree ``{"params":
    ...}`` under tensor parallelism — the shape/dtype/placement targets
    for sharded checkpoint restore and sharded random init. One home so
    the restore path and the engine can never disagree on placements."""
    import dataclasses

    from flax import linen as nn

    model = Llama(dataclasses.replace(cfg, remat=False))

    def init_fn(key):
        variables = model.init(key, jnp.zeros((1, 8), jnp.int32))
        # Params only: init also sows aux collections (MoE losses)
        # that serving never touches.
        return {"params": nn.meta.unbox(variables)["params"]}

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    return abstract, tp_weight_shardings(mesh, abstract), init_fn


def tp_cache_sharding(mesh):
    """KV cache [L, B, Smax, KV, D]: KV heads over ``tensor`` -- each
    device holds its heads' cache for every slot, so decode is fully
    local until the output projection's all-reduce."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, None, None, "tensor", None)
    )


def tp_kv_scale_sharding(mesh):
    """int8 KV-cache scale, lane-aligned storage [L, B, KV, Smax]: same
    head split as the cache it scales, so the scores/probs multiplies
    stay shard-local."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, None, "tensor", None)
    )


def _ngram_draft(hist, lens, k: int):
    """Prompt-lookup drafting, fully on device: for each row find the
    LATEST earlier occurrence of the trailing 2-gram in the token
    history and propose the k tokens that followed it. No draft model,
    no extra weights -- repetition in the context (code, chat echoes,
    structured text) is the signal. Rows with no match draft garbage
    that verification simply rejects (cost: the step degenerates to one
    decode step, never wrongness).

    hist [B, Smax] (prompt + generated, valid to lens); lens [B] = total
    tokens incl. the pending last sample. Returns draft [B, k].
    """
    b, smax = hist.shape
    rows = jnp.arange(b)
    t1 = hist[rows, jnp.maximum(lens - 2, 0)]
    t2 = hist[rows, jnp.maximum(lens - 1, 0)]
    # match[i] == True: (hist[i], hist[i+1]) equals the trailing 2-gram,
    # with i+1 strictly before the trailing occurrence itself.
    m = (hist[:, :-1] == t1[:, None]) & (hist[:, 1:] == t2[:, None])
    m &= (jnp.arange(smax - 1)[None, :] + 1) < (lens - 1)[:, None]
    p = (smax - 2) - jnp.argmax(m[:, ::-1], axis=1)  # latest match
    found = m.any(axis=1)
    start = jnp.where(found, p + 2, 0)
    gpos = start[:, None] + jnp.arange(k)[None, :]
    return jnp.take_along_axis(hist, jnp.minimum(gpos, smax - 1), axis=1)


def _draft_forward(dcfg: LlamaConfig, dw: dict, toks, positions, valid):
    """One full forward of the DRAFT model over a [B, W] token window,
    returning the last position's logits [B, V]. Cache-free: the window
    is tiny and the draft is small, so recomputing self-attention per
    draft step costs less than keeping a second KV cache consistent
    with speculative rollbacks (a rejected draft would strand wrong
    rows in it). ``positions`` [B, W] are ABSOLUTE (RoPE matches how
    the draft was trained on absolute positions); ``valid`` [B, W]
    masks left-padding for rows shorter than the window."""
    b, wlen = toks.shape
    freqs = rope_frequencies(dcfg.head_dim, dcfg.max_seq, dcfg.rope_theta)
    x = _embed_rows(dw, toks, jnp.dtype(dcfg.dtype))          # [B,W,H]
    causal = jnp.arange(wlen)[None, :] <= jnp.arange(wlen)[:, None]
    mask = causal[None, :, :] & valid[:, None, :]             # [B,W,W]

    def layer_body(x, xs):
        lp, _ = xs
        attn = lp["attn"]
        h = _rms(x, lp["attn_norm"]["scale"], dcfg.norm_eps)
        q = _pj("bsh,hnd->bsnd", h, attn["q_proj"]["kernel"])
        k = _pj("bsh,hnd->bsnd", h, attn["k_proj"]["kernel"])
        v = _pj("bsh,hnd->bsnd", h, attn["v_proj"]["kernel"])
        q = _rope(q, freqs, positions)
        k = _rope(k, freqs, positions)
        out = _gqa_attend(q, k, v, mask)
        out = _pj("bsnd,ndh->bsh", out, attn["o_proj"]["kernel"])
        x = x + out
        h = _rms(x, lp["mlp_norm"]["scale"], dcfg.norm_eps)
        return x + _ffn(dcfg, lp, h), None

    x, _ = jax.lax.scan(
        layer_body, x, (dw["layers"], jnp.arange(dcfg.n_layers))
    )
    x = _rms(x[:, -1], dw["final_scale"], dcfg.norm_eps)
    return _lm_logits(x.astype(jnp.float32), dw["lm_head"])


def _draft_model_draft(dcfg: LlamaConfig, dw: dict, window: int, k: int,
                       hist, lens):
    """Trained-draft speculation: k greedy tokens from the DRAFT model,
    conditioned on the last ``window`` tokens of each row's history.
    The window is right-aligned (the newest token sits at index W-1),
    shorter rows left-pad with masked zeros, and each of the k chained
    draft steps rolls the window one token left and re-runs the tiny
    forward -- k small forwards inside the same device program, no
    draft KV cache to keep consistent with rejections.

    hist [B, Smax] valid to ``lens`` (which INCLUDES the pending last
    sample, same contract as _ngram_draft). Returns draft [B, k].
    """
    b, smax = hist.shape
    base = lens[:, None] - window + jnp.arange(window)[None, :]  # [B,W]
    valid = base >= 0
    toks = jnp.take_along_axis(
        hist, jnp.clip(base, 0, smax - 1), axis=1
    )
    toks = jnp.where(valid, toks, 0)
    pos = jnp.clip(base, 0, dcfg.max_seq - 1)

    def body(carry, _):
        toks, pos, valid = carry
        logits = _draft_forward(dcfg, dw, toks, pos, valid)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks[:, 1:], nxt[:, None]], axis=1)
        pos = jnp.concatenate(
            [pos[:, 1:],
             jnp.minimum(pos[:, -1:] + 1, dcfg.max_seq - 1)], axis=1
        )
        valid = jnp.concatenate(
            [valid[:, 1:], jnp.ones((b, 1), bool)], axis=1
        )
        return (toks, pos, valid), nxt

    _, drafts = jax.lax.scan(body, (toks, pos, valid), None, length=k)
    return jnp.transpose(drafts)                              # [B,k]


def _spec_block(cfg: LlamaConfig, m_steps: int, k_draft: int, w: dict,
                cache_k, cache_v, tokens, lengths, hist, draft=None,
                draft_w=None):
    """m_steps SPECULATIVE decode iterations in ONE device program
    (greedy path only; the scheduler falls back to _decode_block for
    sampled/filterered/logprob batches).

    Each step: draft k tokens per slot -- by prompt lookup
    (_ngram_draft) or, when ``draft`` = (draft_cfg, window) and
    ``draft_w`` carry a distilled DRAFT model, by k chained greedy
    forwards of that model over the history window
    (_draft_model_draft) -- then verify [last, d1..dk] in one
    (k+1)-wide forward over the cache --
    decode is HBM-bandwidth bound, so the (k+1)x FLOPs ride the SAME
    weight stream a 1-token step pays for -- then accept the longest
    matched prefix plus the model's bonus token. Per step a slot emits
    1..k+1 tokens for one weight read; on the dispatch-overhead-
    dominated serving path that compounds with block fusion: tokens per
    dispatch goes from m to up to m*(k+1).

    Cache invariant: verification writes K/V for all k+1 candidate
    positions; rows past the accepted count hold garbage that is
    masked-until-overwritten exactly like block-decode overshoot (the
    next step's write window starts at the new length). The carried
    history gets ONLY accepted tokens (mode="drop" scatter) -- garbage
    there would poison later drafts.

    tokens [B] last sampled; lengths [B] total tokens incl. it (cache
    holds lengths-1). hist [B, Smax] token history, valid to lengths.
    Returns (out_tokens [m, B, k+1], counts [m, B], ck, cv, last [B],
    lens [B], hist [B, Smax]); rows of out_tokens past counts are
    zero-padding the host discards, and the trailing carries ride back
    as DEVICE arrays so a chained next spec block (depth-N pipeline)
    consumes them -- history included -- without a host round trip.
    """

    b = tokens.shape[0]
    smax = _kv_smax(cache_k)
    s = k_draft + 1
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    batch_idx = jnp.arange(b)[:, None]
    j = jnp.arange(s)[None, :]

    def step_body(carry, _):
        ck0, cv0, toks, lens, hist = carry
        if draft is not None:
            dcfg, window = draft
            drafted = _draft_model_draft(dcfg, draft_w, window,
                                         k_draft, hist, lens)  # [B,k]
        else:
            drafted = _ngram_draft(hist, lens, k_draft)        # [B,k]
        tokens_in = jnp.concatenate([toks[:, None], drafted], axis=1)
        positions = (lens - 1)[:, None] + j                  # [B,S]
        mask = jnp.arange(smax)[None, None, :] <= positions[:, :, None]
        x = _embed_rows(w, tokens_in, jnp.dtype(cfg.dtype))  # [B,S,H]

        def layer_body(carry2, xs):
            # Full caches in the carry -- same single-buffer rationale
            # as _decode's body.
            x, ck, cv = carry2
            lp, li = xs
            attn = lp["attn"]
            h = _rms(x, lp["attn_norm"]["scale"], cfg.norm_eps)
            q = _pj("bsh,hnd->bsnd", h, attn["q_proj"]["kernel"])
            k = _pj("bsh,hnd->bsnd", h, attn["k_proj"]["kernel"])
            v = _pj("bsh,hnd->bsnd", h, attn["v_proj"]["kernel"])
            q = _rope(q, freqs, positions)
            k = _rope(k, freqs, positions)
            ck = _kv_set(ck, (li, batch_idx, positions), k)
            cv = _kv_set(cv, (li, batch_idx, positions), v)
            out = _gqa_attend(q, _kv_layer(ck, li), _kv_layer(cv, li),
                              mask)
            out = _pj("bsnd,ndh->bsh", out, attn["o_proj"]["kernel"])
            x = x + out
            h = _rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps)
            return (x + _ffn(cfg, lp, h), ck, cv), None

        (x, ck1, cv1), _ = jax.lax.scan(
            layer_body, (x, ck0, cv0),
            (w["layers"], jnp.arange(cfg.n_layers)),
        )
        x = _rms(x, w["final_scale"], cfg.norm_eps)
        g = jnp.argmax(
            _lm_logits(x.astype(jnp.float32), w["lm_head"]), axis=-1
        )                                                    # [B,S]
        eq = drafted == g[:, :-1]
        a = jnp.cumprod(eq.astype(jnp.int32), axis=1).sum(axis=1)  # [B]
        bonus = jnp.take_along_axis(g, a[:, None], axis=1)[:, 0]
        padded_draft = jnp.pad(drafted, ((0, 0), (0, 1)))
        out = jnp.where(j < a[:, None], padded_draft,
                        jnp.where(j == a[:, None], bonus[:, None], 0))
        count = a + 1
        wpos = jnp.where(j <= a[:, None], lens[:, None] + j, smax)
        hist = hist.at[batch_idx, wpos].set(out, mode="drop")
        return (ck1, cv1, bonus, lens + count, hist), (out, count)

    (ck, cv, last, lens, hist), (outs, counts) = jax.lax.scan(
        step_body, (cache_k, cache_v, tokens, lengths, hist),
        None, length=m_steps,
    )
    return outs, counts, ck, cv, last, lens, hist


# ---------------------------------------------------------------------------
# Prefix (KV) cache
# ---------------------------------------------------------------------------


class LatencyHistogram(obs_registry.Histogram):
    """Serving latency histogram on the shared obs.registry.Histogram
    (ms-derived second buckets; the ``le`` strings -- "0.005", "0.01",
    ... -- are bit-identical to the pre-port format). Kept as a named
    subclass so engine call sites read as before and the bucket ladder
    stays a serving-owned constant."""

    BUCKETS_MS = (5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
                  2500.0, 5000.0)

    def __init__(self) -> None:
        super().__init__(tuple(b / 1000.0 for b in self.BUCKETS_MS))


class PrefixCache:
    """Exact-match prompt-prefix reuse (vLLM's prefix caching, slab-shaped).

    Prompts hash block-by-block with a rolling chain hash; a finished
    prefill donates its slot's KV rows [L, plen, KV, D] to the store,
    registered under EVERY block-prefix hash (one buffer, many keys), so
    a later prompt sharing any block-aligned prefix restores those rows
    with one scatter and prefills only the remainder. Shared system
    prompts -- the dominant cost of multi-turn OpenAI chat, which
    re-renders the whole history every turn -- then cost one restore
    instead of a full prefill.

    Device-memory bounded: LRU over whole entries by byte budget. Keys
    are chain hashes of exact token blocks, so a hit implies token-exact
    prefix equality (module collisions of blake2b, not a practical
    concern).
    """

    def __init__(self, block: int, capacity_bytes: int) -> None:
        self.block = max(1, int(block))
        self.capacity = int(capacity_bytes)
        # chain-hash -> (entry, plen). entry = dict(k, v, plen, keys,
        # tick); entries own device buffers and all their prefix keys.
        self.by_prefix: Dict[bytes, tuple] = {}
        self.entries: Dict[bytes, dict] = {}  # full-capture hash -> entry
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self._tick = 0

    def chain_hashes(self, prompt: Sequence[int], max_len: int):
        """[(plen, hash)] at each block boundary <= max_len."""
        import hashlib

        out = []
        h = b"kftpu-prefix"
        n = (min(len(prompt), max_len) // self.block) * self.block
        for end in range(self.block, n + 1, self.block):
            blk = np.asarray(
                prompt[end - self.block:end], np.int64
            ).tobytes()
            h = hashlib.blake2b(h + blk, digest_size=16).digest()
            out.append((end, h))
        return out

    def lookup(self, prompt: Sequence[int], max_len: int):
        """Longest cached (plen, entry) for a block-aligned prefix of
        ``prompt`` no longer than max_len, or (0, None)."""
        best = (0, None)
        # No early break on a miss: eviction can delete a SHORTER prefix
        # key (owned by the victim) while a longer live entry still
        # covers it, so presence is not monotone in prefix length.
        for plen, h in self.chain_hashes(prompt, max_len):
            hit = self.by_prefix.get(h)
            if hit is not None:
                best = (plen, hit[0])
        if best[1] is not None:
            self._tick += 1
            best[1]["tick"] = self._tick
            self.hits += 1
        else:
            self.misses += 1
        return best

    def insert(self, prompt: Sequence[int], k_rows, v_rows) -> None:
        """Donate KV rows covering a block-multiple prefix of prompt.
        k_rows/v_rows: [L, plen, KV, D] device arrays."""
        plen = _kv_rows_len(k_rows)
        hashes = self.chain_hashes(prompt, plen)
        if not hashes or hashes[-1][0] != plen:
            return
        full = hashes[-1][1]
        if full in self.entries:
            return  # already captured (the common repeated-prefix case)
        size = _kv_nbytes(k_rows) + _kv_nbytes(v_rows)
        if size > self.capacity:
            return
        self._tick += 1
        # tokens: the covered prompt prefix, host ints. Needed to re-key
        # the entry on another replica (migration re-derives the chain
        # hashes there) and to re-pack it through the router wire format;
        # a few KB of host RAM against MBs of device rows.
        entry = {"k": k_rows, "v": v_rows, "plen": plen,
                 "keys": [], "tick": self._tick, "bytes": size,
                 "tokens": list(prompt[:plen])}
        for _plen, h in hashes:
            # First writer wins for shorter prefixes (it is the LRU-hot
            # one); the full-length key is ours by the check above.
            if h not in self.by_prefix or h == full:
                self.by_prefix[h] = (entry, _plen)
                entry["keys"].append(h)
        self.entries[full] = entry
        self.bytes += size
        while self.bytes > self.capacity and self.entries:
            victim_full, victim = min(
                self.entries.items(), key=lambda kv: kv[1]["tick"]
            )
            if victim is entry and len(self.entries) == 1:
                break
            for h in victim["keys"]:
                if self.by_prefix.get(h, (None,))[0] is victim:
                    del self.by_prefix[h]
            del self.entries[victim_full]
            self.bytes -= victim["bytes"]

    def stats(self) -> dict:
        return {"entries": len(self.entries), "bytes": self.bytes,
                "hits": self.hits, "misses": self.misses}

    def hot_entries(self, top_k: int = 0) -> List[dict]:
        """Hottest-first inventory of cached entries (LRU tick order),
        host metadata only -- no device buffers. ``top_k`` 0 = all.
        The unit the serving-plane migration path ships: a recipient
        re-derives every chain-hash key from ``tokens``, so the hash is
        advisory (matching the router's affinity key for this entry)."""
        rows = sorted(self.entries.items(), key=lambda kv: -kv[1]["tick"])
        if top_k > 0:
            rows = rows[:top_k]
        return [{
            "hash": full.hex(), "plen": e["plen"], "bytes": e["bytes"],
            "tick": e["tick"], "tokens": list(e.get("tokens", ())),
        } for full, e in rows]


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Request:
    """One in-flight generation."""

    prompt: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0        # 0 = no top-k truncation
    top_p: float = 1.0    # >= 1.0 = no nucleus truncation
    eos_id: Optional[int] = None
    # Stop-sequence hook: called FROM THE ENGINE THREAD with the
    # generated ids after every token; returning True finishes the
    # request immediately (the slot frees mid-block, overshoot
    # discarded). The engine is tokenizer-blind, so text-level stop
    # strings live in the serving layer, which scans the decoded tail
    # here and trims the stop text from its response. The matched tokens
    # stay in the result (ids and text must agree).
    stop_fn: Optional[Any] = None
    # Constrained decoding (serving.jsonmode.JsonConstraint or any
    # object with mask()/advance(id)/complete): the engine applies
    # mask() inside the device sample, advances on each emitted token,
    # and finishes the request at complete. Constrained requests force
    # single-step dispatches (the legal set depends on the previous
    # token), so they cost block-amortization -- documented in
    # serving/jsonmode.py.
    constraint: Optional[Any] = None
    # Top-N logprob capture: 0 = off; else each emitted token appends
    # {"logprob", "top_ids", "top_logprobs"} (f32 log-softmax of the RAW
    # logits -- pre-temperature, the OpenAI contract) to
    # ``logprob_data``. N is capped at LOGPROBS_K (the device program
    # returns a fixed-K top-k; one static shape, one extra compile).
    logprobs: int = 0
    future: Optional[Future] = None
    # Streaming: called with each generated token id, FROM THE ENGINE
    # THREAD, in emission order (the final token included -- the future
    # resolving is the end-of-stream signal). Callbacks must be cheap and
    # thread-safe; server handlers bridge into asyncio via
    # loop.call_soon_threadsafe.
    on_token: Optional[Any] = None
    # Filled by the scheduler:
    slot: int = -1
    # Per-request sampling nonce (stamped at submit): decode-block keys
    # are fold_in(fold_in(base, nonce), position), so a request's draws
    # are independent of batch composition and block partitioning.
    nonce: int = 0
    prefilled: int = 0  # prompt tokens already in the cache (chunked path)
    generated: List[int] = dataclasses.field(default_factory=list)
    # Per-token logprob records, parallel to ``generated`` (only when
    # ``logprobs`` > 0).
    logprob_data: List[dict] = dataclasses.field(default_factory=list)
    # Observability timestamps (engine-internal).
    submit_t: float = 0.0
    last_emit_t: float = 0.0


@dataclasses.dataclass
class _FusedMeta:
    """Host-side bookkeeping for one FUSED (chunk-carrying) pipeline
    lane: which prefilling rows rode the dispatch, whether each one's
    prompt finished inside it, and the device prompt-end logits buffer
    plus the per-row sampling params/keys the consume needs to emit
    first tokens. ``rows`` entries are (chunk_row_index, slot, req,
    completed)."""

    rows: list
    fin_logits: Any
    nonces: np.ndarray
    positions: np.ndarray
    temps: np.ndarray
    top_ks: np.ndarray
    top_ps: np.ndarray


@dataclasses.dataclass
class _Inflight:
    """One dispatched-but-unconsumed block (a pipeline lane).

    ``outs`` are DEVICE arrays still streaming home; ``last``/``lens``
    are the block's final token/position carry, kept on device so the
    next block can chain off them without a host round trip. The
    sampling lane arrays ride along because a chained dispatch reuses
    them verbatim -- no host state changed between the two dispatches,
    so re-packing would produce identical arrays anyway. At
    pipeline_depth=N up to N of these sit queued in the engine's lane
    deque (oldest first) behind the block being consumed.

    Three lane kinds share the deque: pure decode blocks, FUSED
    chunk+decode blocks (``fused`` carries the chunk bookkeeping;
    ``n`` counts their decode steps), and SPECULATIVE blocks
    (``spec_m`` > 0; ``outs`` is the (tokens, counts) pair, ``n`` is
    the worst-case m*(k+1) token exposure, and ``hist_dev`` carries
    the device-resident token history a chained spec block drafts
    from).
    """

    n: int
    outs: Any
    last: Any
    lens: Any
    temps: Any
    top_ks: Any
    top_ps: Any
    nonces: Any
    filtered: bool
    want_lp: bool
    slots: tuple
    fused: Optional[_FusedMeta] = None
    spec_m: int = 0
    hist_dev: Any = None


class GenerationEngine:
    """Slot-based continuous-batching generation over a Llama checkpoint.

    Synchronous core (``submit`` + ``step``) driven by a scheduler thread
    (``start``); jit dispatch blocks, so the thread model matches JAX's
    execution model rather than fighting asyncio.
    """

    def __init__(
        self,
        preset: str = "llama-tiny",
        params: Optional[dict] = None,
        max_slots: int = 8,
        max_seq: Optional[int] = None,
        seed: int = 0,
        config: Optional[LlamaConfig] = None,
        decode_block: int = 8,
        mesh: Optional[jax.sharding.Mesh] = None,
        tensor_parallel: int = 1,
        prefill_chunk: int = 0,
        max_prefill_tokens: int = 8192,
        prefill_decode_steps: Optional[int] = None,
        prefix_cache_mb: int = 0,
        prefix_block: int = 128,
        speculative_k: int = 0,
        decode_attn_kernel: bool = False,
        quantize: Optional[str] = None,
        kv_quant: Optional[str] = None,
        streaming_init: bool = False,
        pipeline_depth: int = 1,
        drain_overshoot_bound: Optional[int] = None,
        continuous_batching: bool = True,
        draft_config: Optional[LlamaConfig] = None,
        draft_params: Optional[dict] = None,
        draft_window: int = 64,
    ) -> None:
        # Max decode steps fused into one device program (power-of-2
        # sub-blocks keep the compile count bounded); 1 = per-token
        # dispatch.
        self.decode_block = max(1, decode_block)
        # Decode steps riding a PREFILL-carrying dispatch (the mixed scan
        # of _fused_block); chunks past this count ride the chunk-only
        # tail scan. Default: the full decode block. MEASURED (r4, axon
        # dispatch tunnel, Poisson 2.5rps mixed 256-1536 prompts):
        # clamping to 2 to shorten the TTFT-critical dispatch backfired
        # -- with most prompts chunked, decode advanced only 2 steps per
        # prefill dispatch, tpot rose 53->62ms, slots stayed occupied
        # longer, and queue wait blew TTFT p50 711->1459ms. On dispatch-
        # overhead-dominated links the block must keep riding along;
        # the knob stays for direct-attached chips where dispatch is
        # cheap and a smaller clamp genuinely trims TTFT.
        self.prefill_decode_steps = max(1, int(
            prefill_decode_steps if prefill_decode_steps is not None
            else self.decode_block
        ))
        # Chunked prefill: prompts longer than this are admitted into a
        # slot immediately and prefilled prefill_chunk tokens per step,
        # interleaved with decode blocks -- one long admission can then
        # stall active decoders for at most one chunk's duration instead
        # of the whole prompt. 0 disables (whole-prompt batched prefill).
        self.prefill_chunk = max(0, int(prefill_chunk))
        # Admission budget for one batched prefill program, in PADDED
        # tokens (K-bucket x len-bucket). The prefill's fp32 attention
        # scores are K*heads*S^2 -- a 16-request burst of 2048-token
        # prompts would materialize ~8 GB of scores and OOM the chip.
        # Overflow waits in a backlog and prefills next step (vLLM's
        # max_num_batched_tokens). A single over-budget prompt still
        # admits alone.
        self.max_prefill_tokens = max(0, int(max_prefill_tokens))
        # Prefix (KV) cache: 0 disables. Hits restore the shared rows
        # into the slot and prefill only the remainder through the fused
        # chunk machinery, so a remainder chunk size exists even in
        # whole-prompt mode.
        self.prefix_cache = (
            PrefixCache(prefix_block, prefix_cache_mb * (1 << 20))
            if prefix_cache_mb > 0 else None
        )
        self._chunk = self.prefill_chunk or 256
        # Continuous chunked-prefill batching (Sarathi-style): fused
        # dispatches carry a BOUNDED chunk budget (the tail shrinks
        # with decode occupancy -- see _dispatch_fused) so long prompts
        # prefill incrementally ACROSS pipelined decode dispatches
        # instead of finishing inside one barrier dispatch, and the
        # lane deque chains fused blocks without host round trips.
        # False restores the one-dispatch-per-prompt barrier (the A/B
        # baseline arm in bench_serving's mixed-continuous phase).
        self.continuous = bool(continuous_batching)
        # Speculative decoding: k draft tokens verified per step when
        # every active slot is greedy and logprob-free; 0 disables.
        # Drafting is prompt-lookup (_ngram_draft) by default, or a
        # distilled DRAFT MODEL when draft_config (+ optionally
        # draft_params; random init otherwise, for tests) is given --
        # see _spec_block / _draft_model_draft.
        self.speculative_k = max(0, int(speculative_k))
        self.draft_cfg = draft_config
        self.draft_weights = None
        self.draft_window = 0
        if draft_config is not None:
            if not self.speculative_k:
                raise ValueError("draft_config requires speculative_k > 0")
            if draft_params is None:
                import flax.linen as nn

                dmodel = Llama(
                    dataclasses.replace(draft_config, remat=False)
                )
                draft_params = nn.meta.unbox(jax.jit(dmodel.init)(
                    jax.random.PRNGKey(seed + 2),
                    jnp.zeros((1, 8), jnp.int32),
                ))
            self.draft_weights = pack_weights(draft_params, draft_config)
            self.draft_window = max(
                2, min(int(draft_window), draft_config.max_seq)
            )
        self.spec_steps = 0       # verify steps run
        self.spec_emitted = 0     # tokens those steps produced
        # Pallas bounded-span decode attention (ops/decode_attention.py).
        # Single-device only: under a TP mesh the sharded cache would
        # need a shard_map wrapper (not wired yet), so the block builder
        # ignores the flag when a mesh is configured.
        self.decode_attn_kernel = bool(decode_attn_kernel)
        # Weight-only int8 (see quantize_packed): halves weight HBM
        # bytes -- the decode bottleneck -- and the 8B resident
        # footprint. KV cache stays bf16 (attends exactly).
        if quantize not in (None, "", "int8"):
            raise ValueError(
                f"quantize={quantize!r}: supported values are 'int8'"
            )
        self.quantize = quantize or None
        # int8 KV cache (see _kv_quantize): rows quantize on write,
        # scales fold out of the attention matmuls on read. Independent
        # of weight quantization; composes with it.
        if kv_quant not in (None, "", "int8"):
            raise ValueError(
                f"kv_quant={kv_quant!r}: supported values are 'int8'"
            )
        self.kv_quant = kv_quant or None
        self._backlog: List[Request] = []  # engine-thread only
        cfg = config or PRESETS[preset]
        if max_seq is not None:
            cfg = dataclasses.replace(cfg, max_seq=max_seq)
        self.cfg = cfg
        self.max_slots = max_slots
        self.buckets = default_buckets(cfg.max_seq)
        # Tensor-parallel serving: a ``tensor``-axis mesh shards weights
        # and KV cache; the host-side scheduler below is unchanged.
        if mesh is None and tensor_parallel > 1:
            mesh = make_tp_mesh(tensor_parallel)
        self.mesh = mesh
        if mesh is not None:
            if "tensor" not in mesh.shape:
                raise ValueError(
                    "serving mesh needs a 'tensor' axis, got "
                    f"{tuple(mesh.axis_names)}"
                )
            _validate_tp(cfg, mesh.shape["tensor"])
        self.streaming_init = bool(streaming_init)
        if params is None and self.streaming_init:
            if self.quantize != "int8" or mesh is not None:
                raise ValueError(
                    "streaming_init requires quantize='int8' and no mesh "
                    "(its point is fitting a model whose bf16 tree "
                    "exceeds one chip; TP shards instead)"
                )
        if params is None and not self.streaming_init:
            # Demo mode: random init (serving tests; real use loads
            # orbax). With a mesh, init sharded from birth — the full
            # tree never exists on one device. (streaming_init skips
            # this entirely: at 8B the fp32 init tree alone is 32 GB.)
            if mesh is not None:
                _, msh, init_fn = abstract_param_targets(cfg, mesh)
                params = jax.jit(init_fn, out_shardings=msh)(
                    jax.random.PRNGKey(seed)
                )
            else:
                import flax.linen as nn

                model = Llama(dataclasses.replace(cfg, remat=False))
                raw = jax.jit(model.init)(
                    jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32)
                )
                params = nn.meta.unbox(raw)
        if mesh is None and params is None and self.streaming_init:
            # Already quantized leaf-by-leaf; nothing else to build.
            self.weights = quantized_random_init(cfg, seed)
        elif mesh is None:
            if self.quantize == "int8":
                # Cast+quantize in ONE jit over the checkpoint-dtype
                # tree: the bf16 intermediates are program-internal, so
                # peak load HBM is ~checkpoint + int8 -- never the full
                # bf16 tree (which alone wouldn't fit 8B on one 16 GiB
                # chip). NOT donated: pack_weights(cast=False) aliases
                # caller params, and donating aliased buffers deletes
                # them under the caller.
                self.weights = jax.jit(
                    lambda raw: quantize_packed(_cast_packed(raw, cfg))
                )(pack_weights(params, cfg, cast=False))
            else:
                self.weights = pack_weights(params, cfg)
        else:
            # Shard-first, cast-on-mesh: each leaf goes to its devices in
            # checkpoint dtype (a no-op for leaves orbax already restored
            # sharded), then one donated jit casts shard-locally. The
            # full serving-dtype tree never exists on a single device.
            raw = pack_weights(params, cfg, cast=False)
            wsh = tp_weight_shardings(mesh, raw)
            placed = jax.tree.map(jax.device_put, raw, wsh)
            # NOT donated: device_put aliases caller buffers whenever a
            # leaf is already on its target devices (e.g. the replicated
            # norm scales), and donating aliased buffers deletes them
            # under the caller -- same hazard as the non-mesh quantize
            # path below. The transient is one extra SHARDED copy during
            # the cast (per-chip: ~2x the shard, not 2x the model),
            # which the 8B-on-v5e-4 budget absorbs.
            self.weights = jax.jit(
                partial(_cast_packed, cfg=cfg), out_shardings=wsh,
            )(placed)
            if self.quantize == "int8":
                # Quantize on-mesh: "q" leaves keep the kernel's spec
                # (rank-preserving), per-output-channel "s" vectors fall
                # back to replicated via spec_for's rank check -- tiny,
                # and the scaled multiply stays shard-local under GSPMD.
                # Donation is safe HERE: the cast jit's outputs are
                # exclusively ours.
                qfn = jax.jit(
                    quantize_packed,
                    donate_argnums=0,
                    out_shardings=tp_weight_shardings(
                        mesh,
                        jax.eval_shape(quantize_packed, self.weights),
                    ),
                )
                self.weights = qfn(self.weights)

        kvshape = (cfg.n_layers, max_slots, cfg.max_seq, cfg.n_kv_heads,
                   cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)

        def _zeros(shape, dtype, sharding):
            if sharding is not None:
                return jnp.zeros(shape, dtype, device=sharding)
            return jnp.zeros(shape, dtype)

        qsh = tp_cache_sharding(mesh) if mesh is not None else None
        if self.kv_quant == "int8":
            ssh = tp_kv_scale_sharding(mesh) if mesh is not None else None
            # Scales store LANE-ALIGNED [L, B, KV, Smax]: Smax (a 128
            # multiple) on the lanes, KV against the 8-sublane tile, so
            # the f32 slab allocates ~its data bytes instead of the 16x
            # (8,128)-tile blowup of [L, B, Smax, KV] (measured r5:
            # 64 MB -> 1.00 GB per cache at 32 slots x Smax 2048).
            sshape = (cfg.n_layers, max_slots, cfg.n_kv_heads,
                      cfg.max_seq)
            self.cache_k = {"q": _zeros(kvshape, jnp.int8, qsh),
                            "s": _zeros(sshape, jnp.float32, ssh)}
            self.cache_v = {"q": _zeros(kvshape, jnp.int8, qsh),
                            "s": _zeros(sshape, jnp.float32, ssh)}
        else:
            self.cache_k = _zeros(kvshape, dt, qsh)
            self.cache_v = _zeros(kvshape, dt, qsh)
        self.lengths = np.zeros(max_slots, np.int64)  # host-side bookkeeping
        # Token history per slot (prompt + generated), the draft source
        # for speculative decoding; host is the source of truth and the
        # device copy is re-uploaded per spec dispatch (128 KB at 16x2k).
        self.hist = (
            np.zeros((max_slots, cfg.max_seq), np.int32)
            if self.speculative_k else None
        )
        self.free_slots = list(range(max_slots))
        self.active: Dict[int, Request] = {}
        self.prefilling: Dict[int, Request] = {}  # slot -> mid-prefill req
        self.pending: "queue.Queue[Request]" = queue.Queue()
        self._rng = jax.random.PRNGKey(seed + 1)

        self._build_dispatch()

        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self.tokens_generated = 0
        self.requests_finished = 0
        self.ttft_hist = LatencyHistogram()
        self.itl_hist = LatencyHistogram()
        # Live TTFT EMA (ms): the router's load signal (docs/FLEET.md).
        # The histogram answers distribution questions after the fact;
        # routing needs one current number per replica, cheap to read
        # from the scrape thread.
        self.ttft_ms_ema: Optional[float] = None
        # -- overlapped dispatch pipeline ------------------------------
        # 0 = fully sequential (dispatch, sync, consume); N >= 1 keeps
        # up to N decode blocks in flight behind the one being consumed,
        # each chained off the previous block's device-resident carry.
        # Depth 1 hides one block's host consume; deeper lanes cover
        # consumes that occasionally outlast a block (logprob-heavy
        # batches, slow stream callbacks, dispatch-tunnel jitter) at the
        # cost of more discarded overshoot when a drain hits -- which
        # drain_overshoot_bound caps.
        self.pipeline_depth = max(0, int(pipeline_depth))
        # Device-computed tokens at risk BEYOND the block being consumed
        # (what a mid-flight finish throws away per freed lane, per
        # drain). _pipeline_fill shrinks chained blocks to fit the
        # remaining budget, so deep pipelines queue smaller blocks near
        # the bound instead of stalling. None -> 2 * decode_block (depth
        # 1 is never clamped: one queued block always fits); <= 0
        # disables the bound -- visible in overshoot_max_per_drain,
        # which the perf ratchet (analysis/perf_baseline.json) caps.
        if drain_overshoot_bound is None:
            drain_overshoot_bound = 2 * self.decode_block
        self.drain_overshoot_bound = int(drain_overshoot_bound)
        # Per-request sampling nonces (see _decode_block): a plain
        # itertools counter -- CPython-atomic, so submit() needs no lock.
        self._req_counter = itertools.count()
        # Base key for ALL per-row sampling (decode steps AND first
        # tokens): every draw is keyed by (request nonce, position)
        # folded into this one constant, so a token's value is
        # independent of batch composition, chunking, pipelining, and
        # dispatch count. The stateful _next_rng split chain is no
        # longer consumed by any sampling path (kept for external
        # callers that want a fresh engine-seeded key).
        self._decode_rng = jax.random.fold_in(
            jax.random.PRNGKey(seed), 0xDEC0DE
        )
        # Queued in-flight lanes, oldest first (consumed FIFO). Length
        # is bounded by pipeline_depth; stats() exports it live as
        # dispatch_inflight.
        self._inflight: collections.deque = collections.deque()
        self._drain_reason = ""  # why _pipeline_next last returned 0
        self._gap_t: Optional[float] = None
        self.decode_dispatches = 0
        # Blocks whose outputs were materialized on the host. Trails
        # decode_dispatches by len(_inflight); the host-sync audit's
        # steady-state denominator (a window can consume blocks that
        # were dispatched before it opened).
        self.decode_blocks_consumed = 0
        self.host_gap_ms_ema: Optional[float] = None
        self.overshoot_tokens_discarded = 0
        # Largest queued-lane discard of any single drain event (the
        # depth-dependent part of overshoot; head-block overshoot exists
        # at depth 0 too and is excluded).
        self.overshoot_max_per_drain = 0
        # Prompts whose chunked prefill completed (the row moved
        # prefilling -> active at a fused-lane consume). A bump during
        # a pipelined consume triggers a drain so the fresh row joins
        # the decode lanes at the very next dispatch.
        self.prefill_activations = 0


    def _build_dispatch(self) -> None:
        """(Re)build every jit dispatch closure against the CURRENT
        mesh / weights / caches. ``__init__`` calls this once; the
        serving-plane reshard (serving/kv_reshard.py) calls it again
        after moving the engine's state onto a different TP mesh -- the
        old compiled programs close over the old shardings and must be
        dropped wholesale. Host scheduler state (slots, lengths, RNG
        chains, in-flight requests) is untouched, which is what lets a
        quiesced resplit resume decode bit-exactly."""
        cfg = self.cfg
        mesh = self.mesh
        # Pin cache outputs to the KV-head sharding under TP: without the
        # constraint GSPMD may pick a different (e.g. head-dim) layout for
        # the donated outputs, leaving the cache off its intended layout.
        if mesh is not None:
            csh = tp_cache_sharding(mesh)
            scale_sh = tp_kv_scale_sharding(mesh)

            def _pin(t):
                if isinstance(t, dict):  # int8 cache: pin each leaf
                    return {
                        "q": jax.lax.with_sharding_constraint(t["q"], csh),
                        "s": jax.lax.with_sharding_constraint(
                            t["s"], scale_sh),
                    }
                return jax.lax.with_sharding_constraint(t, csh)
        else:
            def _pin(t):
                return t

        # cfg is a static closure (hashable primitives); weights are
        # ARGUMENTS so multi-GB params are buffers, not jaxpr constants.
        prefill_jit = jax.jit(partial(_prefill, cfg))
        block_jits = {}

        # Under int8 KV the kernel routes to decode_attention_int8
        # (int8 DMA + VMEM dequant) -- on that path the kernel is not
        # just bounded-span, it is the only reader that avoids XLA
        # materializing a bf16 copy of the cache.
        use_kernel = self.decode_attn_kernel and self.mesh is None
        if (use_kernel and self.kv_quant
                and jax.default_backend() == "tpu"
                and (cfg.n_kv_heads % 4 or cfg.head_dim % 128)):
            # Mosaic's int8 VMEM tiling needs KV a multiple of 4 and a
            # 128-lane head_dim (llama-tiny's KV=2 fails to compile,
            # measured r4); fall back to the XLA quantized path rather
            # than crash the server at warmup.
            use_kernel = False

        def _block_fn(n, filtered, want_lp, masked=False):
            def fn(w, ck, cv, toks, lens, rng, temps, top_ks, top_ps,
                   nonces, *mask):
                outs, ck, cv, last, lens = _decode_block(
                    cfg, n, filtered, want_lp, w, ck, cv, toks, lens,
                    rng, temps, top_ks, top_ps, nonces,
                    kernel=use_kernel, mask=mask[0] if masked else None,
                )
                return outs, _pin(ck), _pin(cv), last, lens
            return fn

        def decode_block_call(n, filtered, want_lp, ck, cv, toks, lens,
                              rng, temps, top_ks, top_ps, nonces,
                              mask=None):
            # ``masked`` is part of the jit key: the unmasked program
            # (the common path) compiles byte-identical to before.
            self._note_dispatch(decode=True)
            masked = mask is not None
            key = (n, filtered, want_lp, masked)
            if key not in block_jits:
                block_jits[key] = jax.jit(
                    _block_fn(n, filtered, want_lp, masked),
                    donate_argnums=(1, 2),
                )
            extra = (jnp.asarray(mask),) if masked else ()
            return block_jits[key](self.weights, ck, cv, toks, lens, rng,
                                   temps, top_ks, top_ps, nonces, *extra)

        self._decode_block_call = decode_block_call

        fused_jits = {}

        def fused_call(n, m, klen, filtered, want_lp, ck, cv, toks,
                       lens, ctoks, coffs, cclens, cslots, rng, temps,
                       top_ks, top_ps, nonces, mask=None):
            self._note_dispatch(decode=False)
            masked = mask is not None
            key = (n, m, klen, ctoks.shape[1], filtered, want_lp, masked)
            if key not in fused_jits:
                def fn(w, ck, cv, toks, lens, ctoks, coffs, cclens,
                       cslots, rng, temps, top_ks, top_ps, nonces, *mk):
                    outs, fin, ck, cv, last, lens = _fused_block(
                        cfg, n, m, self._chunk, klen, filtered,
                        want_lp, w, ck, cv, toks, lens, ctoks, coffs,
                        cclens, cslots, rng, temps, top_ks, top_ps,
                        nonces, mask=mk[0] if masked else None,
                    )
                    return outs, fin, _pin(ck), _pin(cv), last, lens
                fused_jits[key] = jax.jit(fn, donate_argnums=(1, 2))
            extra = (jnp.asarray(mask),) if masked else ()
            return fused_jits[key](self.weights, ck, cv, toks, lens,
                                   ctoks, coffs, cclens, cslots, rng,
                                   temps, top_ks, top_ps, nonces,
                                   *extra)

        self._fused_call = fused_call

        spec_jits = {}
        draft_static = (
            (self.draft_cfg, self.draft_window)
            if self.draft_weights is not None else None
        )

        def spec_call(m, ck, cv, toks, lens, hist):
            self._note_dispatch(decode=False)
            if m not in spec_jits:
                def fn(w, dw, ck, cv, toks, lens, hist):
                    outs, counts, ck, cv, last, lens, hist = _spec_block(
                        cfg, m, self.speculative_k, w, ck, cv, toks,
                        lens, hist, draft=draft_static, draft_w=dw,
                    )
                    return (outs, counts, _pin(ck), _pin(cv), last,
                            lens, hist)
                spec_jits[m] = jax.jit(fn, donate_argnums=(2, 3))
            return spec_jits[m](self.weights, self.draft_weights, ck,
                                cv, toks, lens, hist)

        self._spec_call = spec_call

        # First-token sampling for prefill completions (batched and
        # chunked): per-row keys fold_in(fold_in(base, nonce),
        # prompt_len - 1) -- the position of the prompt-end logits row,
        # one below the first decode step's key, so a request's draws
        # depend only on (request, position) from its very first token.
        # That closes the last batch-composition dependence: chunked,
        # batched, and prefix-restored admissions all sample the same
        # first token for the same request.
        first_jits = {}

        def first_tokens_call(logits, nonces, positions, temps,
                              top_ks, top_ps):
            filtered = bool(
                (np.asarray(top_ks) > 0).any()
                or (np.asarray(top_ps) < 1.0).any()
            )
            if filtered not in first_jits:
                def fn(rng, lg, nonces, poss, temps, tks, tps,
                       filt=filtered):
                    keys = jax.vmap(
                        lambda nc, p: jax.random.fold_in(
                            jax.random.fold_in(rng, nc), p
                        )
                    )(nonces, poss)
                    return _sample_rows(lg, keys, temps,
                                        tks if filt else None,
                                        tps if filt else None)
                first_jits[filtered] = jax.jit(fn)
            return first_jits[filtered](
                self._decode_rng, logits,
                jnp.asarray(nonces, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(top_ks, jnp.int32),
                jnp.asarray(top_ps, jnp.float32),
            )

        self._first_tokens = first_tokens_call

        def _insert_pinned(cache_k, cache_v, k_seq, v_seq, slots):
            ck, cv = _insert(cache_k, cache_v, k_seq, v_seq, slots)
            return _pin(ck), _pin(cv)

        insert_jit = jax.jit(_insert_pinned, donate_argnums=(0, 1))

        # Prefix-cache device ops: extract copies a slot's leading KV
        # rows out (NOT donated -- the live cache stays); restore
        # scatters a stored prefix into a fresh slot. Keyed by static
        # lengths (block multiples, so the compile count is bounded by
        # max_seq/prefix_block).
        extract_jits = {}

        def extract_call(plen, slot):
            if plen not in extract_jits:
                def fn(ck, cv, s):
                    idx = (slice(None), s, slice(None, plen))
                    return _kv_index(ck, idx), _kv_index(cv, idx)
                extract_jits[plen] = jax.jit(fn)
            return extract_jits[plen](self.cache_k, self.cache_v, slot)

        self._extract_call = extract_call
        restore_jits = {}

        def restore_call(ck, cv, pk, pv, slot, plen):
            key = (plen, _kv_rows_len(pk))
            if key not in restore_jits:
                def fn(ck, cv, pk, pv, s):
                    idx = (slice(None), s, slice(None, plen))
                    if isinstance(ck, dict):
                        # Stored rows are already quantized (extracted
                        # from a quantized cache): raw copy, no requant.
                        # Scale rows live lane-aligned [L, KV, plen'].
                        sidx = _scale_index(idx)
                        ck = {"q": ck["q"].at[idx].set(pk["q"][:, :plen]),
                              "s": ck["s"].at[sidx].set(
                                  pk["s"][:, :, :plen])}
                        cv = {"q": cv["q"].at[idx].set(pv["q"][:, :plen]),
                              "s": cv["s"].at[sidx].set(
                                  pv["s"][:, :, :plen])}
                    else:
                        ck = ck.at[idx].set(pk[:, :plen])
                        cv = cv.at[idx].set(pv[:, :plen])
                    return _pin(ck), _pin(cv)
                restore_jits[key] = jax.jit(fn, donate_argnums=(0, 1))
            return restore_jits[key](ck, cv, pk, pv, slot)

        self._restore_call = restore_call
        sample_plain = jax.jit(lambda lg, rng, t: _sample(lg, rng, t))
        sample_filtered = jax.jit(_sample)

        def sample_call(logits, rng, temps, top_ks, top_ps):
            # Host-side static dispatch, same rationale as the decode
            # block's ``filtered`` key.
            if (np.asarray(top_ks) > 0).any() or (
                np.asarray(top_ps) < 1.0
            ).any():
                return sample_filtered(logits, rng, temps, top_ks, top_ps)
            return sample_plain(logits, rng, temps)

        def _prefill_call(tokens, lengths):
            # Accept a scalar for the single-prompt case (tests/oracles).
            self._note_dispatch(decode=False)
            lengths = jnp.atleast_1d(jnp.asarray(lengths, jnp.int32))
            return prefill_jit(self.weights, tokens, lengths)

        self._prefill = _prefill_call
        self._insert = insert_jit
        self._sample = sample_call
        # Introspection surface for analysis.jaxpr_audit: the live jit
        # objects (the dicts are the same mutable caches the dispatch
        # closures fill in), so donation/recompile invariants can be
        # checked against exactly what serves traffic.
        self._jit_registry = {
            "prefill": prefill_jit,
            "insert": insert_jit,
            "decode_block": block_jits,
            "fused": fused_jits,
            "spec": spec_jits,
            "extract": extract_jits,
            "restore": restore_jits,
            "first_tokens": first_jits,
        }

    # -- scheduling core ---------------------------------------------------

    def submit(self, req: Request) -> Future:
        req.future = req.future or Future()
        if not req.prompt:
            req.future.set_exception(ValueError("empty prompt"))
            return req.future
        if len(req.prompt) >= self.cfg.max_seq:
            req.future.set_exception(
                ValueError(
                    f"prompt length {len(req.prompt)} >= max_seq {self.cfg.max_seq}"
                )
            )
            return req.future
        req.submit_t = time.perf_counter()
        req.nonce = next(self._req_counter)
        if trace.enabled():
            # Cross-thread span: B here (submitter), E in _admit (engine
            # thread) -- same explicit per-request track keeps the pair
            # balanced under async interleaving.
            trace.begin("queue-wait", plane="serving",
                        track=f"req/{req.nonce}", nonce=req.nonce,
                        prompt_len=len(req.prompt))
        self.pending.put(req)
        self._wake.set()
        return req.future

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _admit(self) -> None:
        """Admit pending requests into free slots, prefilling them in
        BATCHES: all admissible prompts pad to one (K-bucket x len-bucket)
        shape and run as a single device program, then one scatter writes
        every sequence's KV into its slot. Serial per-prompt prefill was
        the throughput bottleneck at high request rates (one dispatch +
        an underfilled MXU per prompt)."""
        if not (self.free_slots and (
                self._backlog or not self.pending.empty())):
            return  # nothing to admit: no span either (step() calls every tick)
        with trace.span("admit", plane="serving", track="engine"):
            self._admit_batches()

    def _admit_batches(self) -> None:
        while self.free_slots and (
            self._backlog or not self.pending.empty()
        ):
            reqs: List[Request] = []
            took_chunked = False
            deferred = False
            while len(reqs) < len(self.free_slots):
                if self._backlog:
                    req = self._backlog.pop(0)
                else:
                    try:
                        req = self.pending.get_nowait()
                    except queue.Empty:
                        break
                if req.future.cancelled():
                    if trace.enabled():
                        trace.end("queue-wait", plane="serving",
                                  track=f"req/{req.nonce}", cancelled=True)
                    continue
                if self.prefix_cache is not None:
                    # Longest cached block-aligned prefix, capped at
                    # len-1 so a remainder always exists to produce the
                    # prompt-end logits (the first token's distribution).
                    plen, entry = self.prefix_cache.lookup(
                        req.prompt, len(req.prompt) - 1
                    )
                    if plen:
                        slot = self.free_slots.pop()
                        if trace.enabled():
                            trace.end("queue-wait", plane="serving",
                                      track=f"req/{req.nonce}")
                            trace.instant("prefix-cache.hit",
                                          plane="serving", track="engine",
                                          nonce=req.nonce, plen=plen)
                        with trace.span("prefix.restore", plane="serving",
                                        track="engine", plen=plen):
                            self.cache_k, self.cache_v = self._restore_call(
                                self.cache_k, self.cache_v, entry["k"],
                                entry["v"], jnp.int32(slot), plen,
                            )
                        req.slot = slot
                        req.prefilled = plen
                        self.prefilling[slot] = req
                        took_chunked = True
                        continue
                if (self.prefill_chunk
                        and len(req.prompt) > self.prefill_chunk):
                    # Long prompt: claim a slot now, prefill chunk-by-
                    # chunk across steps (_fused_step) so admission
                    # never stalls decoding slots for the whole prompt.
                    req.slot = self.free_slots.pop()
                    if trace.enabled():
                        trace.end("queue-wait", plane="serving",
                                  track=f"req/{req.nonce}", chunked=True)
                    req.prefilled = 0
                    self.prefilling[req.slot] = req
                    took_chunked = True
                    continue
                if reqs and self.max_prefill_tokens:
                    # Padded-token budget for ONE prefill program (the
                    # fp32 scores scale with K x S^2). Over-budget: run
                    # what we have; the deferred request leads the next
                    # batch.
                    k = _pow2_bucket(len(reqs) + 1)
                    s = max(self._bucket(len(r.prompt))
                            for r in reqs + [req])
                    if k * s > self.max_prefill_tokens:
                        # Still queued: its queue-wait span stays open.
                        self._backlog.insert(0, req)
                        deferred = True
                        break
                if trace.enabled():
                    trace.end("queue-wait", plane="serving",
                              track=f"req/{req.nonce}")
                reqs.append(req)
            if not reqs:
                if took_chunked or deferred:
                    continue
                return
            k_real = len(reqs)
            kbucket = _pow2_bucket(k_real)
            bucket = max(self._bucket(len(r.prompt)) for r in reqs)
            with trace.span("prefill.batch", plane="serving",
                            track="engine", k=k_real, kbucket=kbucket,
                            bucket=bucket):
                padded = np.zeros((kbucket, bucket), np.int32)
                lengths = np.ones(kbucket, np.int32)  # dummy rows: 1 token
                for j, r in enumerate(reqs):
                    padded[j, : len(r.prompt)] = r.prompt
                    lengths[j] = len(r.prompt)
                logits, ks, vs = self._prefill(jnp.asarray(padded), lengths)
                slots = [self.free_slots.pop() for _ in reqs]
                # Keep kbucket shapes end-to-end (bounded compile count):
                # dummy rows scatter to an out-of-range slot (dropped) and
                # sample greedily into a discarded lane.
                padded_slots = np.full(kbucket, self.max_slots, np.int32)
                padded_slots[:k_real] = slots
                self.cache_k, self.cache_v = self._insert(
                    self.cache_k, self.cache_v, ks, vs,
                    jnp.asarray(padded_slots),
                )
                temps = np.zeros(kbucket, np.float32)
                top_ks = np.zeros(kbucket, np.int32)
                top_ps = np.ones(kbucket, np.float32)
                nonces = np.zeros(kbucket, np.int32)
                poss = np.zeros(kbucket, np.int32)
                for j, r in enumerate(reqs):
                    temps[j] = r.temperature
                    top_ks[j] = r.top_k
                    top_ps[j] = r.top_p
                    nonces[j] = r.nonce
                    poss[j] = len(r.prompt) - 1
                # Per-(nonce, position) keys, NOT the _next_rng chain:
                # the same request draws the same first token whether it
                # admits batched here or chunked through _fused_block.
                first = np.asarray(self._first_tokens(
                    logits, nonces, poss, temps, top_ks, top_ps,
                ))
                logits_np = None
                for j, (req, slot) in enumerate(zip(reqs, slots)):
                    req.slot = slot
                    self.lengths[slot] = len(req.prompt)
                    if self.hist is not None:
                        self.hist[slot, :len(req.prompt)] = req.prompt
                    self.active[slot] = req
                    self._maybe_capture_prefix(req)
                    if req.logprobs or req.constraint is not None:
                        if logits_np is None:
                            logits_np = np.asarray(logits, np.float32)
                    tok = (self._host_first_token(logits_np[j], req)
                           if req.constraint is not None else int(first[j]))
                    if req.logprobs:
                        req.logprob_data.append(_host_logprobs(
                            logits_np[j], tok, req.logprobs
                        ))
                    self._emit(req, tok)

    def _maybe_capture_prefix(self, req: Request) -> None:
        """Donate a freshly prefilled slot's leading KV rows to the
        prefix cache (block-multiple length). Called at prefill
        completion, while rows [0, prompt_len) are pristine -- decode
        for this slot hasn't run yet. The chain-hash dedupe check runs
        first so the repeated-prefix hot path costs no device gather."""
        pc = self.prefix_cache
        if pc is None:
            return
        plen = (len(req.prompt) // pc.block) * pc.block
        if plen < pc.block:
            return
        hashes = pc.chain_hashes(req.prompt, plen)
        if hashes and hashes[-1][1] in pc.entries:
            return
        pk, pv = self._extract_call(plen, jnp.int32(req.slot))
        pc.insert(req.prompt, pk, pv)

    # -- disaggregated prefill/decode (serving/router.py) ------------------
    #
    # A prefill replica runs ensure_prefix + export_prefix; the packet
    # travels through router.pack_kv_packet's wire format; a decode
    # replica runs import_prefix and then serves the original request,
    # whose admission hits the imported entry and takes the normal
    # prefix-restore + remainder-prefill path. The arrays cross AS
    # STORED (int8 kv_quant: q [L,P,KV,D] + lane-aligned f32 scales
    # [L,KV,Smax]), so decode after a handoff is bit-identical to
    # decode after a local capture of the same prefix.

    def ensure_prefix(self, prompt: Sequence[int],
                      timeout: float = 120.0) -> int:
        """Prefill ``prompt`` into the prefix cache without serving it:
        the prefill-replica entry point. Returns the covered
        (block-multiple) length, 0 when the prompt is under one block
        or the entry didn't fit the cache budget. Runs the engine
        inline when no engine thread is live (tests/benches)."""
        pc = self.prefix_cache
        if pc is None:
            raise RuntimeError("ensure_prefix needs prefix_cache_mb > 0")
        plen = (len(prompt) // pc.block) * pc.block
        if plen < pc.block:
            return 0
        full = pc.chain_hashes(prompt, plen)[-1][1]
        if full in pc.entries:
            return plen
        # One generated token is the cheapest admission that completes
        # prefill (capture happens at prefill completion); greedy so
        # the sampling RNG chain is irrelevant.
        fut = self.submit(Request(prompt=list(prompt), max_new_tokens=1,
                                  temperature=0.0))
        if self._thread is not None and self._thread.is_alive():
            fut.result(timeout)
        else:
            deadline = time.perf_counter() + timeout
            while not fut.done():
                if time.perf_counter() > deadline:
                    raise TimeoutError("ensure_prefix prefill timed out")
                self.step()
            fut.result()
        return plen if full in pc.entries else 0

    def export_prefix(self, prompt: Sequence[int]) -> Optional[dict]:
        """Longest cached prefix of ``prompt`` as host arrays:
        {"tokens", "plen", "k", "v"} ready for router.pack_kv_packet,
        or None on a cache miss."""
        pc = self.prefix_cache
        if pc is None:
            return None
        plen, entry = pc.lookup(prompt, len(prompt))
        if not plen:
            return None
        return {
            "tokens": list(prompt[:plen]),
            "plen": plen,
            "k": jax.device_get(entry["k"]),
            "v": jax.device_get(entry["v"]),
        }

    def import_prefix(self, packet: dict) -> int:
        """Adopt an unpacked handoff packet (router.unpack_kv_packet)
        into this engine's prefix cache. Validates block granularity
        and KV layout against this engine's configuration -- a bf16
        packet cannot land in an int8 cache (and vice versa): restore
        scatters raw rows, so a layout mismatch would corrupt the
        slot. Returns the covered length actually inserted."""
        pc = self.prefix_cache
        if pc is None:
            raise RuntimeError("import_prefix needs prefix_cache_mb > 0")
        if packet["block"] != pc.block:
            raise ValueError(
                f"packet block {packet['block']} != engine prefix_block "
                f"{pc.block}"
            )
        quantized = isinstance(packet["k"], dict)
        if quantized != (self.kv_quant == "int8"):
            raise ValueError(
                f"packet layout {packet['layout']!r} does not match "
                f"engine kv_quant={self.kv_quant!r}"
            )

        def _dev(rows):
            if isinstance(rows, dict):
                return {"q": jnp.asarray(rows["q"]),
                        "s": jnp.asarray(rows["s"])}
            return jnp.asarray(rows)

        tokens = packet["tokens"]
        pc.insert(tokens, _dev(packet["k"]), _dev(packet["v"]))
        full = pc.chain_hashes(tokens, packet["plen"])[-1][1]
        return packet["plen"] if full in pc.entries else 0

    def _pack_constraint_mask(self):
        """[max_slots, vocab] bool of legal next tokens, or None when no
        active slot is constrained (the common case: the unmasked jit
        variants run and the mask upload is skipped entirely)."""
        reqs = [r for r in self.active.values() if r.constraint is not None]
        if not reqs:
            return None
        m = np.ones((self.max_slots, self.cfg.vocab_size), bool)
        for req in reqs:
            # Effective remaining = token budget AND cache headroom
            # (whichever ends the request first bounds the closure).
            allowed = req.constraint.mask(min(
                req.max_new_tokens - len(req.generated),
                self.cfg.max_seq - int(self.lengths[req.slot]),
            ))
            m[req.slot, :] = False
            m[req.slot, :allowed.size] = allowed
        return m

    def _host_first_token(self, row: np.ndarray, req: Request) -> int:
        """First token of a CONSTRAINED request, sampled host-side from
        its prompt-end logits row (f32). Replicates _sample's semantics
        (mask -> temperature -> top-k -> top-p) for one row; first
        tokens are host events anyway, so no extra dispatch."""
        row = row.astype(np.float64).copy()
        allowed = req.constraint.mask(min(
            req.max_new_tokens, self.cfg.max_seq - len(req.prompt),
        ))
        row[:min(allowed.size, row.size)][~allowed[:row.size]] = -np.inf
        row[min(allowed.size, row.size):] = -np.inf
        if req.temperature <= 0:
            return int(row.argmax())
        z = row / max(req.temperature, 1e-6)
        order = np.argsort(-z)
        if req.top_k > 0:
            z[order[req.top_k:]] = -np.inf
        if req.top_p < 1.0:
            p = np.exp(z[order] - np.nanmax(z))
            p = p / p.sum()
            drop = (np.cumsum(p) - p) >= req.top_p
            # The top candidate always survives -- top_p=0 otherwise
            # drops EVERY token, and exp(-inf - -inf) = NaN would kill
            # the engine thread (the device _sample degrades to uniform
            # there; keeping argmax is the saner host behavior).
            drop[0] = False
            z[order[drop]] = -np.inf
        p = np.exp(z - z[order[0]])
        p = p / p.sum()
        gen = np.random.default_rng(
            (self.tokens_generated * 2654435761 + req.slot) & 0x7FFFFFFF
        )
        return int(gen.choice(row.size, p=p))

    def _pack_decode_lanes(self):
        """[max_slots] decode-lane arrays for the active slots; parked
        rows carry safe dummies (Smax-1 invariant documented below)."""
        tokens = np.zeros(self.max_slots, np.int32)
        temps = np.zeros(self.max_slots, np.float32)
        top_ks = np.zeros(self.max_slots, np.int32)
        top_ps = np.ones(self.max_slots, np.float32)
        # Non-active slots park at Smax-1: decode writes dummy K/V for
        # EVERY row, and position 0 of a mid-prefill slot already holds
        # real chunked-prefill state. Smax-1 garbage is safe for any
        # future occupant -- a row first becomes visible (mask: key <=
        # query position) in the very decode step that overwrites it.
        positions = np.full(self.max_slots, self.cfg.max_seq - 1, np.int32)
        nonces = np.zeros(self.max_slots, np.int32)
        for slot, req in self.active.items():
            tokens[slot] = req.generated[-1]
            temps[slot] = req.temperature
            top_ks[slot] = req.top_k
            top_ps[slot] = req.top_p
            # lengths[slot] already counts the last generated token, whose
            # K/V is not in the cache yet: its position is lengths-1.
            positions[slot] = max(int(self.lengths[slot]) - 1, 0)
            nonces[slot] = req.nonce
        filtered = any(
            req.top_k > 0 or req.top_p < 1.0
            for req in self.active.values()
        )
        return tokens, temps, top_ks, top_ps, positions, nonces, filtered

    def _emit_run(self, req: Request, toks: np.ndarray, lp=None) -> int:
        """Emit a run of consecutive decode tokens for ONE request and
        return how many were accepted (the caller discards the rest as
        overshoot). ``lp`` is the request's (logprobs [n], top_ids
        [n,K], top_logprobs [n,K]) slice when the dispatch carried
        logprob outputs.

        Fast path is vectorized numpy -- EOS via compare+flatnonzero,
        budget/headroom as mins, one bulk append -- with logprob
        records, histogram writes, latency observations, and on_token
        callbacks produced in exactly the order the per-token loop
        produced them. Host predicates (stop_fn / constraint) must see
        every token as it lands, so those requests take the per-token
        path unchanged."""
        n = len(toks)
        if req.stop_fn is not None or req.constraint is not None:
            for j in range(n):
                if lp is not None and req.logprobs:
                    kk = min(req.logprobs, LOGPROBS_K)
                    req.logprob_data.append({
                        "logprob": float(lp[0][j]),
                        "top_ids": lp[1][j, :kk].tolist(),
                        "top_logprobs": lp[2][j, :kk].tolist(),
                    })
                self._emit(req, int(toks[j]))
                if req.slot not in self.active:  # finished mid-run
                    return j + 1
            return n
        budget = req.max_new_tokens - len(req.generated)
        headroom = self.cfg.max_seq - int(self.lengths[req.slot])
        k = min(n, budget, headroom)
        if k <= 0:  # defensive: a no-budget request is already finished
            return 0
        done = k >= budget or k >= headroom
        if req.eos_id is not None:
            hits = np.flatnonzero(toks[:k] == req.eos_id)
            if hits.size:
                k = int(hits[0]) + 1
                done = True
        if lp is not None and req.logprobs:
            kk = min(req.logprobs, LOGPROBS_K)
            for j in range(k):
                req.logprob_data.append({
                    "logprob": float(lp[0][j]),
                    "top_ids": lp[1][j, :kk].tolist(),
                    "top_logprobs": lp[2][j, :kk].tolist(),
                })
        slot = req.slot
        base = int(self.lengths[slot])
        acc = toks[:k]
        first = not req.generated
        req.generated.extend(int(t) for t in acc)
        self.tokens_generated += k
        if self.hist is not None:
            end = min(base + k, self.cfg.max_seq)
            if end > base:
                self.hist[slot, base:end] = acc[:end - base]
        now = time.perf_counter()
        if first:
            self._note_ttft(now - req.submit_t)
            if trace.enabled():
                trace.instant("first-token", plane="serving",
                              track=f"req/{req.nonce}", nonce=req.nonce,
                              ttft_ms=round((now - req.submit_t) * 1e3, 3))
        else:
            # First token of the run carries the cross-dispatch gap;
            # the rest landed in the same block (the per-token loop
            # observed microseconds for them -- same bucket as 0).
            self.itl_hist.observe(now - req.last_emit_t)
        for _ in range(k - 1):
            self.itl_hist.observe(0.0)
        req.last_emit_t = now
        if req.on_token is not None:
            for t in acc:
                try:
                    req.on_token(int(t))
                except Exception:  # noqa: BLE001 - a bad stream sink must
                    logger.exception("on_token callback failed")  # not kill
        self.lengths[slot] += k
        if done:
            self._finish(req)
        return k

    def _emit_decode_outs(self, outs, want_lp: bool,
                          dispatch_slots=None) -> None:
        """Emit a dispatch's [n, B] decode tokens in step order; slots
        finishing mid-block drop their overshoot. With ``want_lp`` the
        dispatch also returned per-step logprob arrays, recorded
        parallel to each request's generated ids. ``dispatch_slots``
        (pipelined consume) is the active set AT DISPATCH TIME: a lane
        whose slot freed while the block was in flight is discarded
        whole -- garbage-safe by the parked-row invariant."""
        if want_lp:
            toks, lps, tids, tlps = (np.asarray(o) for o in outs)
        else:
            toks = np.asarray(outs)
        n = toks.shape[0]
        slots = (list(self.active) if dispatch_slots is None
                 else dispatch_slots)
        for slot in slots:
            req = self.active.get(slot)
            if req is None:  # freed mid-flight
                self.overshoot_tokens_discarded += n
                continue
            lp = None
            if want_lp and req.logprobs:
                lp = (lps[:, slot], tids[:, slot], tlps[:, slot])
            k = self._emit_run(req, toks[:, slot], lp)
            self.overshoot_tokens_discarded += n - k

    def _fused_step(self) -> None:
        """One mixed dispatch: n decode steps fused with prefill chunks
        (_fused_block). In continuous mode the chunk tail is BOUNDED by
        decode occupancy and the dispatch enters the lane deque like
        any decode block -- further fused blocks chain off its device
        carry (_pipeline_fill), so long prompts prefill incrementally
        across pipelined dispatches. With continuous_batching=False the
        whole prompt finishes inside this one dispatch (the prefill
        barrier) and the pipeline drains, the pre-continuous behavior."""
        with trace.span("prefill.fused", plane="serving", track="engine",
                        rows=len(self.prefilling)) as sp:
            self._fused_step_inner(sp)

    def _fused_step_inner(self, sp=trace._NULL_SPAN) -> None:
        mask = self._pack_constraint_mask()
        fl = self._dispatch_fused(mask=mask, sp=sp)
        if mask is not None:
            self._consume_block(fl, behind=False, drain="constraint-mask")
            return
        self._pipeline_advance(fl)

    def _dispatch_fused(self, tail: Optional[_Inflight] = None,
                        n_cap: Optional[int] = None, mask=None,
                        sp=trace._NULL_SPAN) -> _Inflight:
        """Build and dispatch ONE fused chunk+decode block over the
        current prefilling set. ``tail=None`` packs the decode lanes
        from host state (a fresh dispatch); otherwise the new block
        chains off ``tail``'s device-resident carry -- tokens and
        positions never touch the host, only the (host-known) chunk
        schedule is fresh. ``req.prefilled`` advances AT DISPATCH TIME:
        the chunk writes are unconditionally executed device work, so a
        later chained dispatch must schedule the NEXT chunks; only the
        prefilling->active transition (and first-token emission) waits
        for the consume (_consume_fused)."""
        if tail is None:
            (tokens, temps, top_ks, top_ps, positions, nonces,
             filtered) = self._pack_decode_lanes()
            want_lp = any(r.logprobs for r in self.active.values())
            toks_dev = jnp.asarray(tokens)
            pos_dev = jnp.asarray(positions)
            temps_dev = jnp.asarray(temps)
            tks_dev = jnp.asarray(top_ks)
            tps_dev = jnp.asarray(top_ps)
            nonces_dev = jnp.asarray(nonces)
            slots = tuple(self.active)
        else:
            toks_dev, pos_dev = tail.last, tail.lens
            temps_dev, tks_dev, tps_dev = (tail.temps, tail.top_ks,
                                           tail.top_ps)
            nonces_dev = tail.nonces
            filtered, want_lp = tail.filtered, tail.want_lp
            slots = tail.slots
        items = list(self.prefilling.items())
        c = self._chunk
        # Chunk-lane admission budget, same spirit (and knob) as the
        # batched-prefill token budget: each lane's attention scores are
        # heads x C x klen fp32, so K unbounded lanes at K=max_slots,
        # C=512, klen=2048 compile ~4 GB of temps and OOM the chip
        # (measured r4: the 32-slot mixed-throughput bench). Rows beyond
        # the budget simply keep their slot and ride the next dispatch.
        max_rows = max(1, self.max_prefill_tokens // c)
        items = items[:max_rows]
        need = max(
            -(-(len(req.prompt) - req.prefilled) // c) for _, req in items
        )
        # Mixed-scan step count: a power of 2 bounded by
        # prefill_decode_steps (every step here is on the new prompt's
        # TTFT critical path), the active slots' cache headroom (decode
        # lanes must not write past Smax-1... the scatter would drop,
        # but the step would be waste), and the chunk work (steps past
        # the last scheduled chunk run a garbage c-token chunk each).
        # The decode-budget bound is deliberately absent: chunk rows
        # need the steps regardless, and decode overshoot is discarded
        # host-side. Chained dispatches pass ``n_cap`` instead: host
        # lengths trail the device mid-pipeline, so the caller
        # (_pipeline_next) already discounted the in-flight tokens.
        cap = min(self.decode_block, self.prefill_decode_steps)
        if n_cap is not None:
            cap = min(cap, max(n_cap, 1))
        elif self.active:
            cap = min(cap, max(1, min(
                self.cfg.max_seq - int(self.lengths[slot])
                for slot in self.active
            )))
        if mask is not None:
            cap = 1  # constrained decode lanes: single-step dispatches
        n = 1
        while n * 2 <= cap and n < need:
            n *= 2
        # Chunk-only tail sizing is where continuous batching happens.
        # Legacy (continuous=False): the tail always covers the whole
        # remaining prompt -- one dispatch, the prefill barrier. In
        # continuous mode the tail budget SCALES WITH IDLE CAPACITY:
        # an idle engine still prefills whole prompts in one dispatch
        # (pure-TTFT, nothing to starve), but with decode slots active
        # each fused block only spends ~the idle fraction of the fleet
        # on extra chunk-only steps and the rest of the prompt rides
        # later (chained) fused blocks, so decode lanes keep emitting
        # every ~n steps instead of stalling for the whole prompt.
        rem = need - n
        if rem <= 0:
            m = 0
        elif self.continuous and self.active:
            idle = self.max_slots - len(self.active)
            allow = rem * idle // self.max_slots
            m = _pow2_bucket(min(allow, rem)) if allow > 0 else 0
        else:
            m = _pow2_bucket(rem)
        total = n + m
        kbucket = _pow2_bucket(len(items))
        ctoks = np.zeros((total, kbucket, c), np.int32)
        cclens = np.zeros((total, kbucket), np.int32)
        coffs = np.zeros(kbucket, np.int32)
        cslots = np.full(kbucket, self.max_slots, np.int32)  # dummies drop
        ctemps = np.zeros(kbucket, np.float32)
        ctop_ks = np.zeros(kbucket, np.int32)
        ctop_ps = np.ones(kbucket, np.float32)
        cnonces = np.zeros(kbucket, np.int32)
        cpos = np.zeros(kbucket, np.int32)
        rows = []
        max_end = 1
        for j, (slot, req) in enumerate(items):
            pos = req.prefilled
            coffs[j] = pos
            cslots[j] = slot
            ctemps[j] = req.temperature
            ctop_ks[j] = req.top_k
            ctop_ps[j] = req.top_p
            cnonces[j] = req.nonce
            # Prompt-end logits row position: the first-token sampling
            # key (consume side) pairs it with the request nonce.
            cpos[j] = len(req.prompt) - 1
            for s in range(total):
                take = min(c, len(req.prompt) - pos)
                if take <= 0:
                    break
                ctoks[s, j, :take] = req.prompt[pos:pos + take]
                cclens[s, j] = take
                pos += take
            # Real tokens bound klen; padding lanes attend garbage that's
            # discarded, so they don't need covering.
            max_end = max(max_end, pos)
            completed = pos >= len(req.prompt)
            rows.append((j, slot, req, completed))
            # Dispatch-time chunk progress: the scheduled writes WILL
            # execute (queued lanes are never cancelled), so the next
            # dispatch -- possibly chained before this one lands --
            # must schedule from ``pos``. Activation waits for consume.
            req.prefilled = pos
            if completed:
                del self.prefilling[slot]
        klen = self._bucket(max_end)
        # Chunk-shape annotations: mixed decode steps, chunk-only tail
        # steps, chunk size, attention klen bucket for this dispatch.
        sp.annotate(mixed_steps=n, tail_steps=m, chunk=c, klen=klen)
        outs, fin_logits, self.cache_k, self.cache_v, last, lens = (
            self._fused_call(
                n, m, klen, filtered, want_lp, self.cache_k,
                self.cache_v, toks_dev, pos_dev, jnp.asarray(ctoks),
                jnp.asarray(coffs), jnp.asarray(cclens),
                jnp.asarray(cslots), self._decode_rng, temps_dev,
                tks_dev, tps_dev, nonces_dev, mask,
            )
        )
        meta = _FusedMeta(rows, fin_logits, cnonces, cpos, ctemps,
                          ctop_ks, ctop_ps)
        return _Inflight(n, outs, last, lens, temps_dev, tks_dev,
                         tps_dev, nonces_dev, filtered, want_lp, slots,
                         fused=meta)

    def _consume_fused(self, meta: _FusedMeta) -> None:
        """Activate the rows whose prompt completed inside a consumed
        fused block: sample first tokens from the latched prompt-end
        logits with per-(nonce, position) keys -- the same draw the
        batched-prefill path makes for the same request, whatever the
        chunking -- then move them prefilling->active and emit. The
        ``prefill_activations`` bump tells _pipeline_advance to drain:
        queued lanes predate the activation and keep the new row
        parked, so the pipeline collapses one step and the next fresh
        dispatch folds the row into the decode lanes."""
        done = [(j, slot, req)
                for j, slot, req, completed in meta.rows if completed]
        if not done:
            return
        first = None  # sampled lazily: logits stay on device otherwise
        fin_np = None
        for j, slot, req in done:
            if first is None:
                first = np.asarray(self._first_tokens(
                    meta.fin_logits, meta.nonces, meta.positions,
                    meta.temps, meta.top_ks, meta.top_ps,
                ))
            self.lengths[slot] = len(req.prompt)
            if self.hist is not None:
                self.hist[slot, :len(req.prompt)] = req.prompt
            self.active[slot] = req
            self._maybe_capture_prefix(req)
            if req.logprobs or req.constraint is not None:
                if fin_np is None:
                    fin_np = np.asarray(meta.fin_logits, np.float32)
            tok = (self._host_first_token(fin_np[j], req)
                   if req.constraint is not None else int(first[j]))
            if req.logprobs:
                req.logprob_data.append(
                    _host_logprobs(fin_np[j], tok, req.logprobs)
                )
            self._emit(req, tok)
            self.prefill_activations += 1

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(token)
        self.tokens_generated += 1
        if self.hist is not None and self.lengths[req.slot] < self.cfg.max_seq:
            self.hist[req.slot, self.lengths[req.slot]] = token
        now = time.perf_counter()
        if len(req.generated) == 1:
            self._note_ttft(now - req.submit_t)
            if trace.enabled():
                trace.instant("first-token", plane="serving",
                              track=f"req/{req.nonce}", nonce=req.nonce,
                              ttft_ms=round((now - req.submit_t) * 1e3, 3))
        else:
            # Engine-side gap; block decode makes these bursty (the
            # dispatch boundary carries the whole block's latency).
            self.itl_hist.observe(now - req.last_emit_t)
        req.last_emit_t = now
        if req.on_token is not None:
            try:
                req.on_token(token)
            except Exception:  # noqa: BLE001 - a bad stream sink must not
                logger.exception("on_token callback failed")  # kill the slot
        self.lengths[req.slot] += 1
        stopped = False
        constrained_done = False
        if req.constraint is not None:
            # advance() False means the emitted token broke the
            # grammar -- impossible while the mask is applied, but a
            # defensive finish beats emitting unparseable output.
            if not req.constraint.advance(token):
                logger.warning("constraint rejected emitted token %d", token)
                constrained_done = True
            elif req.constraint.complete:
                # Root value closed: finishing here (like a stop match)
                # is what guarantees the result parses as exactly one
                # JSON document.
                constrained_done = True
        if req.stop_fn is not None:
            try:
                stopped = bool(req.stop_fn(req.generated))
            except Exception:  # noqa: BLE001 - a bad predicate must not
                logger.exception("stop_fn failed")  # kill the slot
        done = (
            stopped
            or constrained_done
            or (req.eos_id is not None and token == req.eos_id)
            or len(req.generated) >= req.max_new_tokens
            or self.lengths[req.slot] >= self.cfg.max_seq
        )
        if done:
            self._finish(req)

    def _note_ttft(self, seconds: float, alpha: float = 0.2) -> None:
        self.ttft_hist.observe(seconds)
        ms = seconds * 1e3
        self.ttft_ms_ema = (
            ms if self.ttft_ms_ema is None
            else alpha * ms + (1 - alpha) * self.ttft_ms_ema
        )

    def _finish(self, req: Request) -> None:
        slot = req.slot
        self.active.pop(slot, None)
        self.lengths[slot] = 0
        self.free_slots.append(slot)
        self.requests_finished += 1
        if not req.future.done():
            req.future.set_result(req.generated)

    def stats(self) -> dict:
        """Scheduler-state gauges for /metrics. Called from the scrape
        thread while the engine thread mutates the containers, so
        snapshot them first -- iterating live would intermittently raise
        'changed size during iteration' and blank the scrape."""
        backlog_tokens = sum(
            len(r.prompt) for r in list(self._backlog)
        ) + sum(
            len(r.prompt) - r.prefilled
            for r in list(self.prefilling.values())
        )
        out = {
            "queue_depth": self.pending.qsize() + len(self._backlog),
            "slots_active": len(self.active),
            "slots_prefilling": len(self.prefilling),
            "max_slots": self.max_slots,
            "prefill_backlog_tokens": backlog_tokens,
            "tokens_generated": self.tokens_generated,
            "requests_finished": self.requests_finished,
            # Overlapped-dispatch pipeline gauges (docs/SERVING.md):
            # CONFIGURED depth vs the LIVE queued-lane count, EMA of the
            # host-side bubble between a block's outputs landing and
            # the next dispatch (the gap the pipeline exists to hide),
            # tokens decoded past a request's accepted stream
            # (EOS/budget overshoot + mid-flight-freed lanes --
            # discarded by design), and the worst single-drain
            # queued-lane discard (bounded by drain_overshoot_bound).
            "dispatch_depth": self.pipeline_depth,
            "dispatch_inflight": len(self._inflight),
            "decode_dispatches": self.decode_dispatches,
            "decode_blocks_consumed": self.decode_blocks_consumed,
            "host_gap_ms_ema": (
                round(self.host_gap_ms_ema, 3)
                if self.host_gap_ms_ema is not None else 0.0
            ),
            "overshoot_tokens_discarded": self.overshoot_tokens_discarded,
            "overshoot_max_per_drain": self.overshoot_max_per_drain,
            "ttft_ema_ms": (
                round(self.ttft_ms_ema, 3)
                if self.ttft_ms_ema is not None else 0.0
            ),
            # Continuous chunked-prefill gauges: whether incremental
            # admission is on, the chunk grain, how many prompts have
            # activated out of chunked prefill, and how many MORE
            # chunked prompts this engine could absorb right now (free
            # slots when chunked admission is available, else 0) -- the
            # router's long-prompt steering keys off chunk_headroom.
            "continuous_batching": self.continuous,
            "prefill_chunk": self.prefill_chunk,
            "prefill_activations": self.prefill_activations,
            "chunk_headroom": (
                len(self.free_slots)
                if (self.prefill_chunk and self.continuous) else 0
            ),
        }
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        if self.quantize:
            out["quantize"] = self.quantize
            if self.weights is not None:
                out["weight_bytes"] = int(sum(
                    x.size * x.dtype.itemsize
                    for x in jax.tree.leaves(self.weights)
                ))
        if self.kv_quant:
            out["kv_quant"] = self.kv_quant
            if self.cache_k is not None:
                out["kv_cache_bytes"] = (
                    _kv_nbytes(self.cache_k) + _kv_nbytes(self.cache_v)
                )
        if self.speculative_k:
            out["spec"] = {
                "k": self.speculative_k,
                "steps": self.spec_steps,
                "emitted": self.spec_emitted,
                # Accepted drafts per step / k (1.0 = every draft lands).
                "acceptance": round(
                    (self.spec_emitted - self.spec_steps)
                    / (self.spec_steps * self.speculative_k), 4,
                ) if self.spec_steps else 0.0,
                "drafter": ("model" if self.draft_weights is not None
                            else "ngram"),
            }
        return out

    def step(self) -> bool:
        """Admit pending, then run one mixed dispatch: a fused
        chunk+decode program when any slot is mid-prefill, else a pure
        decode block. With ``pipeline_depth>=1`` at slot saturation up
        to that many NEXT blocks are chained off the current one's
        device-resident carry before its outputs are consumed, so the
        host work (EOS/stop detection, logprobs, stream callbacks)
        overlaps the queued blocks' device time; queued blocks are left
        in flight for later steps. Returns True if work ran."""

        if chaos.enabled():
            # Chaos seam (hot-path free when unarmed: one cached env
            # read). crash SIGKILLs the replica mid-decode; straggler /
            # wedge stall this step exactly where a slow or hung device
            # program would.
            chaos.apply("engine.decode")
        if self._inflight:
            return self._pipeline_step()
        self._admit()
        if self.prefilling:
            self._fused_step()
            return True
        if not self.active:
            return False
        if self.speculative_k and all(
            r.temperature <= 0 and r.top_k == 0 and r.top_p >= 1.0
            and not r.logprobs and r.constraint is None
            for r in self.active.values()
        ):
            # Speculation preserves greedy outputs exactly; sampled /
            # filtered / logprob batches take the normal block path.
            self._spec_step()
            return True
        # Block size: largest power-of-2 <= decode_block within every
        # slot's CACHE headroom (an out-of-range write must not happen).
        # The MIN token budget is deliberately NOT a bound: a single
        # nearly-done slot would otherwise convoy the whole batch down to
        # per-token dispatch; its overshoot is discarded host-side like
        # EOS. The MAX budget IS a bound: when every active slot is nearly
        # done, fused steps past the longest budget are pure waste.
        remaining = min(
            self.cfg.max_seq - int(self.lengths[slot])
            for slot in self.active
        )
        budget = max(
            req.max_new_tokens - len(req.generated)
            for req in self.active.values()
        )
        mask = self._pack_constraint_mask()
        n = 1
        if mask is None:
            while n * 2 <= min(self.decode_block, max(remaining, 1),
                               max(budget, 1)):
                n *= 2
        # else: constrained slots are active -- the legal-token set
        # depends on each sampled token, so dispatches are single-step
        # for the whole batch (jsonmode.py documents the cost).
        tokens, temps, top_ks, top_ps, positions, nonces, filtered = (
            self._pack_decode_lanes()
        )
        want_lp = any(req.logprobs for req in self.active.values())
        jt, jk, jp, jn = (jnp.asarray(temps), jnp.asarray(top_ks),
                          jnp.asarray(top_ps), jnp.asarray(nonces))
        outs, self.cache_k, self.cache_v, last, lens = (
            self._decode_block_call(
                n, filtered, want_lp, self.cache_k, self.cache_v,
                jnp.asarray(tokens), jnp.asarray(positions),
                self._decode_rng, jt, jk, jp, jn, mask,
            )
        )
        fl = _Inflight(n, outs, last, lens, jt, jk, jp, jn, filtered,
                       want_lp, tuple(self.active))
        if mask is not None:
            self._consume_block(fl, behind=False, drain="constraint-mask")
            return True
        self._pipeline_advance(fl)
        return True

    def _pipeline_step(self) -> bool:
        self._pipeline_advance(self._inflight.popleft())
        return True

    def _pipeline_advance(self, fl: _Inflight) -> None:
        """Consume block N with its successors already on device: top
        up the lane deque FIRST (stream callbacks must never sit
        between two dispatches), then materialize and emit N's outputs
        while the queued lanes run. Every step thus emits exactly one
        block -- same cadence as depth-0 -- whether it entered with a
        fresh dispatch or an in-flight one. Any finish discovered
        during the consume drains every queued lane immediately: a
        freed slot must never be re-admitted under a still-in-flight
        stale lane."""
        self._pipeline_fill(fl)
        if not self._inflight:
            self._consume_block(fl, behind=False,
                                drain=self._drain_reason)
            return
        fins = self.requests_finished
        acts = self.prefill_activations
        self._consume_block(fl, behind=True)
        if self.requests_finished != fins:
            # Mid-flight finish (EOS before the predicted budget):
            # drain now; the freed lane's overshoot is discarded whole.
            self._drain_inflight("mid-flight-finish")
        elif self.prefill_activations != acts:
            # A chunked prompt just activated: queued lanes predate it
            # and keep its decode lane parked, so drain -- the next
            # fresh dispatch folds the new row into the batch. Nothing
            # is discarded; the queued lanes' tokens all emit.
            self._drain_inflight("prefill-activation")

    def _pipeline_fill(self, fl: _Inflight) -> None:
        """Chain blocks off the deepest in-flight carry until the lane
        deque holds ``pipeline_depth`` blocks, the drain predicate says
        stop, or the next block would push queued-token exposure past
        ``drain_overshoot_bound``. Near the bound chained blocks SHRINK
        (power-of-2) rather than stop, so a deep pipeline keeps lanes
        queued at reduced block size instead of collapsing to depth 1."""
        while len(self._inflight) < self.pipeline_depth:
            queued = sum(b.n for b in self._inflight)
            tail = self._inflight[-1] if self._inflight else fl
            kind, n = self._pipeline_next(fl.n + queued, tail)
            if n == 0:
                return
            if self.drain_overshoot_bound > 0:
                lim = self.drain_overshoot_bound - queued
                if kind == "spec":
                    # Spec exposure shrinks in whole verify steps of
                    # k+1 tokens each, not single tokens.
                    unit = self.speculative_k + 1
                    m = n // unit
                    while m and m * unit > lim:
                        m //= 2
                    n = m * unit
                else:
                    while n > lim:
                        n //= 2
                if n < 1:
                    self._drain_reason = "overshoot-bound"
                    return
            if kind == "fused":
                nxt = self._dispatch_fused(tail=tail, n_cap=n)
            elif kind == "spec":
                nxt = self._dispatch_spec(
                    tail=tail, m=n // (self.speculative_k + 1))
            else:
                nxt = self._dispatch_chained(tail, n)
            self._copy_async(nxt)
            self._inflight.append(nxt)

    def _drain_inflight(self, reason: str) -> None:
        """Consume every queued lane now, oldest first (emission order
        is dispatch order, so non-finished slots' tokens stay exact).
        A freed slot's tokens in these lanes are discarded whole by
        _emit_decode_outs; the per-drain queued-lane discard delta
        feeds overshoot_max_per_drain, the gauge the perf ratchet
        bounds (an unbounded pipeline shows up there, not in a hang)."""
        before = self.overshoot_tokens_discarded
        while self._inflight:
            blk = self._inflight.popleft()
            if self._inflight:
                self._consume_block(blk, behind=True)
            else:
                self._consume_block(blk, behind=False, drain=reason)
        delta = self.overshoot_tokens_discarded - before
        if delta > self.overshoot_max_per_drain:
            self.overshoot_max_per_drain = delta

    def _pipeline_next(self, n_pending: int, tail: _Inflight):
        """(kind, n) of the next block to chain off ``tail``, or
        (kind, 0) to drain. Mirrors the fresh-dispatch choices under
        the PREDICTED state after every in-flight block lands (host
        lengths/generated trail the device by up to ``n_pending``
        tokens until the consumes); any event a chained dispatch
        couldn't honor -- an admission, a constraint turning on, a
        predicted in-block finish, a lane-kind switch the device carry
        can't express -- forces a drain back to the sequential path.

        Chain-compatibility matrix: fused->fused while prompts remain
        mid-prefill (continuous mode), fused->decode once the chunk
        work is done (identical token/position carry convention),
        decode->decode; spec->spec only (a spec carry is TOTAL lengths
        plus a device hist no other kind maintains); nothing chains
        INTO spec -- the "spec-eligible" drain hands the batch to
        _spec_step instead."""
        if self.pipeline_depth < 1:
            self._drain_reason = "depth-0"
            return "decode", 0
        if not self.active and not self.prefilling:
            self._drain_reason = "idle"
            return "decode", 0
        if self.free_slots:
            # A free slot means an admission could arrive between steps
            # (submit() is async); a block held in flight would delay it
            # a full block. The pipeline only engages at slot
            # saturation, where it pays for itself and no admission can
            # proceed anyway.
            self._drain_reason = "free-slots"
            return "decode", 0
        if any(r.constraint is not None for r in self.active.values()):
            self._drain_reason = "constraint"
            return "decode", 0
        if tail.spec_m:
            return self._pipeline_next_spec(n_pending)
        if self.prefilling and not self.continuous:
            self._drain_reason = "prefilling"
            return "decode", 0
        n_prev = n_pending
        if self.active:
            rem_pred = min(
                self.cfg.max_seq - int(self.lengths[slot]) - n_prev
                for slot in self.active
            )
            if rem_pred < 1:
                self._drain_reason = "cache-headroom"
                return "decode", 0
            if min(
                req.max_new_tokens - len(req.generated) - n_prev
                for req in self.active.values()
            ) <= 0:
                self._drain_reason = "budget-exhausted"
                return "decode", 0  # a budget exhausts in flight: drain
            budget_pred = max(
                req.max_new_tokens - len(req.generated) - n_prev
                for req in self.active.values()
            )
            cap = min(self.decode_block, rem_pred, max(budget_pred, 1))
        else:
            # Pure-prefill pipeline (every slot mid-prompt): decode
            # lanes are all parked, so only the fused caps below bound
            # the block.
            cap = self.decode_block
        if self.prefilling:
            # Chunk work remains: chain another fused block off the
            # decode carry. Rows that completed in flight already left
            # self.prefilling (dispatch-time progress), so this
            # schedules exactly the not-yet-dispatched chunks.
            return "fused", max(min(cap, self.prefill_decode_steps), 1)
        if not self.active:
            self._drain_reason = "idle"
            return "decode", 0
        if self.speculative_k and all(
            r.temperature <= 0 and r.top_k == 0 and r.top_p >= 1.0
            and not r.logprobs and r.constraint is None
            for r in self.active.values()
        ):
            self._drain_reason = "spec-eligible"
            return "decode", 0  # the drained batch takes the spec path
        n = 1
        while n * 2 <= cap:
            n *= 2
        return "decode", n

    def _pipeline_next_spec(self, n_pending: int):
        """Predicted sizing for a spec->spec chain: host lengths and
        budgets trail the device by up to ``n_pending`` tokens (the
        worst case -- every draft of every queued step accepted), so
        bounds mirror _spec_step's under that pessimistic state.
        Eligibility itself can't lapse mid-pipeline: per-request
        sampling params are immutable and set changes drain first."""
        k = self.speculative_k
        rem_pred = min(
            self.cfg.max_seq - int(self.lengths[slot]) - n_pending
            for slot in self.active
        )
        if rem_pred < k + 1:
            self._drain_reason = "cache-headroom"
            return "decode", 0
        if min(
            req.max_new_tokens - len(req.generated) - n_pending
            for req in self.active.values()
        ) <= 0:
            self._drain_reason = "budget-exhausted"
            return "decode", 0
        budget_pred = max(
            req.max_new_tokens - len(req.generated) - n_pending
            for req in self.active.values()
        )
        m = 1
        while m * 2 <= min(self.decode_block,
                           max(rem_pred // (k + 1), 1),
                           max(budget_pred, 1)):
            m *= 2
        return "spec", m * (k + 1)

    def _dispatch_chained(self, fl: _Inflight, n: int) -> _Inflight:
        """Dispatch block N+1 straight off block N's device carry --
        tokens and positions never touch the host."""
        outs, self.cache_k, self.cache_v, last, lens = (
            self._decode_block_call(
                n, fl.filtered, fl.want_lp, self.cache_k, self.cache_v,
                fl.last, fl.lens, self._decode_rng, fl.temps,
                fl.top_ks, fl.top_ps, fl.nonces,
            )
        )
        return _Inflight(n, outs, last, lens, fl.temps, fl.top_ks,
                         fl.top_ps, fl.nonces, fl.filtered, fl.want_lp,
                         fl.slots)

    @staticmethod
    def _copy_async(fl: _Inflight) -> None:
        outs = fl.outs if isinstance(fl.outs, tuple) else (fl.outs,)
        for o in outs:
            o.copy_to_host_async()

    def _consume_block(self, fl: _Inflight, behind: bool,
                       drain: str = "") -> None:
        """Materialize an in-flight block's outputs (the only blocking
        host sync of a steady-state pipelined step) and emit them. With
        ``behind`` a newer block is already queued on device, so this
        consume opens NO host gap -- record 0 directly; otherwise start
        the gap clock that the next dispatch closes.

        ``drain``: why the pipeline drained instead of chaining (empty
        when ``behind`` -- a chained block IS in flight). The span is
        consumption-side instrumentation only: it brackets the one
        np.asarray sync this method already performs and adds none."""
        with trace.span("decode-block.consume", plane="serving",
                        track="engine", n=fl.n,
                        depth=len(self._inflight), drain=drain):
            if fl.fused is None and not fl.spec_m:
                # PURE decode blocks only: this is the denominator of
                # the host-syncs-per-block audit (jaxpr_audit), whose
                # steady state is decode-only traffic.
                self.decode_blocks_consumed += 1
            if fl.spec_m or fl.want_lp:
                outs = tuple(np.asarray(o) for o in fl.outs)
            else:
                outs = np.asarray(fl.outs)
            if behind:
                self._ema_gap(0.0)
            else:
                self._gap_t = time.perf_counter()
            if fl.spec_m:
                self._emit_spec_outs(fl, *outs)
            else:
                self._emit_decode_outs(outs, fl.want_lp,
                                       dispatch_slots=fl.slots)
                if fl.fused is not None:
                    self._consume_fused(fl.fused)
            if not self.active:
                # Going idle: time to the next dispatch is queue wait, not
                # pipeline bubble -- don't count it.
                self._gap_t = None

    def _note_dispatch(self, decode: bool) -> None:
        """Called at every device dispatch: closes any open host-gap
        window (the gauge is 'outputs materialized -> next device
        work') and counts pure decode blocks for the host-sync audit."""
        if decode:
            self.decode_dispatches += 1
        if self._gap_t is not None:
            self._ema_gap((time.perf_counter() - self._gap_t) * 1000.0)
            # Single-stepper invariant: step() is driven EITHER by the
            # start() loop thread OR inline by generate() (which only
            # waits on the future once _thread is set) -- never both,
            # so the gap clock has one writer at a time.
            self._gap_t = None  # kt-lint: disable=KT-GUARD01 -- single-stepper: loop thread XOR inline generate() drives step()

    def _ema_gap(self, ms: float) -> None:
        if self.host_gap_ms_ema is None:
            self.host_gap_ms_ema = ms
        else:
            self.host_gap_ms_ema = (
                0.9 * self.host_gap_ms_ema + 0.1 * ms
            )

    # -- convenience / threaded driver ------------------------------------

    def _spec_step(self) -> None:
        """One speculative dispatch: m verify steps of k drafts each
        (_spec_block), entering the lane deque like a decode block so
        chained spec blocks draft+verify on device while this one's
        outputs stream home."""
        fl = self._dispatch_spec()
        self._pipeline_advance(fl)

    def _dispatch_spec(self, tail: Optional[_Inflight] = None,
                       m: Optional[int] = None) -> _Inflight:
        """Dispatch one speculative verify block. Fresh (``tail`` is
        None): token/length/hist state uploads from host bookkeeping.
        Chained: spec lanes carry TOTAL lengths (pending tokens
        included) plus the device-resident hist the drafter reads, so
        the next block drafts straight off the previous one's carry
        without materializing its outputs."""
        k = self.speculative_k
        if m is None:
            remaining = min(
                self.cfg.max_seq - int(self.lengths[slot])
                for slot in self.active
            )
            budget = max(
                req.max_new_tokens - len(req.generated)
                for req in self.active.values()
            )
            # Steps are pow2-bounded like decode blocks; each step emits
            # 1..k+1 tokens, so headroom divides by the worst-case
            # growth and the budget bound uses the guaranteed-min 1.
            m = 1
            while m * 2 <= min(self.decode_block,
                               max(remaining // (k + 1), 1),
                               max(budget, 1)):
                m *= 2
        if tail is None:
            tokens = np.zeros(self.max_slots, np.int32)
            lens = np.full(self.max_slots, self.cfg.max_seq, np.int32)
            for slot, req in self.active.items():
                tokens[slot] = req.generated[-1]
                lens[slot] = max(int(self.lengths[slot]), 1)
            toks_dev = jnp.asarray(tokens)
            lens_dev = jnp.asarray(lens)
            hist_dev = jnp.asarray(self.hist)
            slots = tuple(self.active)
        else:
            toks_dev, lens_dev = tail.last, tail.lens
            hist_dev = tail.hist_dev
            slots = tail.slots
        outs, counts, self.cache_k, self.cache_v, last, lens_out, hist = (
            self._spec_call(m, self.cache_k, self.cache_v, toks_dev,
                            lens_dev, hist_dev)
        )
        return _Inflight(m * (k + 1), (outs, counts), last, lens_out,
                         None, None, None, None, False, False, slots,
                         spec_m=m, hist_dev=hist)

    def _emit_spec_outs(self, fl: _Inflight, outs: np.ndarray,
                        counts: np.ndarray) -> None:
        """Emit a consumed spec block: per slot, the accepted drafts of
        each step flattened row-major -- exactly the per-(step, draft)
        order sequential verification would emit in. A slot freed while
        the block was in flight discards its lane whole (parked-row
        invariant, same as decode)."""
        width = outs.shape[2]  # k+1
        for slot in fl.slots:
            req = self.active.get(slot)
            if req is None:  # freed mid-flight
                self.overshoot_tokens_discarded += int(
                    counts[:, slot].sum())
                continue
            self.spec_steps += fl.spec_m
            self.spec_emitted += int(counts[:, slot].sum())
            keep = np.arange(width)[None, :] < counts[:, slot][:, None]
            run = outs[:, slot, :][keep]
            acc = self._emit_run(req, run)
            self.overshoot_tokens_discarded += run.size - acc

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 top_k: int = 0, top_p: float = 1.0,
                 constraint=None) -> List[int]:
        """Synchronous single-request generation (drives step() inline)."""

        req = Request(list(prompt), max_new_tokens, temperature,
                      top_k, top_p, eos_id, constraint=constraint)
        fut = self.submit(req)
        if self._thread is not None:
            return fut.result(timeout=600)
        while not fut.done():
            if not self.step():
                break
        return fut.result()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if not self.step():
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kftpu-engine")
        self._thread.start()

    def stop(self) -> None:
        if trace.enabled():
            # Final load snapshot into the process trace: `kftpu trace
            # dump` aggregates these per plane (queue depth / TTFT EMA
            # per replica) without scraping a live /metrics.
            trace.instant(
                "engine-stats", plane="serving", track="engine",
                queue_depth=self.pending.qsize() + len(self._backlog),
                slots_active=len(self.active),
                ttft_ema_ms=round(self.ttft_ms_ema or 0.0, 3),
                tokens_generated=self.tokens_generated,
                requests_finished=self.requests_finished,
            )
        if self._thread is not None:
            self._stop.set()
            self._wake.set()
            self._thread.join(timeout=5)
            self._thread = None

    def quiesce(self, reason: str = "kv-reshard") -> bool:
        """Halt dispatch at a block boundary: stop the scheduler thread
        (if one is running) and drain every in-flight pipeline lane so
        the host bookkeeping (lengths, generated tokens) and the device
        cache agree exactly. Active requests KEEP their slots and their
        KV rows -- quiesce is a pause, not an abort. Returns whether the
        engine thread was running (pass it back to ``resume``)."""
        was_running = self._thread is not None
        if was_running:
            self.stop()
        self._drain_inflight(reason)
        for c in (self.cache_k, self.cache_v):
            for leaf in jax.tree_util.tree_leaves(c):
                if hasattr(leaf, "block_until_ready"):
                    leaf.block_until_ready()
        return was_running

    def resume(self, was_running: bool) -> None:
        """Undo ``quiesce``: restart the scheduler thread when one was
        running before. The decode loop picks up exactly where it
        drained -- same slots, same lengths, same RNG chains."""
        if was_running:
            self.start()
            self._wake.set()

    def prefix_inventory(self, top_k: int = 0) -> List[dict]:
        """Hottest-first metadata for this engine's prefix-cache
        entries (see PrefixCache.hot_entries); [] with no cache."""
        pc = self.prefix_cache
        return pc.hot_entries(top_k) if pc is not None else []

    def resplit_tp(self, tensor_parallel: int, *, devices=None,
                   hbm_bytes: Optional[int] = None) -> dict:
        """Live-resplit this engine onto a ``tensor_parallel``-way mesh:
        quiesce at a block boundary, move weights + in-place KV cache +
        prefix-cache entries through parallel/reshard.py's plan/execute
        machinery, rebuild the jit dispatch closures, resume. Returns
        the plan summary (serving/kv_reshard.py owns the mechanics)."""
        from kubeflow_tpu.serving import kv_reshard

        return kv_reshard.resplit_engine_tp(
            self, tensor_parallel, devices=devices, hbm_bytes=hbm_bytes)

    def close(self) -> None:
        """Release device memory (weights + KV cache) and the compiled
        calls that close over them. The jit closures reference the engine
        through ``self``, a reference CYCLE -- without an explicit break,
        a dropped engine waits for the cyclic GC while its multi-GB HBM
        buffers stay live, and the next engine OOMs. Unusable after."""
        self.stop()
        self._inflight.clear()  # lanes hold device outs + chain carries
        self.weights = None
        self.cache_k = None
        self.cache_v = None
        self.prefix_cache = None  # stored prefix buffers are HBM too
        self._decode_block_call = None
        self._fused_call = None
        self._prefill = None
        self._insert = None
        self._sample = None
        self._extract_call = None
        self._restore_call = None
        self._spec_call = None
        self._first_tokens = None
        self.draft_weights = None  # distilled drafts are HBM buffers too
        self.hist = None
