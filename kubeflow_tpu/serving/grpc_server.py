"""gRPC transport for the Open Inference Protocol (V2).

The reference's model server speaks the V2 protocol over REST *and* gRPC
(SURVEY.md 3.3 S4); this is the gRPC side, backed by the SAME
ModelRepository and ModelServer.v2_infer core as the aiohttp routes --
the transports are thin codecs over one inference path.

Service wiring uses grpc.method_handlers_generic_handler over the
protoc-generated messages (kubeflow_tpu/serving/oip.proto ->
oip_pb2.py), so no grpcio-tools plugin is needed at build time.

Edge note: the activator/ingress is an L7 HTTP proxy; gRPC is served
per-replica (the controller allocates and reports a grpc_port per
replica) rather than through the activator. This mirrors the reference's
split, where gRPC rides the mesh gateway, not the Knative activator's
HTTP buffer path.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import grpc
import numpy as np

from kubeflow_tpu.serving import oip_pb2 as pb
from kubeflow_tpu.serving.model import InferenceError

logger = logging.getLogger(__name__)

SERVICE = "inference.GRPCInferenceService"

# OIP datatype -> (InferTensorContents field, numpy dtype for flattening)
_DTYPE_FIELDS = {
    "BOOL": ("bool_contents", np.bool_),
    "INT8": ("int_contents", np.int32),
    "INT16": ("int_contents", np.int32),
    "INT32": ("int_contents", np.int32),
    "INT64": ("int64_contents", np.int64),
    "UINT8": ("uint_contents", np.uint32),
    "UINT16": ("uint_contents", np.uint32),
    "UINT32": ("uint_contents", np.uint32),
    "UINT64": ("uint64_contents", np.uint64),
    "FP16": ("fp32_contents", np.float32),
    "FP32": ("fp32_contents", np.float32),
    "FP64": ("fp64_contents", np.float64),
    "BYTES": ("bytes_contents", None),
}


_RAW_NP_DTYPES = {
    "BOOL": np.bool_, "INT8": np.int8, "INT16": np.int16,
    "INT32": np.int32, "INT64": np.int64, "UINT8": np.uint8,
    "UINT16": np.uint16, "UINT32": np.uint32, "UINT64": np.uint64,
    "FP16": np.float16, "FP32": np.float32, "FP64": np.float64,
}


def _zip_raw(inputs, raw_list):
    """Pair each input tensor with its raw_input_contents entry, if the
    client used the raw representation (positional, one per tensor)."""
    raw_list = list(raw_list)
    for i, t in enumerate(inputs):
        yield t, (raw_list[i] if i < len(raw_list) else None)


def _decode_raw(datatype: str, raw: bytes) -> list:
    """OIP raw tensor representation -> flat python list. BYTES elements
    are 4-byte little-endian length-prefixed; numeric types are packed
    little-endian arrays."""
    if datatype == "BYTES":
        out, off = [], 0
        while off + 4 <= len(raw):
            n = int.from_bytes(raw[off:off + 4], "little")
            off += 4
            out.append(raw[off:off + n].decode("utf-8", errors="replace"))
            off += n
        return out
    dt = _RAW_NP_DTYPES.get(datatype, np.float32)
    return np.frombuffer(raw, dtype=dt).tolist()


def tensor_to_dict(t: "pb.ModelInferRequest.InferInputTensor",
                   raw: Optional[bytes] = None) -> dict:
    """Proto input tensor -> the V2 JSON-shaped dict the model sees.

    Standard OIP clients (Triton/KServe defaults) ship tensor data in
    ModelInferRequest.raw_input_contents rather than the typed contents
    fields -- both representations are accepted."""
    if raw:
        data = _decode_raw(t.datatype, raw)
    else:
        field, _ = _DTYPE_FIELDS.get(t.datatype,
                                     ("fp32_contents", np.float32))
        data = list(getattr(t.contents, field))
        if t.datatype == "BYTES":
            data = [b.decode("utf-8", errors="replace") for b in data]
    return {
        "name": t.name, "datatype": t.datatype,
        "shape": list(t.shape), "data": data,
    }


def dict_to_tensor(d: dict) -> "pb.ModelInferResponse.InferOutputTensor":
    """V2 JSON-shaped output dict -> proto output tensor."""
    out = pb.ModelInferResponse.InferOutputTensor(
        name=str(d.get("name", "output_0")),
        datatype=str(d.get("datatype", "FP32")),
    )
    shape = d.get("shape")
    data = d.get("data", [])
    if d.get("datatype") == "BYTES":
        flat = [
            x if isinstance(x, bytes) else str(x).encode()
            for x in np.asarray(data, dtype=object).reshape(-1)
        ]
        out.shape.extend(shape if shape is not None else [len(flat)])
        out.contents.bytes_contents.extend(flat)
        return out
    field, np_dtype = _DTYPE_FIELDS.get(
        out.datatype, ("fp32_contents", np.float32)
    )
    try:
        arr = np.asarray(data, dtype=np_dtype)
    except (TypeError, ValueError):
        # Arbitrary JSON outputs (echo/custom models whose postprocess
        # returns dicts): a typed tensor can't hold them -- ship each
        # element as JSON in a BYTES tensor, mirroring what the REST
        # transport serializes.
        import json

        flat = [
            json.dumps(x).encode()
            for x in np.asarray(data, dtype=object).reshape(-1)
        ]
        out.datatype = "BYTES"
        out.shape.extend(shape if shape is not None else [len(flat)])
        out.contents.bytes_contents.extend(flat)
        return out
    out.shape.extend(shape if shape is not None else list(arr.shape))
    getattr(out.contents, field).extend(arr.reshape(-1).tolist())
    return out


def _grpc_status(e: Exception) -> grpc.StatusCode:
    status = e.status if isinstance(e, InferenceError) else 500
    return {
        400: grpc.StatusCode.INVALID_ARGUMENT,
        404: grpc.StatusCode.NOT_FOUND,
        409: grpc.StatusCode.FAILED_PRECONDITION,
        501: grpc.StatusCode.UNIMPLEMENTED,
        503: grpc.StatusCode.UNAVAILABLE,
    }.get(status, grpc.StatusCode.INTERNAL)


class OIPServicer:
    """GRPCInferenceService over a ModelServer (shared repository/core)."""

    def __init__(self, server) -> None:
        self.server = server  # ModelServer
        self.repo = server.repository

    async def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=True)

    async def ServerReady(self, request, context):
        return pb.ServerReadyResponse(ready=self.server._ready())

    async def ModelReady(self, request, context):
        try:
            model = self.repo.get(request.name)
        except InferenceError:
            return pb.ModelReadyResponse(ready=False)
        return pb.ModelReadyResponse(ready=model.ready)

    async def ServerMetadata(self, request, context):
        return pb.ServerMetadataResponse(
            name=self.server.name, version="2",
            extensions=["model_repository"],
        )

    async def ModelMetadata(self, request, context):
        try:
            meta = self.repo.get(request.name).metadata()
        except Exception as e:  # noqa: BLE001
            await context.abort(_grpc_status(e), str(e))
        resp = pb.ModelMetadataResponse(
            name=meta.get("name", request.name),
            platform=meta.get("platform", "kftpu"),
        )
        for key, dest in (("inputs", resp.inputs), ("outputs", resp.outputs)):
            for t in meta.get(key) or []:
                dest.add(name=t.get("name", ""),
                         datatype=t.get("datatype", ""),
                         shape=t.get("shape") or [])
        return resp

    async def ModelInfer(self, request, context):
        import time

        self.server.request_count += 1
        t0 = time.monotonic()
        try:
            inputs = [
                tensor_to_dict(t, raw)
                for t, raw in _zip_raw(request.inputs,
                                       request.raw_input_contents)
            ]
            # S6 payload logging: same audit trail as the REST route.
            rid = ""
            if self.server.payload_logger is not None:
                rid = request.id or self.server.payload_logger.new_id()
                await self.server.payload_logger.log_request(
                    request.model_name, {"inputs": inputs}, rid
                )
            outputs = await self.server.v2_infer(request.model_name, inputs)
        except Exception as e:  # noqa: BLE001
            self.server.error_count += 1
            await context.abort(_grpc_status(e), str(e))
        finally:
            self.server.predict_seconds += time.monotonic() - t0
        resp = pb.ModelInferResponse(
            model_name=request.model_name, id=request.id,
        )
        resp.outputs.extend(dict_to_tensor(d) for d in outputs)
        # Mirror the REST route: engine-backed models annotate the
        # response with their dispatch-pipeline gauges through the
        # existing OIP `parameters` map (no proto change needed).
        try:
            model = self.repo.get(request.model_name)
        except InferenceError:
            model = None  # raced an unload; gauges are best-effort
        gauges = getattr(model, "engine_gauges", None)
        if gauges is not None:
            for key, val in gauges().items():
                if isinstance(val, float):
                    resp.parameters[key].double_param = val
                else:
                    resp.parameters[key].int64_param = int(val)
        if self.server.payload_logger is not None:
            await self.server._log_response(
                request.model_name,
                {"model_name": request.model_name, "outputs": outputs},
                rid,
            )
        return resp

    async def ModelStreamGenerate(self, request, context):
        """Server-streaming generation: one frame per token delta, then
        a finished frame -- the gRPC analog of the SSE
        /v2/models/{m}/generate_stream route, riding the SAME
        ModelServer._stream_deltas core (split-codepoint withholding
        included; stop= stops the engine without transport trimming,
        matching the REST v2 generate semantics)."""
        import time

        self.server.request_count += 1
        t0 = time.monotonic()
        try:
            model = self.repo.get(request.model_name)
            if not model.ready:
                raise InferenceError(
                    f"model {request.model_name} is not ready", 503
                )
            self.repo.touch(request.model_name)
            inst: dict = {}
            if request.token_ids:
                inst["token_ids"] = list(request.token_ids)
            else:
                inst["prompt"] = request.text_input
            if request.max_new_tokens:
                inst["max_new_tokens"] = request.max_new_tokens
            if request.temperature:
                inst["temperature"] = request.temperature
            if request.top_k:
                inst["top_k"] = request.top_k
            if request.top_p:
                inst["top_p"] = request.top_p
            stops = [s for s in request.stop if s]
            if stops:
                # Engine-side stop only (slot frees at the match), the
                # same semantics as the REST v2 generate routes -- no
                # transport-level trim, so both transports stay
                # token-exact (OpenAI routes own the trimming contract).
                inst["stop"] = stops
            stream = self.server._stream_deltas(model, inst)
            # Prime before the first yield: submit-time errors (bad
            # instance, dead engine) become clean gRPC statuses, not
            # mid-stream aborts.
            first = await anext(stream, None)
        except ValueError as e:
            # Engine-side request validation (empty/too-long prompt):
            # the client's fault, same mapping as the SSE route's 400.
            self.server.error_count += 1
            self.server.predict_seconds += time.monotonic() - t0
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            return
        except Exception as e:  # noqa: BLE001
            self.server.error_count += 1
            self.server.predict_seconds += time.monotonic() - t0
            await context.abort(_grpc_status(e), str(e))
            return
        def frame(delta, tok):
            return pb.ModelGenerateResponse(
                text_output=delta,
                token_id=tok if tok is not None else 0,
                has_token=tok is not None,
            )

        try:
            if first is not None:
                yield frame(first[0], first[1])
                async for delta, tok, _ids in stream:
                    yield frame(delta, tok)
            yield pb.ModelGenerateResponse(finished=True)
        except Exception as e:  # noqa: BLE001 - mid-stream engine error:
            self.server.error_count += 1  # count it and end with a
            await context.abort(_grpc_status(e), str(e))  # mapped status
        finally:
            self.server.predict_seconds += time.monotonic() - t0

    async def RepositoryModelLoad(self, request, context):
        try:
            params = request.parameters
            uri = (params["storage_uri"].string_param
                   if "storage_uri" in params else None)
            opts_raw = (params["options"].string_param
                        if "options" in params else "")
            if uri is not None or opts_raw:
                import json

                await self.repo.load_dynamic_async(
                    request.model_name, uri,
                    json.loads(opts_raw) if opts_raw else {},
                )
            else:
                self.repo.load(request.model_name)
        except Exception as e:  # noqa: BLE001
            await context.abort(_grpc_status(e), str(e))
        return pb.RepositoryModelLoadResponse()

    async def RepositoryModelUnload(self, request, context):
        try:
            if self.repo.multi_model:
                self.repo.evict(request.model_name)
            else:
                self.repo.unload(request.model_name)
        except Exception as e:  # noqa: BLE001
            await context.abort(_grpc_status(e), str(e))
        return pb.RepositoryModelUnloadResponse()


def _handlers(servicer: OIPServicer) -> grpc.GenericRpcHandler:
    def unary(method, req_cls, resp_cls):
        return grpc.unary_unary_rpc_method_handler(
            method,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )

    return grpc.method_handlers_generic_handler(SERVICE, {
        "ServerLive": unary(servicer.ServerLive, pb.ServerLiveRequest,
                            pb.ServerLiveResponse),
        "ServerReady": unary(servicer.ServerReady, pb.ServerReadyRequest,
                             pb.ServerReadyResponse),
        "ModelReady": unary(servicer.ModelReady, pb.ModelReadyRequest,
                            pb.ModelReadyResponse),
        "ServerMetadata": unary(servicer.ServerMetadata,
                                pb.ServerMetadataRequest,
                                pb.ServerMetadataResponse),
        "ModelMetadata": unary(servicer.ModelMetadata,
                               pb.ModelMetadataRequest,
                               pb.ModelMetadataResponse),
        "ModelInfer": unary(servicer.ModelInfer, pb.ModelInferRequest,
                            pb.ModelInferResponse),
        "RepositoryModelLoad": unary(servicer.RepositoryModelLoad,
                                     pb.RepositoryModelLoadRequest,
                                     pb.RepositoryModelLoadResponse),
        "RepositoryModelUnload": unary(servicer.RepositoryModelUnload,
                                       pb.RepositoryModelUnloadRequest,
                                       pb.RepositoryModelUnloadResponse),
        "ModelStreamGenerate": grpc.unary_stream_rpc_method_handler(
            servicer.ModelStreamGenerate,
            request_deserializer=pb.ModelGenerateRequest.FromString,
            response_serializer=(
                pb.ModelGenerateResponse.SerializeToString
            ),
        ),
    })


async def start_grpc(model_server, host: str, port: int) -> grpc.aio.Server:
    """Start the asyncio gRPC server on the running event loop (same loop
    as the aiohttp app: the repository's batchers live there)."""
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((_handlers(OIPServicer(model_server)),))
    server.add_insecure_port(f"{host}:{port}")
    await server.start()
    logger.info("OIP gRPC listening on %s:%d", host, port)
    return server


# -- client helpers (tests / SDK) -------------------------------------------


def client_stubs(channel: grpc.Channel) -> dict:
    """Method-name -> callable stubs for a (sync or aio) channel, built
    without generated *_pb2_grpc code."""
    def u(name, req_cls, resp_cls):
        return channel.unary_unary(
            f"/{SERVICE}/{name}",
            request_serializer=req_cls.SerializeToString,
            response_deserializer=resp_cls.FromString,
        )

    return {
        "ServerLive": u("ServerLive", pb.ServerLiveRequest,
                        pb.ServerLiveResponse),
        "ServerReady": u("ServerReady", pb.ServerReadyRequest,
                         pb.ServerReadyResponse),
        "ModelReady": u("ModelReady", pb.ModelReadyRequest,
                        pb.ModelReadyResponse),
        "ServerMetadata": u("ServerMetadata", pb.ServerMetadataRequest,
                            pb.ServerMetadataResponse),
        "ModelMetadata": u("ModelMetadata", pb.ModelMetadataRequest,
                           pb.ModelMetadataResponse),
        "ModelInfer": u("ModelInfer", pb.ModelInferRequest,
                        pb.ModelInferResponse),
        "RepositoryModelLoad": u("RepositoryModelLoad",
                                 pb.RepositoryModelLoadRequest,
                                 pb.RepositoryModelLoadResponse),
        "RepositoryModelUnload": u("RepositoryModelUnload",
                                   pb.RepositoryModelUnloadRequest,
                                   pb.RepositoryModelUnloadResponse),
        "ModelStreamGenerate": channel.unary_stream(
            f"/{SERVICE}/ModelStreamGenerate",
            request_serializer=(
                pb.ModelGenerateRequest.SerializeToString
            ),
            response_deserializer=pb.ModelGenerateResponse.FromString,
        ),
    }


def infer_request(model: str, inputs: list,
                  request_id: str = "") -> "pb.ModelInferRequest":
    """Build a ModelInferRequest from V2 JSON-shaped input dicts."""
    req = pb.ModelInferRequest(model_name=model, id=request_id)
    for d in inputs:
        t = req.inputs.add(
            name=str(d.get("name", "input_0")),
            datatype=str(d.get("datatype", "FP32")),
        )
        data = d.get("data", [])
        arr = np.asarray(data, dtype=object if d.get("datatype") == "BYTES"
                         else None)
        t.shape.extend(d.get("shape") or list(np.shape(data)))
        if d.get("datatype") == "BYTES":
            t.contents.bytes_contents.extend(
                x if isinstance(x, bytes) else str(x).encode()
                for x in arr.reshape(-1)
            )
        else:
            field, np_dtype = _DTYPE_FIELDS.get(
                t.datatype, ("fp32_contents", np.float32)
            )
            flat = np.asarray(data, dtype=np_dtype).reshape(-1)
            getattr(t.contents, field).extend(flat.tolist())
    return req
