"""Constrained decoding: OpenAI ``response_format: json_object``.

vLLM-class structured output for the TPU engine (SURVEY.md 3.3 S5
delta), sized to what the contract needs: a character-level JSON
valid-prefix automaton, lifted to token level by simulating each vocab
token's string, produces a boolean vocab mask per decode step. The
engine applies the mask INSIDE the device sample (engine._sample:
disallowed logits -> -inf before greedy/temperature/top-k/top-p), so
the constraint composes with every sampling mode; the automaton itself
advances on the host with each emitted token.

Design notes (TPU-first reasoning):
- Per-step masks are inherently sequential (the allowed set depends on
  the token just sampled), so constrained requests run at
  decode-block=1 -- one dispatch per token, mask uploaded as a [B, V]
  bool (V bytes/slot). That is the honest cost of JSON mode on a
  remote-dispatch chip; unconstrained requests are untouched (the
  masked program is a separate jit variant, so the common path compiles
  byte-identical code to before).
- Masks are cached by automaton state (state, literal-tail, stack):
  steady-state decoding revisits a handful of states, so the
  32k-token simulation sweep runs once per distinct state, not per
  step. A first-character pre-filter prunes most of the vocab before
  simulation.
- Root is an OBJECT, opened immediately (no leading whitespace --
  see _MAX_WS_RUN): that is what "json_object" promises, and it
  sidesteps the bare-number ambiguity
  (a top-level ``12`` is a valid prefix of ``123`` forever, so
  completion would be undecidable).
- When the automaton reaches the complete state the engine finishes
  the request (like a stop match): the result text parses as exactly
  one JSON object, with no trailing garbage to trim.

Known limitation: token-string simulation decodes each id standalone,
so byte-level BPE tokens carrying a fragment of a multi-byte UTF-8
codepoint surface as U+FFFD and are masked out inside strings -- JSON
mode effectively constrains string content to whole-codepoint tokens
(ASCII is always safe; use ``\\uXXXX`` escapes for the rest). See
tokenizer_vocab_strings for details.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_WS = " \t\n\r"
_HEX = "0123456789abcdefABCDEF"
_MAX_DEPTH = 64


class JsonFsm:
    """Valid-prefix automaton for one object-rooted JSON document.

    advance_char(c) -> bool consumes one character (False = the char
    cannot extend any valid JSON document). ``complete`` is True once
    the root object has closed (only whitespace may follow; the engine
    finishes the request instead).
    """

    __slots__ = ("stack", "state", "lit", "key_str", "ws_run")

    # Consecutive structural whitespace allowed. Whitespace never
    # changes JSON semantics, but an unbounded allowance lets a
    # weak/greedy model emit it forever and run out the token budget
    # with the root object never opened (observed on random weights) --
    # so the automaton treats it as a decoding POLICY: at most
    # _MAX_WS_RUN in a row, none before the root '{'.
    _MAX_WS_RUN = 2

    def __init__(self) -> None:
        self.stack: List[str] = []   # 'o' | 'a'
        self.state = "start"
        self.lit = ""                # remaining literal chars / hex count
        self.key_str = False
        self.ws_run = 0

    # -- bookkeeping -----------------------------------------------------

    def clone(self) -> "JsonFsm":
        f = JsonFsm.__new__(JsonFsm)
        f.stack = list(self.stack)
        f.state = self.state
        f.lit = self.lit
        f.key_str = self.key_str
        f.ws_run = self.ws_run
        return f

    def mask_key(self) -> Tuple:
        return (self.state, self.lit, self.key_str, tuple(self.stack),
                self.ws_run)

    @property
    def complete(self) -> bool:
        return self.state == "after_value" and not self.stack

    def min_close_chars(self) -> int:
        """Fewest characters that complete the document from here (the
        budget-forcing bound: every char is at least one token, and
        byte-level BPE vocabularies contain every single byte, so a
        char count lower-bounds the token count)."""
        s = self.state
        key_extra = 2 if self.key_str else 0  # ':' + shortest value '0'
        if s == "start":
            return 2  # '{' '}'
        if s == "in_str":
            cost = 1 + key_extra
        elif s == "str_esc":
            cost = 2 + key_extra
        elif s == "str_u":
            cost = int(self.lit) + 1 + key_extra
        elif s == "lit":
            cost = len(self.lit)
        elif s in ("num_minus", "num_dot", "num_e", "num_esign"):
            cost = 1  # one digit, then the number may end at a closer
        elif s == "value":
            cost = 1  # shortest value: a single digit
        elif s == "expect_colon":
            cost = 2  # ':' + digit
        elif s == "expect_key_more":
            cost = 4  # shortest member: '"' '"' ':' '0'
        else:
            # after_value / num_* that may end / expect_key (closes via
            # '}') / arr_first (closes via ']') -- the closer is already
            # counted in the stack term.
            cost = 0
        return cost + len(self.stack)

    # -- transitions -----------------------------------------------------

    def _end_value(self) -> None:
        self.state = "after_value"

    def _push(self, kind: str) -> bool:
        if len(self.stack) >= _MAX_DEPTH:
            return False
        self.stack.append(kind)
        return True

    def _start_value(self, c: str) -> bool:
        """Value-start dispatch shared by 'value' and 'arr_first'."""
        if c == "{":
            self.state = "expect_key"
            return self._push("o")
        if c == "[":
            self.state = "arr_first"
            return self._push("a")
        if c == '"':
            self.state = "in_str"
            self.key_str = False
            return True
        if c == "-":
            self.state = "num_minus"
            return True
        if c == "0":
            self.state = "num_zero"
            return True
        if c in "123456789":
            self.state = "num_int"
            return True
        if c == "t":
            self.state, self.lit = "lit", "rue"
            return True
        if c == "f":
            self.state, self.lit = "lit", "alse"
            return True
        if c == "n":
            self.state, self.lit = "lit", "ull"
            return True
        return False

    def _ws_ok(self) -> bool:
        if self.ws_run >= self._MAX_WS_RUN:
            return False
        self.ws_run += 1
        return True

    def advance_char(self, c: str) -> bool:  # noqa: C901 - one automaton
        ok = self._advance_char(c)
        if ok and c not in _WS:
            self.ws_run = 0
        return ok

    def _advance_char(self, c: str) -> bool:  # noqa: C901
        s = self.state
        if s == "start":
            if c in _WS:
                return False  # root opens immediately (see _MAX_WS_RUN)
            if c == "{":
                self.state = "expect_key"
                return self._push("o")
            return False
        if s == "in_str":
            if c == '"':
                if self.key_str:
                    self.state = "expect_colon"
                else:
                    self._end_value()
                return True
            if c == "\\":
                self.state = "str_esc"
                return True
            return ord(c) >= 0x20
        if s == "str_esc":
            if c == "u":
                self.state, self.lit = "str_u", "4"
                return True
            if c in '"\\/bfnrt':
                self.state = "in_str"
                return True
            return False
        if s == "str_u":
            if c not in _HEX:
                return False
            left = int(self.lit) - 1
            if left == 0:
                self.state = "in_str"
            else:
                self.lit = str(left)
            return True
        if s == "lit":
            if not self.lit or c != self.lit[0]:
                return False
            self.lit = self.lit[1:]
            if not self.lit:
                self._end_value()
            return True
        if s in ("num_minus", "num_zero", "num_int", "num_dot",
                 "num_frac", "num_e", "num_esign", "num_exp"):
            return self._advance_number(s, c)
        if s == "value":
            if c in _WS:
                return self._ws_ok()
            return self._start_value(c)
        if s == "arr_first":
            if c in _WS:
                return self._ws_ok()
            if c == "]":
                self.stack.pop()
                self._end_value()
                return True
            return self._start_value(c)
        if s in ("expect_key", "expect_key_more"):
            if c in _WS:
                return self._ws_ok()
            if c == '"':
                self.state = "in_str"
                self.key_str = True
                return True
            if s == "expect_key_more":
                return False  # after a comma only a key may follow
            if c == "}":
                self.stack.pop()
                self._end_value()
                return True
            return False
        if s == "expect_colon":
            if c in _WS:
                return self._ws_ok()
            if c == ":":
                self.state = "value"
                return True
            return False
        if s == "after_value":
            if c in _WS:
                return self._ws_ok()
            if not self.stack:
                return False  # root closed: nothing but whitespace
            top = self.stack[-1]
            if c == ",":
                # "expect_key_more", not "expect_key": a comma promises
                # another member, so '}' (trailing comma) is invalid.
                self.state = "expect_key_more" if top == "o" else "value"
                return True
            if c == "}" and top == "o":
                self.stack.pop()
                self._end_value()
                return True
            if c == "]" and top == "a":
                self.stack.pop()
                self._end_value()
                return True
            return False
        raise AssertionError(f"unknown state {s!r}")

    def _advance_number(self, s: str, c: str) -> bool:
        if s == "num_minus":
            if c == "0":
                self.state = "num_zero"
                return True
            if c in "123456789":
                self.state = "num_int"
                return True
            return False
        if s == "num_e":
            if c in "+-":
                self.state = "num_esign"
                return True
            if c.isdigit():
                self.state = "num_exp"
                return True
            return False
        if s == "num_esign":
            if c.isdigit():
                self.state = "num_exp"
                return True
            return False
        if s == "num_dot":
            if c.isdigit():
                self.state = "num_frac"
                return True
            return False
        # num_zero / num_int / num_frac / num_exp: may continue or end.
        if s in ("num_zero",):
            if c == ".":
                self.state = "num_dot"
                return True
            if c in "eE":
                self.state = "num_e"
                return True
        if s == "num_int":
            if c.isdigit():
                return True
            if c == ".":
                self.state = "num_dot"
                return True
            if c in "eE":
                self.state = "num_e"
                return True
        if s == "num_frac":
            if c.isdigit():
                return True
            if c in "eE":
                self.state = "num_e"
                return True
        if s == "num_exp" and c.isdigit():
            return True
        # The number ends here; the char belongs to the enclosing
        # structure -- re-dispatch it from after_value.
        self._end_value()
        return self._advance_char(c)

    def advance_str(self, text: str) -> bool:
        for c in text:
            if not self.advance_char(c):
                return False
        return True


class JsonTokenMasks:
    """Token-level lift of JsonFsm for one vocabulary, with a mask cache
    keyed by automaton state. Shared across requests (build once per
    model: the per-token strings + first-char table cost one pass over
    the vocab)."""

    def __init__(self, vocab: Sequence[Optional[str]],
                 vocab_size: Optional[int] = None) -> None:
        self.vocab_size = vocab_size or len(vocab)
        # Token id -> string; None/empty = never allowed (special
        # tokens, ids past the tokenizer's range).
        self.strings: List[Optional[str]] = [
            (s if s else None) for s in vocab
        ] + [None] * (self.vocab_size - len(vocab))
        self.first = [s[0] if s else None for s in self.strings]
        # LRU-bounded: one vocab-size bool array per distinct automaton
        # state; adversarially varied nesting would otherwise grow the
        # table without bound over a server's lifetime.
        self._cache: "OrderedDict[Tuple, np.ndarray]" = OrderedDict()
        self._cache_cap = 256

    # Budget forcing kicks in once this many tokens remain: below it,
    # a token is only legal if the document can still CLOSE within the
    # post-token budget (min_close_chars lower-bounds tokens-to-close).
    # Without this, a weak model rambles inside a string until
    # max_new_tokens and the output is an unparseable prefix.
    FORCE_CLOSE_AT = 48

    # Cache keys quantize ``remaining`` DOWN onto these buckets: a raw
    # key would make every late-request step a cache miss (remaining
    # decrements each token), re-running the full-vocab FSM sweep per
    # token on the host critical path. Rounding down is conservative --
    # a mask computed for a smaller budget only closes earlier, never
    # emits an unclosable token.
    _REMAINING_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48)

    def mask_for(self, fsm: JsonFsm,
                 remaining: Optional[int] = None) -> np.ndarray:
        tight = remaining is not None and remaining <= self.FORCE_CLOSE_AT
        if tight:
            remaining = max(
                b for b in self._REMAINING_BUCKETS if b <= max(remaining, 1)
            )
        key = fsm.mask_key() + ((remaining,) if tight else ())
        m = self._cache.get(key)
        if m is not None:
            self._cache.move_to_end(key)
            return m
        # First-char pre-filter: one clone per DISTINCT first char.
        ok_first: dict[str, bool] = {}
        m = np.zeros(self.vocab_size, bool)
        for tid, s in enumerate(self.strings):
            if s is None:
                continue
            c0 = self.first[tid]
            ok = ok_first.get(c0)
            if ok is None:
                ok = ok_first[c0] = fsm.clone().advance_char(c0)
            if not ok:
                continue
            if not tight and len(s) == 1:
                m[tid] = True
                continue
            f2 = fsm.clone()
            if not f2.advance_str(s):
                continue
            m[tid] = (not tight
                      or f2.min_close_chars() <= remaining - 1)
        if tight and not m.any():
            # Budget already unsatisfiable (caller gave too few tokens):
            # best effort -- fall back to the unrestricted valid set so
            # generation stays grammatical as far as it goes.
            m = self.mask_for(fsm)
        self._cache[key] = m
        while len(self._cache) > self._cache_cap:
            self._cache.popitem(last=False)
        return m


class JsonConstraint:
    """Per-request constraint object the engine consumes:
    ``mask()`` -> [vocab] bool of currently-legal tokens,
    ``advance(token_id)`` after each emitted token,
    ``complete`` -> finish the request (output parses as one object)."""

    def __init__(self, masks: JsonTokenMasks) -> None:
        self.masks = masks
        self.fsm = JsonFsm()

    def mask(self, remaining: Optional[int] = None) -> np.ndarray:
        return self.masks.mask_for(self.fsm, remaining)

    def advance(self, token_id: int) -> bool:
        s = (self.masks.strings[token_id]
             if 0 <= token_id < len(self.masks.strings) else None)
        if s is None:
            return False
        return self.fsm.advance_str(s)

    @property
    def complete(self) -> bool:
        return self.fsm.complete


def byte_vocab(vocab_size: int) -> List[Optional[str]]:
    """Vocab strings for the ByteTokenizer: ids 0..255 are single
    bytes (decoded latin-1-ish via utf-8 semantics: only ASCII ids map
    to standalone chars; non-ASCII lead/continuation bytes cannot be
    validated char-wise, so they are masked out -- constrained JSON
    from a byte model is ASCII-only, which json.loads accepts with
    \\u escapes available for everything else)."""
    out: List[Optional[str]] = []
    for i in range(min(vocab_size, 256)):
        out.append(chr(i) if i < 0x80 else None)
    return out


def tokenizer_vocab_strings(tok, vocab_size: int) -> List[Optional[str]]:
    """Per-token strings from a `tokenizers`/HF-style tokenizer via
    single-id decode (byte-level BPE decodes any id standalone).
    Special tokens decode to ""/markers that the FSM then rejects.

    LIMITATION (multi-byte UTF-8): a byte-level BPE token holding a
    FRAGMENT of a multi-byte codepoint does not decode standalone --
    ``tok.decode([i])`` yields U+FFFD for it, so the simulated string
    diverges from what the token actually contributes mid-sequence.
    Consequences: (a) such tokens are masked out inside JSON strings
    even where the real bytes would be legal, so constrained output is
    restricted to codepoints the vocabulary covers with whole-codepoint
    tokens (ASCII always works; ``\\uXXXX`` escapes remain available
    for the rest); (b) the min_close_chars token budget counts the
    replacement char, not the fragment's true length, so the
    force-close bound is computed against the simulated -- not actual
    -- text. Fixing this needs byte-level vocab extraction (e.g.
    ByteLevel alphabet inversion), deferred until a real tokenizer
    rides this path in CI."""
    out: List[Optional[str]] = []
    failed = 0
    last_err: Optional[BaseException] = None
    for i in range(vocab_size):
        try:
            s = tok.decode([i])
        except Exception as e:  # kt-lint: disable=KT-SWALLOW01 -- per-id
            # decode failures (special/out-of-range ids) are expected and
            # per-id logging would spam 32k lines; summarized below.
            s = None
            failed += 1
            last_err = e
        out.append(s if s else None)
    if failed:
        logger.debug("vocab extraction: %d/%d ids failed to decode "
                     "(last error: %s)", failed, vocab_size, last_err)
    return out
