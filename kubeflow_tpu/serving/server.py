"""aiohttp ModelServer speaking the V1 and V2 inference protocols.

Routes (KServe-equivalent, SURVEY.md 3.3 S4 / call stack 4.5):

V1:
- ``GET  /v1/models/{m}``            readiness {"name", "ready"}
- ``POST /v1/models/{m}:predict``    {"instances": [...]} -> {"predictions": [...]}

V2 (Open Inference Protocol):
- ``GET  /v2``                        server metadata
- ``GET  /v2/health/live|ready``
- ``GET  /v2/models/{m}``             model metadata
- ``GET  /v2/models/{m}/ready``
- ``POST /v2/models/{m}/infer``       {"inputs": [{name, shape, datatype, data}]}
- ``POST /v2/repository/models/{m}/load|unload``

Plus ``GET /healthz`` (controller readiness probe) and ``GET /metrics``.

The server process is what an ISVC replica runs; the controller spawns it
via the same ProcessLauncher that runs training workers, with --port/
--model-dir injected (the reference's container args).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional

from aiohttp import web

from kubeflow_tpu.obs import registry as obs_registry
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.serving.model import TRACE, InferenceError, ModelRepository

logger = logging.getLogger(__name__)


class ModelServer:
    def __init__(self, repository: Optional[ModelRepository] = None,
                 name: str = "kftpu-modelserver",
                 payload_logger=None, grpc_port: int = 0,
                 grpc_host: str = "127.0.0.1") -> None:
        self.name = name
        self.repository = repository or ModelRepository()
        # S6 request/response logger (serving.payload_logger), optional.
        self.payload_logger = payload_logger
        # OIP gRPC transport (serving/grpc_server.py); 0 = HTTP only.
        self.grpc_port = grpc_port
        self.grpc_host = grpc_host
        self._grpc_server = None
        self.started_at = time.time()
        self.request_count = 0
        self.error_count = 0
        self.predict_seconds = 0.0
        # Server-level counters expose through the shared registry
        # formatter (h_metrics); the attribute ints above stay the
        # increment sites (hot handlers touch a plain int, the registry
        # sees the value at scrape time).
        self.metrics = obs_registry.Registry()
        self._stream_seq = 0  # stream-emit span track ids

    # -- app --------------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.add_routes([
            web.get("/healthz", self.h_healthz),
            web.get("/metrics", self.h_metrics),
            web.get("/debug/trace", self.h_debug_trace),
            # V1
            web.get("/v1/models/{m}", self.h_v1_status),
            web.post("/v1/models/{m:[^:]+}:predict", self.h_v1_predict),
            web.post("/v1/models/{m:[^:]+}:explain", self.h_v1_explain),
            # V2
            web.get("/v2", self.h_v2_server),
            web.get("/v2/health/live", self.h_v2_live),
            web.get("/v2/health/ready", self.h_v2_ready),
            web.get("/v2/models/{m}", self.h_v2_model_meta),
            web.get("/v2/models/{m}/ready", self.h_v2_model_ready),
            web.post("/v2/models/{m}/infer", self.h_v2_infer),
            web.post("/v2/models/{m}/generate", self.h_v2_generate),
            # Disaggregated prefill/decode KV handoff (docs/FLEET.md):
            # export serializes a prefilled prefix-cache entry through
            # the router wire format; import adopts one.
            web.post("/v2/models/{m}/prefix/export",
                     self.h_v2_prefix_export),
            web.post("/v2/models/{m}/prefix/import",
                     self.h_v2_prefix_import),
            web.get("/v2/models/{m}/prefix/inventory",
                    self.h_v2_prefix_inventory),
            web.post("/v2/models/{m}/generate_stream",
                     self.h_v2_generate_stream),
            web.post("/v2/repository/models/{m}/load", self.h_v2_load),
            web.post("/v2/repository/models/{m}/unload", self.h_v2_unload),
            # OpenAI-compatible surface (reference: huggingfaceserver).
            web.get("/openai/v1/models", self.h_openai_models),
            web.post("/openai/v1/completions", self.h_openai_completions),
            web.post("/openai/v1/chat/completions", self.h_openai_chat),
            web.post("/openai/v1/embeddings", self.h_openai_embeddings),
        ])

        async def on_startup(app):
            self.repository.start()
            if self.grpc_port:
                from kubeflow_tpu.serving.grpc_server import start_grpc

                self._grpc_server = await start_grpc(
                    self, self.grpc_host, self.grpc_port
                )

        async def on_cleanup(app):
            if self._grpc_server is not None:
                await self._grpc_server.stop(grace=2.0)
                self._grpc_server = None
            await self.repository.stop()
            if self.payload_logger is not None:
                await self.payload_logger.close()

        app.on_startup.append(on_startup)
        app.on_cleanup.append(on_cleanup)
        return app

    def run(self, host: str = "127.0.0.1", port: int = 8080) -> None:
        web.run_app(self.build_app(), host=host, port=port, print=None)

    # -- helpers ----------------------------------------------------------

    def _ready(self) -> bool:
        if self.repository.multi_model:
            # Multi-model replicas are ready when the PROCESS is up:
            # they boot empty, and one slow/unloaded model must not take
            # every other model on the replica out of rotation (per-model
            # readiness is enforced per-request).
            return True
        names = self.repository.names()
        return bool(names) and all(
            self.repository.get(n).ready for n in names
        )

    @staticmethod
    def _err(e: Exception) -> web.Response:
        status = e.status if isinstance(e, InferenceError) else 500
        return web.json_response({"error": str(e)}, status=status)

    # -- health / metrics --------------------------------------------------

    async def h_healthz(self, req: web.Request) -> web.Response:
        out = {
            "ok": True, "ready": self._ready(),
            "models": self.repository.names(),
            "uptime": time.time() - self.started_at,
        }
        # Router load signals (docs/FLEET.md): per-model queue/TTFT
        # gauges so the activator's load poll is this one GET instead
        # of a Prometheus scrape + parse. Additive key -- old probers
        # only read "ready".
        role = os.environ.get("KFTPU_REPLICA_ROLE", "")
        if role:
            out["role"] = role
        load = {}
        for n in self.repository.names():
            model = self.repository.get(n)
            gauges = getattr(model, "engine_gauges", None)
            if gauges is None or getattr(model, "engine", None) is None:
                continue
            g = gauges()
            load[n] = {k: g[k] for k in (
                "queue_depth", "slots_active", "max_slots", "ttft_ema_ms",
                "chunk_headroom",
            ) if k in g}
        if load:
            out["load"] = load
        return web.json_response(out)

    async def h_v2_prefix_export(self, req: web.Request) -> web.Response:
        name = req.match_info["m"]
        try:
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", 503)
            fn = getattr(model, "export_prefix_packet", None)
            if fn is None:
                raise InferenceError(
                    f"model {name} does not support KV handoff", 501
                )
            body = await req.json()
            # ensure_prefix blocks on an engine-thread prefill: keep the
            # event loop serving while it runs.
            buf = await asyncio.to_thread(
                fn, body.get("prompt"), body.get("token_ids"),
                bool(body.get("ensure", True)),
            )
        except json.JSONDecodeError:
            return web.json_response({"error": "body must be JSON"},
                                     status=400)
        except InferenceError as e:
            return self._err(e)
        if buf is None:
            # Prompt under one prefix block: nothing to hand off, the
            # decode replica just prefills it locally.
            return web.Response(status=204)
        return web.Response(body=buf,
                            content_type="application/octet-stream")

    async def h_v2_prefix_import(self, req: web.Request) -> web.Response:
        name = req.match_info["m"]
        try:
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", 503)
            fn = getattr(model, "import_prefix_packet", None)
            if fn is None:
                raise InferenceError(
                    f"model {name} does not support KV handoff", 501
                )
            buf = await req.read()
            plen = await asyncio.to_thread(fn, buf)
        except InferenceError as e:
            return self._err(e)
        return web.json_response({"plen": plen})

    async def h_v2_prefix_inventory(self, req: web.Request) -> web.Response:
        """Hottest-first prefix-cache inventory (hash/plen/bytes/tick/
        tokens rows) -- what the migration planner (serving/kv_reshard)
        feeds ring_diff to decide which entries to ship on a fleet
        topology change. ``?top_k=N`` caps the listing."""
        name = req.match_info["m"]
        try:
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", 503)
            fn = getattr(model, "prefix_inventory", None)
            if fn is None:
                raise InferenceError(
                    f"model {name} does not support KV handoff", 501
                )
            try:
                top_k = int(req.query.get("top_k", 0))
            except ValueError:
                return web.json_response(
                    {"error": "top_k must be an integer"}, status=400)
            rows = await asyncio.to_thread(fn, top_k)
        except InferenceError as e:
            return self._err(e)
        return web.json_response({"entries": rows})

    async def h_metrics(self, req: web.Request) -> web.Response:
        m = self.metrics
        m.counter("kftpu_server_requests_total").value = self.request_count
        m.counter("kftpu_server_errors_total").value = self.error_count
        # Pre-formatted at six decimals: the exact pre-port line format.
        m.counter("kftpu_server_predict_seconds_total").value = (
            f"{self.predict_seconds:.6f}"
        )
        lines = m.expose()
        for name in self.repository.names():
            try:
                lines += self.repository.get(name).prom_metrics()
            except Exception:  # noqa: BLE001 - one model's metrics
                logger.exception(  # failure must not break the scrape
                    "prom_metrics failed for %s", name)
        return web.Response(text="\n".join(lines) + "\n")

    async def h_debug_trace(self, req: web.Request) -> web.Response:
        """This process's span recorder as Chrome trace-event JSON --
        loadable in Perfetto directly, or merged across planes by
        ``kftpu trace dump``. Empty trace when tracing is off."""
        return web.json_response(obs_trace.recorder().export())

    # -- V1 ----------------------------------------------------------------

    async def h_v1_status(self, req: web.Request) -> web.Response:
        name = req.match_info["m"]
        try:
            model = self.repository.get(name)
        except InferenceError as e:
            return self._err(e)
        return web.json_response({"name": name, "ready": model.ready})

    async def h_v1_predict(self, req: web.Request) -> web.Response:
        name = req.match_info["m"]
        self.request_count += 1
        t0 = time.monotonic()
        if TRACE:
            logger.info("TRACE v1_predict start %s", name)
        try:
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", status=503)
            self.repository.touch(name)  # LRU recency for multi-model
            body = await req.json()
            instances = body.get("instances")
            if not isinstance(instances, list):
                raise InferenceError('body must have "instances": [...]', status=400)
            rid = await self._log_request(name, body, req)
            batcher = self.repository.batcher(name)
            pre = [model.preprocess(i) for i in instances]
            outs = await asyncio.gather(*(batcher.predict(i) for i in pre))
            preds = [model.postprocess(o) for o in outs]
            resp = {"predictions": preds}
            await self._log_response(name, resp, rid)
            return web.json_response(resp)
        except json.JSONDecodeError:
            self.error_count += 1
            return web.json_response({"error": "body is not JSON"}, status=400)
        except Exception as e:  # noqa: BLE001
            self.error_count += 1
            return self._err(e)
        finally:
            self.predict_seconds += time.monotonic() - t0

    async def h_v1_explain(self, req: web.Request) -> web.Response:
        """V1 explain (the reference's :explain verb): explainer replicas
        serve this via Model.explain; attribution calls back into the
        predictor happen inside the model (off-loop -- explain fans one
        instance into many predictor calls)."""
        name = req.match_info["m"]
        self.request_count += 1
        t0 = time.monotonic()
        try:
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", status=503)
            self.repository.touch(name)
            body = await req.json()
            instances = body.get("instances")
            if not isinstance(instances, list):
                raise InferenceError('body must have "instances": [...]', status=400)
            rid = await self._log_request(name, body, req)
            outs = await asyncio.to_thread(model.explain, instances)
            resp = {"explanations": outs}
            await self._log_response(name, resp, rid)
            return web.json_response(resp)
        except json.JSONDecodeError:
            self.error_count += 1
            return web.json_response({"error": "body is not JSON"}, status=400)
        except Exception as e:  # noqa: BLE001
            self.error_count += 1
            return self._err(e)
        finally:
            self.predict_seconds += time.monotonic() - t0

    # -- V2 ----------------------------------------------------------------

    async def h_v2_server(self, req: web.Request) -> web.Response:
        return web.json_response({
            "name": self.name, "version": "2",
            "extensions": ["model_repository"],
        })

    async def h_v2_live(self, req: web.Request) -> web.Response:
        return web.json_response({"live": True})

    async def h_v2_ready(self, req: web.Request) -> web.Response:
        return web.json_response({"ready": self._ready()})

    async def h_v2_model_meta(self, req: web.Request) -> web.Response:
        try:
            return web.json_response(self.repository.get(req.match_info["m"]).metadata())
        except InferenceError as e:
            return self._err(e)

    async def h_v2_model_ready(self, req: web.Request) -> web.Response:
        try:
            model = self.repository.get(req.match_info["m"])
        except InferenceError as e:
            return self._err(e)
        return web.json_response({"name": model.name, "ready": model.ready})

    async def v2_infer(self, name: str, inputs: list) -> list:
        """The V2 infer core, shared by the REST route and the gRPC
        ModelInfer servicer: readiness, batcher fan-out, output
        normalization. Returns the V2 output-tensor dicts."""
        model = self.repository.get(name)
        if not model.ready:
            raise InferenceError(f"model {name} is not ready", status=503)
        self.repository.touch(name)  # LRU recency for multi-model
        if not isinstance(inputs, list) or not inputs:
            raise InferenceError('body must have "inputs": [...]', status=400)
        batcher = self.repository.batcher(name)
        # V2 tensors ride through preprocess/predict as dicts; simple
        # models treat input.data as the instance list.
        pre = model.preprocess({"inputs": inputs})
        instances = pre["inputs"] if isinstance(pre, dict) and "inputs" in pre else pre
        outs = await asyncio.gather(*(batcher.predict(i) for i in instances))
        outputs = model.postprocess(outs)
        if not (isinstance(outputs, list) and outputs
                and isinstance(outputs[0], dict) and "data" in outputs[0]):
            outputs = [{
                "name": "output_0", "datatype": "FP32",
                "shape": [len(outs)], "data": outputs,
            }]
        return outputs

    async def h_v2_infer(self, req: web.Request) -> web.Response:
        name = req.match_info["m"]
        self.request_count += 1
        t0 = time.monotonic()
        try:
            body = await req.json()
            rid = await self._log_request(name, body, req)
            outputs = await self.v2_infer(name, body.get("inputs"))
            resp = {
                "model_name": name, "id": body.get("id", ""), "outputs": outputs,
            }
            # OIP response `parameters` map: live dispatch-pipeline
            # gauges for engine-backed models (docs/SERVING.md), the
            # same payload the gRPC ModelInfer response carries. Plain
            # models expose no gauges and the key stays absent.
            gauges = getattr(self.repository.get(name), "engine_gauges", None)
            if gauges is not None:
                resp["parameters"] = gauges()
            await self._log_response(name, resp, rid)
            return web.json_response(resp)
        except json.JSONDecodeError:
            self.error_count += 1
            return web.json_response({"error": "body is not JSON"}, status=400)
        except Exception as e:  # noqa: BLE001
            self.error_count += 1
            return self._err(e)
        finally:
            self.predict_seconds += time.monotonic() - t0

    # -- V2 generate extension (LLM text generation, streaming) ------------

    @staticmethod
    def _generate_instance(body: dict) -> dict:
        """Map a V2 generate body to an engine instance. Accepts the OIP
        generate-extension shape ({"text_input", "parameters": {...}})
        and the V1-instance shape ({"prompt"|"token_ids", ...}) alike."""
        inst = dict(body.get("parameters") or {})
        for k in ("prompt", "token_ids", "max_new_tokens", "temperature",
                  "top_k", "top_p", "eos_id", "stop", "logprobs",
                  "response_format", "stream_pacing"):
            if k in body:
                inst[k] = body[k]
        if "text_input" in body:
            inst["prompt"] = body["text_input"]
        return inst

    async def h_v2_generate(self, req: web.Request) -> web.Response:
        """Non-streaming generate: same contract as generate_stream with
        the tokens collected server-side."""
        name = req.match_info["m"]
        self.request_count += 1
        t0 = time.monotonic()
        try:
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", status=503)
            self.repository.touch(name)
            body = await req.json()
            fut, decode = model.submit_stream(
                self._generate_instance(body), None
            )
            try:
                ids = await asyncio.wrap_future(fut)
            except ValueError as e:
                raise InferenceError(str(e), 400)
            return web.json_response({
                "model_name": name, "id": body.get("id", ""),
                "text_output": decode(ids), "token_ids": ids,
            })
        except json.JSONDecodeError:
            self.error_count += 1
            return web.json_response({"error": "body is not JSON"}, status=400)
        except Exception as e:  # noqa: BLE001
            self.error_count += 1
            return self._err(e)
        finally:
            self.predict_seconds += time.monotonic() - t0

    async def _stream_deltas(self, model, inst, stops=()):
        """Traced wrapper over ``_stream_deltas_inner``: one
        ``stream-emit`` span per streaming request on its own track
        (streams interleave on the event loop, so a shared track would
        unbalance B/E pairs), annotated with the emitted event count."""
        if not obs_trace.enabled():
            async for item in self._stream_deltas_inner(model, inst, stops):
                yield item
            return
        self._stream_seq += 1
        track = f"stream/{self._stream_seq}"
        obs_trace.begin("stream-emit", plane="serving", track=track,
                        model=model.name)
        events = 0
        try:
            async for item in self._stream_deltas_inner(model, inst, stops):
                events += 1
                yield item
        finally:
            obs_trace.end("stream-emit", plane="serving", track=track,
                          events=events)

    async def _stream_deltas_inner(self, model, inst, stops=()):
        """Async generator over one streaming generation: yields
        (delta_text, token_id_or_None, ids_so_far) per event, handling
        the engine-thread bridge and split-codepoint withholding (deltas
        must concatenate EXACTLY to the final text: a codepoint split
        across tokens decodes to a trailing U+FFFD that the next token
        replaces -- or raises, for a strict decoder -- so the unstable
        tail is held back). With ``stops``, text is additionally
        withheld while it could be a stop-string prefix, and the stream
        ends at the match with the stop text excluded (the engine-side
        stop_fn frees the slot; this trims the transport). Raises the
        engine error, if any, at the end. Shared by the V2
        generate_stream and OpenAI SSE framings.

        PACING (on by default; ``stream_pacing: false`` opts out): the
        engine's block decode delivers tokens in dispatch-boundary
        BURSTS (decode_block at a time), so raw forwarding gives a
        client ITL of 0ms within a burst and a whole block-time at its
        edge. The drain below re-times emission at the measured steady
        per-token rate (cumulative mean of arrival intervals), which is
        what a human reader or a typewriter UI actually wants. The
        trade: a token emits up to ~one block-time later than it
        arrived (final-token latency grows by its in-burst index x
        TPOT); throughput and TTFT are untouched (the first token is
        never delayed, and the engine never waits on the transport)."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        done = object()
        pacing = bool(inst.get("stream_pacing", True))

        def on_token(tok: int) -> None:  # engine thread
            loop.call_soon_threadsafe(q.put_nowait, (tok, time.monotonic()))

        fut, decode = model.submit_stream(inst, on_token)
        fut.add_done_callback(
            lambda _f: loop.call_soon_threadsafe(q.put_nowait, done)
        )
        ids: list = []
        text = ""
        stopped = False
        t_prev = None    # previous ARRIVAL (rate estimation)
        tpot = 0.0       # EMA of per-token arrival interval
        next_t = 0.0     # earliest next emission
        while True:
            item = await q.get()
            if item is done:
                break
            tok, t_arr = item
            if t_prev is not None and pacing:
                # EMA over inter-arrival gaps: burst-interior gaps are
                # ~0 and the dispatch boundary carries the whole block,
                # so the EMA converges to block_time/block = steady
                # TPOT within a couple of blocks, and re-converges fast
                # if the engine's rate shifts (slots joining/leaving).
                tpot = 0.9 * tpot + 0.1 * (t_arr - t_prev)
                now = time.monotonic()
                # Sleep toward the schedule, capped at 2 token-times;
                # a growing backlog shrinks the sleep proportionally so
                # buffered lag stays bounded (smoothly, no cliff) when
                # the estimate runs slow or the engine finished early.
                wait = min(next_t - now, 2.0 * tpot) / (1 + q.qsize() / 8)
                if wait > 0:
                    await asyncio.sleep(wait)
                next_t = max(now, next_t) + tpot
            t_prev = t_arr
            ids.append(tok)
            try:
                full = decode(ids)
            except (UnicodeDecodeError, ValueError):
                full = None
            delta = ""
            if (full is not None and full.startswith(text)
                    and not full.endswith("\ufffd")):
                if stops:
                    trimmed, stopped = self._trim_at_stop(full, stops)
                    if stopped:
                        # Everything before the stop (never emitted past
                        # it: partial matches below were withheld).
                        yield trimmed[len(text):], tok, ids
                        break
                    # Withhold a tail that could grow into a stop match.
                    safe = len(full)
                    for s in stops:
                        for L in range(
                            min(len(s) - 1, len(full)), 0, -1
                        ):
                            if full.endswith(s[:L]):
                                safe = min(safe, len(full) - L)
                                break
                    safe = max(safe, len(text))
                    delta, text = full[len(text):safe], full[:safe]
                else:
                    delta, text = full[len(text):], full
            yield delta, tok, ids
        if ids and not stopped:
            # Flush any withheld tail (stream ended mid-codepoint or in
            # a partial stop match that never completed).
            try:
                full = decode(ids)
            except (UnicodeDecodeError, ValueError):
                full = text
            tail = full[len(text):] if full.startswith(text) else full
            if stops:
                tail, _ = self._trim_at_stop(tail, stops)
            if tail:
                yield tail, None, ids
        # After a transport-side stop break the engine may still be
        # finishing the request (its own stop_fn normally ends it): a
        # bare fut.exception() would BLOCK the event loop until then.
        if fut.done():
            exc = fut.exception()
            if exc is not None:
                raise exc

    async def _sse_response(self, req: web.Request) -> web.StreamResponse:
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "text/event-stream"
        resp.headers["Cache-Control"] = "no-cache"
        resp.headers["X-Accel-Buffering"] = "no"
        await resp.prepare(req)
        return resp

    async def h_v2_generate_stream(self, req: web.Request) -> web.StreamResponse:
        """SSE token stream: one ``data: {...}`` event per generated token
        with the incremental text delta, then ``data: [DONE]``. TTFT is
        the time to the first event -- the reason this route exists."""
        name = req.match_info["m"]
        self.request_count += 1
        t0 = time.monotonic()
        try:
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", status=503)
            self.repository.touch(name)
            body = await req.json()
            stream = self._stream_deltas(
                model, self._generate_instance(body)
            )
            # Prime before prepare: submit-time errors (bad instance,
            # dead engine) must be clean HTTP errors, not mid-SSE.
            first = await anext(stream, None)
        except json.JSONDecodeError:
            self.error_count += 1
            return web.json_response({"error": "body is not JSON"}, status=400)
        except ValueError as e:
            # Engine-side request validation (too long, empty): client
            # error, same status the buffered route returns.
            self.error_count += 1
            return self._err(InferenceError(str(e), 400))
        except Exception as e:  # noqa: BLE001
            self.error_count += 1
            return self._err(e)
        resp = await self._sse_response(req)
        try:
            async def emit(delta, tok):
                ev = {"text_output": delta}
                if tok is not None:
                    ev["token_id"] = tok
                await resp.write(b"data: " + json.dumps(ev).encode()
                                 + b"\n\n")

            try:
                if first is not None:
                    await emit(first[0], first[1])
                    async for delta, tok, _ids in stream:
                        await emit(delta, tok)
            except (ConnectionResetError, asyncio.CancelledError):
                raise  # client hung up: outer handler, not an error stat
            except Exception as e:  # noqa: BLE001 - headers already sent:
                self.error_count += 1  # the error must go in-band
                await resp.write(
                    b"data: " + json.dumps({"error": str(e)}).encode()
                    + b"\n\n"
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away mid-stream: the engine request keeps
            # running to completion (slot freed by budget/EOS); nothing
            # to clean up here beyond dropping the queue.
            pass
        finally:
            self.predict_seconds += time.monotonic() - t0
        return resp

    # -- OpenAI-compatible API (reference: huggingfaceserver's OpenAI
    # endpoints in front of the vLLM backend) ------------------------------

    @staticmethod
    def _openai_instance(body: dict, prompt: str, chat: bool) -> dict:
        # Every knob is NULLABLE in the OpenAI API (clients/proxies send
        # explicit nulls): null means default, not TypeError.
        def opt(key, default, cast):
            v = body.get(key)
            return default if v is None else cast(v)

        inst = {
            "prompt": prompt,
            "max_new_tokens": opt("max_tokens", 16, int),
            "temperature": opt("temperature", 1.0, float),
            "top_p": opt("top_p", 1.0, float),
        }
        stop = body.get("stop")
        if stop:
            inst["stop"] = stop
        # Logprob capture count for the engine. Completions: logprobs is
        # an int top-N (0 = chosen-token logprob only -- still needs
        # capture, so floor at 1 and trim in the response). Chat:
        # logprobs is a bool gating top_logprobs.
        if chat:
            if body.get("logprobs"):
                inst["logprobs"] = max(1, opt("top_logprobs", 0, int))
        elif body.get("logprobs") is not None:
            inst["logprobs"] = max(1, int(body["logprobs"]))
        # Client-paced streaming opt-out: OpenAI's stream_options
        # carries extensions; a top-level stream_pacing also works.
        so = body.get("stream_options")
        if isinstance(so, dict) and "pacing" in so:
            inst["stream_pacing"] = bool(so["pacing"])
        elif body.get("stream_pacing") is not None:
            inst["stream_pacing"] = bool(body["stream_pacing"])
        rf = body.get("response_format")
        if rf is not None:
            # OpenAI structured output: {"type": "text" | "json_object"}.
            # json_object rides token-mask constrained decoding in the
            # engine (serving/jsonmode.py); json_schema is out of scope
            # and rejected explicitly rather than silently ignored.
            rtype = rf.get("type") if isinstance(rf, dict) else rf
            if rtype == "json_object":
                inst["response_format"] = "json_object"
            elif rtype not in (None, "text"):
                raise InferenceError(
                    f'unsupported response_format type {rtype!r} '
                    '(supported: "text", "json_object")', 400)
        return inst

    @staticmethod
    def _stops(body: dict) -> list:
        stop = body.get("stop")
        if stop is None:
            return []
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list) or not all(
            isinstance(s, str) for s in stop
        ):
            raise InferenceError(
                '"stop" must be a string or a list of strings', 400)
        return [s for s in stop if s]

    @staticmethod
    def _normalize_messages(messages) -> list:
        """Validate and flatten OpenAI messages to
        [{"role", "content":str}] (content-parts concatenated)."""
        if not isinstance(messages, list) or not messages:
            raise InferenceError('"messages" must be a non-empty list', 400)
        norm = []
        for m in messages:
            if not isinstance(m, dict) or "content" not in m:
                raise InferenceError(
                    'each message needs "role" and "content"', 400)
            content = m["content"]
            if isinstance(content, list):
                # OpenAI content-parts form: concatenate the text parts.
                texts = [
                    part.get("text", "") for part in content
                    if isinstance(part, dict) and part.get("type") == "text"
                ]
                if not texts:
                    raise InferenceError(
                        "only text content parts are supported", 400)
                content = " ".join(texts)
            elif not isinstance(content, str):
                raise InferenceError(
                    'message "content" must be a string or text parts',
                    400)
            norm.append({"role": m.get("role", "user"), "content": content})
        return norm

    @staticmethod
    def _chat_prompt(messages: list) -> str:
        """Fallback chat rendering when the model has no chat template:
        role-prefixed lines + assistant cue (documented, deterministic,
        good enough for the protocol surface). Models with a real
        template render through Model.render_chat instead."""
        lines = [f"{m['role']}: {m['content']}" for m in messages]
        lines.append("assistant:")
        return "\n".join(lines)

    @staticmethod
    def _trim_at_stop(text: str, stops: list) -> tuple:
        """(trimmed_text, stopped): cut at the EARLIEST stop match --
        OpenAI semantics exclude the stop sequence from the response."""
        hit = -1
        for s in stops:
            i = text.find(s)
            if i >= 0 and (hit < 0 or i < hit):
                hit = i
        return (text[:hit], True) if hit >= 0 else (text, False)

    @staticmethod
    def _logprobs_block(fut, decode, chat: bool, body: dict,
                        limit_chars=None):
        """Per-choice logprobs in the OpenAI response shape, from the
        engine request's captured records (riding fut.kftpu_request).
        ``limit_chars`` bounds the entries to the (stop-trimmed)
        response text -- the OpenAI contract excludes the stop sequence
        from text AND logprobs alike."""
        req = getattr(fut, "kftpu_request", None)
        if req is None or not req.logprob_data:
            return None

        def tok_str(tid):
            return decode([int(tid)])

        if chat:
            want_top = int(body.get("top_logprobs") or 0)
            content = []
            offset = 0
            for tid, rec in zip(req.generated, req.logprob_data):
                if limit_chars is not None and offset >= limit_chars:
                    break
                t = tok_str(tid)
                offset += len(t)
                content.append({
                    "token": t,
                    "logprob": rec["logprob"],
                    "top_logprobs": [
                        {"token": tok_str(i), "logprob": lp}
                        for i, lp in zip(
                            rec["top_ids"][:want_top],
                            rec["top_logprobs"][:want_top],
                        )
                    ],
                })
            return {"content": content}
        want_top = int(body.get("logprobs") or 0)
        tokens, token_lps, tops, offsets = [], [], [], []
        offset = 0
        for tid, rec in zip(req.generated, req.logprob_data):
            if limit_chars is not None and offset >= limit_chars:
                break
            t = tok_str(tid)
            tokens.append(t)
            token_lps.append(rec["logprob"])
            tops.append({
                tok_str(i): lp
                for i, lp in zip(rec["top_ids"][:want_top],
                                 rec["top_logprobs"][:want_top])
            } if want_top else None)
            offsets.append(offset)
            offset += len(t)
        return {
            "tokens": tokens,
            "token_logprobs": token_lps,
            "top_logprobs": tops if want_top else None,
            "text_offset": offsets,
        }

    async def h_openai_models(self, req: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [{"id": n, "object": "model", "owned_by": "kftpu"}
                     for n in self.repository.names()],
        })

    async def h_openai_embeddings(self, req: web.Request) -> web.Response:
        """OpenAI-compatible embeddings over any runtime whose predict
        returns one vector per instance (the jax-embed runtime; an HF
        embedding-task model works too). input: str | [str] | [ids] |
        [[ids]], the OpenAI contract."""
        self.request_count += 1
        t0 = time.monotonic()
        try:
            body = await req.json()
            name = body.get("model") or ""
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready",
                                     status=503)
            self.repository.touch(name)
            raw = body.get("input")
            if isinstance(raw, str):
                items: list = [raw]
            elif isinstance(raw, list) and raw and all(
                type(t) is int for t in raw
            ):
                items = [raw]  # one token-id array
            elif isinstance(raw, list) and raw:
                items = raw
            else:
                raise InferenceError(
                    '"input" must be a string, a list of strings, or '
                    "token-id array(s)", 400,
                )
            # Validate each item BEFORE enqueueing: the Batcher coalesces
            # concurrent requests into one predict batch and fails the
            # whole batch on any exception, so a malformed item must be
            # rejected here or it poisons other clients' requests.
            for i, item in enumerate(items):
                # type(t) is int, not isinstance: bool is an int
                # subclass, so [[true, false]] would otherwise embed as
                # token ids [1, 0] instead of being rejected.
                ok = (isinstance(item, str) and item) or (
                    isinstance(item, (list, tuple)) and item
                    and all(type(t) is int for t in item)
                )
                if not ok:
                    raise InferenceError(
                        f"input[{i}] must be a non-empty string or "
                        "token-id list", 400,
                    )
            # Through the model's Batcher, like the V1 route: the
            # repository's eviction guard watches batcher.inflight, so
            # an LRU unload cannot null the model mid-request; same-model
            # requests also coalesce into one device batch.
            batcher = self.repository.batcher(name)
            vecs = await asyncio.gather(
                *(batcher.predict(i) for i in items)
            )
            for v in vecs:
                if not isinstance(v, list) or (
                    v and not isinstance(v[0], (int, float))
                ):
                    raise InferenceError(
                        f"model {name} is not an embedding model "
                        "(predict must return one vector per input)", 400,
                    )
            n_tok = sum(
                len(i) if isinstance(i, list) else max(1, len(i) // 4)
                for i in items
            )
            return web.json_response({
                "object": "list",
                "model": name,
                "data": [
                    {"object": "embedding", "index": i, "embedding": v}
                    for i, v in enumerate(vecs)
                ],
                "usage": {"prompt_tokens": n_tok, "total_tokens": n_tok},
            })
        except json.JSONDecodeError:
            self.error_count += 1
            return web.json_response({"error": "body is not JSON"},
                                     status=400)
        except Exception as e:  # noqa: BLE001 - route must answer
            self.error_count += 1
            return self._err(e)
        finally:
            self.predict_seconds += time.monotonic() - t0

    async def _openai_generate(self, req, chat: bool) -> web.StreamResponse:
        self.request_count += 1
        t0 = time.monotonic()
        obj = "chat.completion" if chat else "text_completion"
        streaming = False  # once True, the SSE tail owns predict_seconds
        try:
            body = await req.json()
            name = body.get("model") or ""
            model = self.repository.get(name)
            if not model.ready:
                raise InferenceError(f"model {name} is not ready", status=503)
            self.repository.touch(name)
            if chat:
                norm = self._normalize_messages(body.get("messages"))
                prompt = None
                try:
                    prompt = model.render_chat(norm)
                except Exception as e:  # noqa: BLE001 - template rejects
                    logger.warning(  # these messages: generic fallback
                        "chat template failed (%s); generic rendering", e)
                if prompt is None:
                    prompt = self._chat_prompt(norm)
            else:
                p = body.get("prompt")
                if isinstance(p, list):
                    if len(p) != 1:
                        raise InferenceError(
                            "only a single prompt is supported", 400)
                    p = p[0]
                if not isinstance(p, str):
                    raise InferenceError('"prompt" must be a string', 400)
                prompt = p
            inst = self._openai_instance(body, prompt, chat)
            stops = self._stops(body)
            n_choices = int(body.get("n") or 1)
            if not 1 <= n_choices <= 16:
                raise InferenceError('"n" must be between 1 and 16', 400)
            rid = f"{'chatcmpl' if chat else 'cmpl'}-{int(t0 * 1000):x}"
            if not body.get("stream"):
                # n > 1: n engine requests (continuous batching runs them
                # concurrently); sampling lanes draw independent noise,
                # so choices differ at temperature > 0 and are identical
                # at 0, the OpenAI behavior.
                futs = [model.submit_stream(inst, None)
                        for _ in range(n_choices)]
                choices = []
                completion_tokens = 0
                for i, (fut, decode) in enumerate(futs):
                    try:
                        ids = await asyncio.wrap_future(fut)
                    except ValueError as e:
                        raise InferenceError(str(e), 400)
                    completion_tokens += len(ids)
                    text = decode(ids)
                    finish = ("length"
                              if len(ids) >= inst["max_new_tokens"]
                              else "stop")
                    text, stopped = self._trim_at_stop(text, stops)
                    if stopped:
                        finish = "stop"
                    lp = self._logprobs_block(
                        fut, decode, chat, body,
                        limit_chars=len(text) if stopped else None,
                    )
                    choice = (
                        {"index": i, "finish_reason": finish,
                         "message": {"role": "assistant", "content": text}}
                        if chat else
                        {"index": i, "finish_reason": finish, "text": text}
                    )
                    if lp is not None:
                        choice["logprobs"] = lp
                    choices.append(choice)
                pt = model.count_tokens(prompt)
                return web.json_response({
                    "id": rid, "object": obj, "model": name,
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": pt,
                        "completion_tokens": completion_tokens,
                        "total_tokens": pt + completion_tokens,
                    },
                })
            if n_choices != 1:
                raise InferenceError(
                    '"n" > 1 is not supported with "stream": true', 400)
            stream = self._stream_deltas(model, inst, stops=stops)
            first = await anext(stream, None)
            streaming = True
        except json.JSONDecodeError:
            self.error_count += 1
            return web.json_response({"error": "body is not JSON"}, status=400)
        except ValueError as e:
            self.error_count += 1
            return self._err(InferenceError(str(e), 400))
        except Exception as e:  # noqa: BLE001
            self.error_count += 1
            return self._err(e)
        finally:
            if not streaming:
                # Buffered + error paths account here; the SSE tail's own
                # finally covers the streaming path end-to-end.
                self.predict_seconds += time.monotonic() - t0
        resp = await self._sse_response(req)
        try:
            n_tokens = 0
            first_chunk = True

            async def emit(delta, finish=None):
                nonlocal first_chunk
                if chat:
                    d = {} if finish is not None else {"content": delta}
                    if first_chunk:
                        # OpenAI chat-stream contract: the first delta
                        # carries the assistant role.
                        d = {"role": "assistant", **d}
                    choice = {"index": 0, "finish_reason": finish,
                              "delta": d}
                else:
                    choice = {"index": 0, "finish_reason": finish,
                              "text": delta}
                first_chunk = False
                await resp.write(b"data: " + json.dumps({
                    "id": rid, "object": obj + ".chunk", "model": name,
                    "choices": [choice],
                }).encode() + b"\n\n")

            try:
                if first is not None:
                    n_tokens += first[1] is not None
                    await emit(first[0])
                    async for delta, tok, _ids in stream:
                        n_tokens += tok is not None
                        await emit(delta)
                await emit("", finish=(
                    "length" if n_tokens >= inst["max_new_tokens"]
                    else "stop"))
            except (ConnectionResetError, asyncio.CancelledError):
                raise  # client hung up: outer handler, not an error stat
            except Exception as e:  # noqa: BLE001 - headers sent: in-band
                self.error_count += 1
                await resp.write(
                    b"data: " + json.dumps({"error": str(e)}).encode()
                    + b"\n\n"
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self.predict_seconds += time.monotonic() - t0
        return resp

    async def h_openai_completions(self, req: web.Request):
        return await self._openai_generate(req, chat=False)

    async def h_openai_chat(self, req: web.Request):
        return await self._openai_generate(req, chat=True)

    # -- payload logging (S6) ----------------------------------------------

    async def _log_request(self, model: str, body, req) -> str:
        if self.payload_logger is None:
            return ""
        rid = req.headers.get("X-Request-Id") or self.payload_logger.new_id()
        await self.payload_logger.log_request(model, body, rid)
        return rid

    async def _log_response(self, model: str, resp, rid: str) -> None:
        if self.payload_logger is not None:
            await self.payload_logger.log_response(model, resp, rid)

    async def h_v2_load(self, req: web.Request) -> web.Response:
        name = req.match_info["m"]
        try:
            spec = None
            if req.can_read_body:
                try:
                    spec = await req.json()
                except json.JSONDecodeError:
                    spec = None
            if isinstance(spec, dict) and (
                "storage_uri" in spec or "options" in spec
            ):
                # Multi-model admission: the controller ships the model
                # spec; the repository constructs + loads it (LRU-
                # evicting at the replica's budget; heavy load off-loop).
                await self.repository.load_dynamic_async(
                    name, spec.get("storage_uri"),
                    spec.get("options") or {},
                )
            else:
                self.repository.load(name)
            return web.json_response({"name": name, "ready": True})
        except Exception as e:  # noqa: BLE001
            return self._err(e)

    async def h_v2_unload(self, req: web.Request) -> web.Response:
        name = req.match_info["m"]
        try:
            if self.repository.multi_model:
                # Deregister entirely: frees the replica's model budget.
                self.repository.evict(name)
            else:
                self.repository.unload(name)
            return web.json_response({"name": name, "ready": False})
        except Exception as e:  # noqa: BLE001
            return self._err(e)
