"""JAX/TPU text-embedding runtime (KServe huggingfaceserver's embedding
task, SURVEY.md 3.3 S5 delta).

The reference's serving stack exposes embedding models next to
generation (huggingfaceserver task=text_embedding; OpenAI-compatible
``/v1/embeddings``). The TPU-native equivalent runs the flax BERT
encoder (models/bert.py) under jit with bucketed static shapes:

- prompts tokenize, pad to a power-of-2 length bucket, and run as ONE
  batched forward per bucket (compile count O(#buckets), MXU-friendly
  batches);
- padding rides the encoder's ``pad_mask`` (attention segment ids), so
  an embedding is invariant to how much batch padding it shipped with;
- pooling: masked mean over real tokens (default) or the [CLS]/first
  token; L2-normalized by default (cosine-ready, the OpenAI contract).

Options (ModelSpec.options):
- ``preset``: bert config name (default bert-base; bert-tiny for tests)
- ``pooling``: "mean" (default) | "cls"
- ``normalize``: L2-normalize outputs (default True)
- ``tokenizer``: "byte" (default) or a local-cache HF tokenizer name
- ``checkpoint``: "none" (random init demo) or "orbax" (a BertTask
  training checkpoint directory via storage_uri)
- ``max_seq``: truncation length (default: the preset's max_seq)
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main

logger = logging.getLogger(__name__)


def _bucket(n: int, max_seq: int) -> int:
    b = 8
    while b < n and b < max_seq:
        b *= 2
    return min(b, max_seq)


class JaxEmbedModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self.tokenizer = None
        self._embed = None      # jitted (params, tokens, mask) -> [B, D]
        self._params = None
        self.dim = 0
        self.max_seq = 0

    def load(self) -> None:
        import dataclasses

        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models.bert import PRESETS, Bert
        from kubeflow_tpu.serving.runtimes.jax_llm_server import (
            ByteTokenizer,
            HFTokenizer,
        )

        opts = self.options
        tok = opts.get("tokenizer", "byte")
        self.tokenizer = ByteTokenizer() if tok == "byte" else HFTokenizer(tok)
        preset = opts.get("preset", "bert-base")
        if preset not in PRESETS:
            raise InferenceError(
                f"unknown bert preset {preset!r}; have {sorted(PRESETS)}",
                500,
            )
        cfg = dataclasses.replace(PRESETS[preset], remat=False)
        if opts.get("max_seq"):
            cfg = dataclasses.replace(cfg, max_seq=int(opts["max_seq"]))
        self.max_seq = cfg.max_seq
        self.dim = cfg.hidden
        pooling = opts.get("pooling", "mean")
        if pooling not in ("mean", "cls"):
            raise InferenceError(
                f"pooling={pooling!r}: supported values are mean/cls", 500,
            )
        normalize = bool(opts.get("normalize", True))
        model = Bert(cfg)
        ckpt = opts.get("checkpoint", "none" if not self.path else "orbax")
        if ckpt not in ("none", "orbax"):
            # A typo must not silently serve random-init vectors.
            raise InferenceError(
                f"checkpoint={ckpt!r}: supported values are none/orbax",
                500,
            )
        if ckpt == "orbax":
            if not self.path:
                raise InferenceError(
                    "checkpoint=orbax requires storage_uri", 500
                )
            self._params = _restore_bert_params(self.path, model)
        else:
            import flax.linen as nn

            raw = jax.jit(model.init)(
                jax.random.PRNGKey(int(opts.get("seed", 0))),
                jnp.zeros((1, 8), jnp.int32),
            )
            self._params = nn.meta.unbox(raw)

        def embed_fn(params, tokens, mask):
            h = model.apply(params, tokens, None, True, mask)  # [B,S,H]
            h = h.astype(jnp.float32)
            if pooling == "cls":
                v = h[:, 0]
            else:
                m = mask[..., None].astype(jnp.float32)
                v = (h * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
            if normalize:
                v = v / jnp.maximum(
                    jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-9
                )
            return v

        self._embed = jax.jit(embed_fn)
        # Warm the smallest bucket so first-request latency is serving
        # time, not compile time.
        import numpy as np

        self._embed(
            self._params, np.zeros((1, 8), np.int32),
            np.ones((1, 8), bool),
        )
        self.ready = True

    def unload(self) -> None:
        self._embed = None
        self._params = None
        self.ready = False

    def _ids(self, inst: Any) -> List[int]:
        if isinstance(inst, dict):
            inst = inst.get("text", inst.get("token_ids"))
        if isinstance(inst, str):
            ids = self.tokenizer.encode(inst)
        elif isinstance(inst, (list, tuple)):
            ids = [int(t) for t in inst]
        else:
            raise InferenceError(
                "embedding instances are strings, token-id lists, or "
                '{"text"| "token_ids"} dicts', 400,
            )
        if not ids:
            raise InferenceError("empty embedding input", 400)
        return ids[: self.max_seq]

    # Device-batch row cap: OpenAI clients legitimately send thousands
    # of inputs in one request; an unchunked [next_pow2(N), S] batch
    # would OOM or trigger a fresh compile per batch bucket. 64 rows of
    # max_seq tokens is well inside one chip's activation budget.
    MAX_ROWS = 64

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        import numpy as np

        seqs = [self._ids(i) for i in instances]
        out: List[Any] = []
        for lo in range(0, len(seqs), self.MAX_ROWS):
            chunk = seqs[lo:lo + self.MAX_ROWS]
            # One padded batch per chunk, bucketed both ways: compile
            # count stays O(#len-buckets x #batch-buckets <= 7x7).
            s = _bucket(max(len(x) for x in chunk), self.max_seq)
            b = 1
            while b < len(chunk):
                b *= 2
            tokens = np.zeros((b, s), np.int32)
            mask = np.zeros((b, s), bool)
            for i, ids in enumerate(chunk):
                tokens[i, : len(ids)] = ids
                mask[i, : len(ids)] = True
            vecs = np.asarray(self._embed(self._params, tokens, mask))
            out.extend(vecs[i].tolist() for i in range(len(chunk)))
        return out


def _restore_bert_params(path: str, model) -> dict:
    """Latest-step params from a BertTask training checkpoint directory
    (runtime/checkpoint.py layout: orbax CheckpointManager over a state
    dict carrying "params")."""
    import jax
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    mgr = ocp.CheckpointManager(path)
    step = mgr.latest_step()
    if step is None:
        raise InferenceError(f"no checkpoint steps under {path}", 500)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    )
    import flax.linen as nn

    abstract = nn.meta.unbox(abstract)
    restored = mgr.restore(
        step,
        args=ocp.args.StandardRestore({"params": abstract["params"]}),
    )
    return {"params": restored["params"]}


def main(argv=None) -> int:
    return serve_main(JaxEmbedModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
