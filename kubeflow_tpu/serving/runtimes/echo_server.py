"""Echo runtime: protocol-conformance and controller-test runtime.

Plays the role of the reference's "custom predictor" example images in
e2e tests -- a trivially fast model so tests exercise the serving path
(storage init, readiness, V1/V2, batching, scaling) without model weights.
Options: ``delay_ms`` (sleep per batch, for autoscale tests), ``fail``
(predict raises, for failure-path tests), ``stream_tokens`` +
``token_delay_ms`` (deterministic SSE token stream, for the activator's
stream-resume chaos tests).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main


class EchoModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self.batch_sizes: List[int] = []  # inspected by in-process tests

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        if self.options.get("fail"):
            raise InferenceError("echo runtime configured to fail", 500)
        delay = float(self.options.get("delay_ms", 0)) / 1000.0
        if delay:
            time.sleep(delay)
        self.batch_sizes.append(len(instances))
        out = [{"echo": i, "model_path": self.path} for i in instances]
        if "tag" in self.options:
            # Revision marker for canary-rollout tests: identifies which
            # spec generation served the request.
            for o in out:
                o["tag"] = self.options["tag"]
        return out

    def submit_stream(self, instance: Any, on_token) -> tuple:
        """Deterministic token stream: ids 0..stream_tokens-1, one per
        token_delay_ms. Two replicas produce byte-identical streams, so
        a resumed stream must concatenate seamlessly -- the property the
        activator's resume-by-offset chaos e2e asserts."""
        n = int(self.options.get("stream_tokens", 0))
        if n <= 0:
            raise InferenceError(
                f"model {self.name} does not support streaming "
                "generation", 501)
        delay = float(self.options.get("token_delay_ms", 0)) / 1000.0
        fut: Future = Future()

        def run() -> None:
            ids: List[int] = []
            for i in range(n):
                if delay:
                    time.sleep(delay)
                ids.append(i)
                on_token(i)
            fut.set_result(ids)

        threading.Thread(target=run, daemon=True).start()
        return fut, lambda ids: "".join(f"<{t}>" for t in ids)


def main(argv=None) -> int:
    return serve_main(EchoModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
