"""Echo runtime: protocol-conformance and controller-test runtime.

Plays the role of the reference's "custom predictor" example images in
e2e tests -- a trivially fast model so tests exercise the serving path
(storage init, readiness, V1/V2, batching, scaling) without model weights.
Options: ``delay_ms`` (sleep per batch, for autoscale tests), ``fail``
(predict raises, for failure-path tests).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main


class EchoModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self.batch_sizes: List[int] = []  # inspected by in-process tests

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        if self.options.get("fail"):
            raise InferenceError("echo runtime configured to fail", 500)
        delay = float(self.options.get("delay_ms", 0)) / 1000.0
        if delay:
            time.sleep(delay)
        self.batch_sizes.append(len(instances))
        out = [{"echo": i, "model_path": self.path} for i in instances]
        if "tag" in self.options:
            # Revision marker for canary-rollout tests: identifies which
            # spec generation served the request.
            for o in out:
                o["tag"] = self.options["tag"]
        return out


def main(argv=None) -> int:
    return serve_main(EchoModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
