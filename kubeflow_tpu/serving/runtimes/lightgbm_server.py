"""lightgbm runtime (KServe lgbserver equivalent, SURVEY.md 3.3 S5).

Loads a LightGBM Booster from a ``.txt``/``.model`` file and serves
predictions. Like the xgboost runtime, the library is an OPTIONAL
dependency here: an absent library fails at LOAD time with an
actionable message rather than crashing the process at import.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main

_SUFFIXES = (".txt", ".model", ".lgb")


class LightGBMModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self._booster = None

    def load(self) -> None:
        try:
            import lightgbm  # noqa: PLC0415 - optional dependency
        except ImportError:
            raise InferenceError(
                "the lightgbm library is not installed in this image; "
                "install it or serve the model via format=sklearn "
                "(joblib-wrapped LGBM estimators work there)", 500,
            )
        path = self.path
        if path is None:
            raise InferenceError("lightgbm runtime requires storage_uri", 500)
        if os.path.isdir(path):
            cands = [f for f in sorted(os.listdir(path))
                     if f.endswith(_SUFFIXES)]
            if not cands:
                raise InferenceError(f"no {_SUFFIXES} file in {path}", 500)
            path = os.path.join(path, cands[0])
        self._booster = lightgbm.Booster(model_file=path)
        self.ready = True

    def unload(self) -> None:
        self._booster = None
        self.ready = False

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        return np.asarray(
            self._booster.predict(np.asarray(instances))
        ).tolist()


def main(argv=None) -> int:
    return serve_main(LightGBMModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
