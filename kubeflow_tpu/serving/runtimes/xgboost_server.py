"""xgboost runtime (KServe xgbserver equivalent, SURVEY.md 3.3 S5).

Loads an xgboost Booster from a ``.json``/``.ubj``/``.bst`` model file
and serves predictions. The library is an OPTIONAL dependency in this
image (the runtime registry must cover the reference's format catalog;
an absent library fails at LOAD time with an actionable message, not an
import crash at process start — the same gating the HF runtime uses for
missing model assets).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main

_SUFFIXES = (".json", ".ubj", ".bst", ".model")


class XGBoostModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self._booster = None
        self._xgb = None

    def load(self) -> None:
        try:
            import xgboost  # noqa: PLC0415 - optional dependency
        except ImportError:
            raise InferenceError(
                "the xgboost library is not installed in this image; "
                "install it or serve the model via format=sklearn "
                "(joblib-wrapped XGB estimators work there)", 500,
            )
        path = self.path
        if path is None:
            raise InferenceError("xgboost runtime requires storage_uri", 500)
        if os.path.isdir(path):
            cands = [f for f in sorted(os.listdir(path))
                     if f.endswith(_SUFFIXES)]
            if not cands:
                raise InferenceError(f"no {_SUFFIXES} file in {path}", 500)
            path = os.path.join(path, cands[0])
        booster = xgboost.Booster()
        booster.load_model(path)
        self._booster = booster
        self._xgb = xgboost
        self.ready = True

    def unload(self) -> None:
        self._booster = None
        self.ready = False

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        dmat = self._xgb.DMatrix(np.asarray(instances))
        return np.asarray(self._booster.predict(dmat)).tolist()


def main(argv=None) -> int:
    return serve_main(XGBoostModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
