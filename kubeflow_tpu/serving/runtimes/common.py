"""Shared runtime-process scaffolding.

The flag contract between the ISVC controller (which spawns replica
processes) and every bundled runtime. Mirrors the reference's
ServingRuntime container contract (args: --model_name --model_dir
--http_port; storage-initializer as initContainer) collapsed into one
process: initialize storage, construct the model, load, serve.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from typing import Any, Callable, Dict, Optional

from kubeflow_tpu.obs import trace
from kubeflow_tpu.serving.model import Model, ModelRepository
from kubeflow_tpu.serving.server import ModelServer
from kubeflow_tpu.serving.storage import model_path

ModelFactory = Callable[[str, Optional[str], Dict[str, Any]], Model]


def serve_main(factory: ModelFactory, argv=None) -> int:
    """Run one runtime process: flags -> storage init -> load -> serve.

    ``factory(model_name, local_model_path, options) -> Model``.
    """

    p = argparse.ArgumentParser("kftpu model runtime")
    p.add_argument("--model-name", default=None)
    p.add_argument("--storage-uri", default=None)
    p.add_argument("--multi-model", action="store_true",
                   help="ModelMesh mode: boot empty; models are admitted "
                        "via the V2 repository API with per-model specs")
    p.add_argument("--max-loaded", type=int, default=4,
                   help="multi-model LRU budget per replica")
    p.add_argument("--model-dir", default=None,
                   help="where storage is materialized (default: ./models)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=int(os.environ.get("PORT", "8080")))
    p.add_argument("--grpc-port", type=int,
                   default=int(os.environ.get("GRPC_PORT", "0")),
                   help="serve the Open Inference Protocol over gRPC on "
                        "this port too (0 = HTTP only)")
    p.add_argument("--options-json", default="{}",
                   help="format-specific options (ModelSpec.options)")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--max-latency-ms", type=float, default=5.0)
    p.add_argument("--logger-json", default=None,
                   help='payload logger config: {"sink": ..., "mode": ...}')
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    # Debugging aid: `kill -USR1 <replica pid>` dumps every thread's
    # stack to stderr (the replica's log file) — invaluable for a
    # wedged-handler diagnosis without py-spy in the image.
    import faulthandler
    import signal as _signal

    faulthandler.register(_signal.SIGUSR1)

    # Adopt the controller's trace context (KFTPU_TRACE_*) so replica
    # spans land in the same distributed trace as reconcile/spawn.
    trace.activate_from_env(
        plane="serving", label=args.model_name or "multi-model"
    )

    options = json.loads(args.options_json)
    model_dir = args.model_dir or os.path.abspath("./models")

    if args.multi_model:
        # ModelMesh mode (S7): no fixed model; the repository constructs
        # models on demand from per-load specs, resolving each model's
        # storage under its own subdirectory.
        def dyn_factory(name: str, storage_uri, opts) -> Model:
            local = model_path(storage_uri, os.path.join(model_dir, name))
            return factory(name, local, opts)

        repo = ModelRepository(
            factory=dyn_factory, max_loaded=args.max_loaded,
            max_batch=args.max_batch, max_latency_ms=args.max_latency_ms,
        )
        path = None
    else:
        if not args.model_name:
            p.error("--model-name is required (or pass --multi-model)")
        path = model_path(args.storage_uri, model_dir)
        model = factory(args.model_name, path, options)
        repo = ModelRepository()
        repo.register(model, max_batch=args.max_batch,
                      max_latency_ms=args.max_latency_ms)
        model.load()

    from kubeflow_tpu.serving import payload_logger

    server = ModelServer(
        repository=repo,
        payload_logger=payload_logger.from_json(args.logger_json),
        grpc_port=args.grpc_port,
        grpc_host=args.host,
    )
    logging.getLogger(__name__).info(
        "serving %s on %s:%d (model path %s)",
        args.model_name, args.host, args.port, path,
    )
    server.run(host=args.host, port=args.port)
    # Graceful shutdown: leave this replica's spans where `kftpu trace
    # dump` merges them (live fetches go through GET /debug/trace).
    trace.write_process_trace()
    return 0
