"""JAX/PJRT LLM serving runtime (north-star config #5, SURVEY.md 3.3 S5 delta).

The TPU replacement for the reference's huggingfaceserver+vLLM GPU path:
orbax/msgpack checkpoint -> GenerationEngine (jitted prefill/decode,
continuous batching) -> V1/V2 protocol.

Request shapes (V1 instances / V2 input rows):
- ``{"prompt": "...", "max_new_tokens": N, "temperature": T}`` -- text in,
  text out (requires a tokenizer).
- ``{"token_ids": [...], ...}`` -- pre-tokenized; returns token ids.

Options (ModelSpec.options):
- ``preset``: llama preset name (default llama-tiny), or "auto" to read
  the geometry from the checkpoint's kftpu_config.json (written by
  kubeflow_tpu.runtime.convert_hf)
- ``max_slots``: concurrent sequences in the KV cache (default 8)
- ``decode_block``: decode steps fused per device dispatch (default 8;
  1 = per-token dispatch for lowest streaming latency)
- ``prefill_chunk``: prompts longer than this prefill in chunks of this
  many tokens, interleaved with decode blocks, so one long admission
  never stalls active slots (default 0 = whole-prompt prefill)
- ``max_prefill_tokens``: padded-token budget for one batched prefill
  program (bounds the K x S^2 fp32 attention-score memory; overflow
  prefills next step). Default 8192.
- ``prefix_cache_mb``: device-memory budget (MiB) for exact-match
  prompt-prefix KV reuse (0 = off). Repeated system prompts / chat
  histories restore their shared prefix instead of re-prefilling.
- ``prefix_block``: prefix-cache hash-block granularity (default 128
  tokens; reuse lengths are multiples of this).
- ``max_seq``: override cache length
- ``tokenizer``: "byte" (default; ids = utf-8 bytes, self-contained) or a
  HF tokenizer name resolved from the local cache only (zero egress)
- ``checkpoint``: "orbax" (TrainState dir from the training runtime) or
  "none" (random init -- demo/e2e mode)
- ``tensor_parallel``: shard weights + KV cache over an N-device
  ``tensor`` mesh (config #5 targets v5e-4: tensor_parallel=4). N must
  divide n_heads/n_kv_heads/intermediate/vocab. Default 1.
- ``quantize``: "int8" for weight-only int8 serving (per-output-channel
  scales; halves weight HBM bytes and footprint, KV cache stays bf16).
  Default off. The reference's quantized-variant analog (vLLM int8).
- ``kv_quant``: "int8" for an int8 KV cache (per-position-per-head
  scales folded out of the attention matmuls; halves cache HBM reads
  and footprint -- the long-context lever). Composes with ``quantize``;
  the vLLM kv-cache-dtype analog. Default off.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main

logger = logging.getLogger(__name__)


class ByteTokenizer:
    """utf-8 bytes as token ids: zero-dependency, works with any vocab>=256.

    Not a language model tokenizer -- it exists so the serving path is fully
    exercisable (and benchable) without staged tokenizer assets.
    """

    eos_id: Optional[int] = None

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def chat_prompt(self, messages) -> Optional[str]:
        return None  # no template: server falls back to generic rendering


class HFTokenizer:
    def __init__(self, name_or_path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path, local_files_only=True)
        self.eos_id = self._tok.eos_token_id

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids))

    def chat_prompt(self, messages) -> Optional[str]:
        """The checkpoint's own chat template, when it has one --
        instruction-tuned models must see the prompt format they were
        trained on, not a generic role-prefixed rendering."""
        if not getattr(self._tok, "chat_template", None):
            return None
        return self._tok.apply_chat_template(
            list(messages), tokenize=False, add_generation_prompt=True
        )


def make_stop_fn(decode, stops: List[str]):
    """Engine-side stop predicate: scan the DECODED tail of the
    generation for any stop string, so the slot frees mid-block instead
    of running out the token budget. Only the tail is decoded -- a full
    decode per token would be O(n^2) over long generations. The window
    is 4 tokens per stop char + slack: byte-level tokenizers (and HF
    byte-fallback BPE) emit up to ~4 tokens per CJK/emoji char, so a
    1-token-per-char window would miss such stop strings entirely. Text
    trimming is the transport layer's job; the matched tokens stay in
    the result so ids and text agree."""
    tail = 4 * max(len(s) for s in stops) + 16

    def stop_fn(generated: List[int]) -> bool:
        text = decode(generated[-tail:])
        return any(s in text for s in stops)

    return stop_fn


def _stop_list(inst) -> List[str]:
    stop = inst.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    return [s for s in stop if isinstance(s, str) and s]


def load_params_from_checkpoint(path: str, cfg, mesh=None) -> dict:
    """Restore model params from a training checkpoint directory.

    Accepts either a raw orbax step dir or a job checkpoint dir (picks the
    latest step). With a mesh, first tries an abstract-target restore so
    every leaf lands SHARDED across the mesh directly from disk — at 8B
    on 16 GiB chips a single-device restore would OOM before the engine
    could reshard. Falls back to the generic restore for checkpoint
    layouts that don't match the model tree (e.g. full TrainState dirs).
    """

    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    mgr = ocp.CheckpointManager(path)
    step = mgr.latest_step()
    if step is None:
        raise InferenceError(f"no checkpoint steps under {path}", 500)
    restored = None
    if mesh is not None:
        try:
            restored = _restore_sharded(mgr, step, cfg, mesh)
        except Exception as e:  # noqa: BLE001 - layout mismatch: fall back
            logger.info(
                "sharded restore unavailable (%s: %s); generic restore",
                type(e).__name__, e,
            )
    if restored is None:
        # Target-less StandardRestore: this orbax lineage cannot infer a
        # handler for the saved "default" item from a bare restore(step).
        restored = mgr.restore(step, args=ocp.args.StandardRestore())
    mgr.close()
    # Unwrap to the MODEL param tree: a TrainState checkpoint nests it as
    # state["params"]["params"] (TrainState.params holds the variables
    # dict), a raw variables checkpoint as ["params"]. Peel "params"
    # wrappers until the tree has model keys.
    tree = restored
    if hasattr(tree, "params"):
        tree = tree.params
    while (
        isinstance(tree, dict) and "params" in tree
        and "layers" not in tree and "embed" not in tree
    ):
        tree = tree["params"]
    if not (isinstance(tree, dict) and "layers" in tree):
        raise InferenceError(f"checkpoint at {path} has no params", 500)
    return {"params": _unbox(tree)}


def _unbox(tree):
    """Strip flax partitioning metadata the GENERIC orbax restore keeps:
    nn.with_logical_partitioning boxes every param, and a target-less
    restore returns each box as a dict like {"value": arr, ...} instead
    of the bare leaf (the sharded/abstract-target path never sees this
    -- its targets are unboxed)."""
    if isinstance(tree, dict):
        if "value" in tree and not isinstance(tree["value"], dict) and (
            set(tree) <= {"value", "names", "mesh", "rules", "unbox_fn"}
        ):
            return tree["value"]
        return {k: _unbox(v) for k, v in tree.items()}
    return tree


def _restore_sharded(mgr, step: int, cfg, mesh) -> dict:
    """Abstract-target restore: shape/dtype/sharding targets from the
    engine's shared abstract-param helper, so restore placements can
    never diverge from what the engine expects. Works for the
    ``{"params": ...}`` layout our converter and raw-variables
    checkpoints use; raises on structure mismatch (caller falls back)."""
    import jax
    import orbax.checkpoint as ocp

    from kubeflow_tpu.serving.engine import abstract_param_targets

    abstract, shardings, _ = abstract_param_targets(cfg, mesh)
    target = jax.tree.map(
        lambda leaf, sh: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=sh
        ),
        abstract, shardings,
    )
    return mgr.restore(step, args=ocp.args.StandardRestore(target))


class JaxLLMModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self.engine = None
        self.tokenizer = None
        self._json_mask_table = None  # built lazily (see _json_masks)
        self._prom = None  # per-model obs.registry.Registry (see prom_metrics)
        self._prom_engine = None  # engine the registry was built for

    def load(self) -> None:
        from kubeflow_tpu.serving.engine import GenerationEngine

        if self.engine is not None:
            # Repository re-load: release the old engine's HBM (weights +
            # KV cache) before building a new one (else both stay live).
            self.engine.close()
            self.engine = None
        opts = self.options
        tok = opts.get("tokenizer", "byte")
        self.tokenizer = ByteTokenizer() if tok == "byte" else HFTokenizer(tok)
        self._json_mask_table = None  # tokenizer changed: rebuild lazily

        params = None
        config = None
        ckpt_mode = opts.get("checkpoint", "orbax" if self.path else "none")
        preset = opts.get("preset", "llama-tiny")
        if preset == "auto" and ckpt_mode != "orbax":
            raise InferenceError(
                "preset=auto reads the geometry from a converted "
                "checkpoint; it requires checkpoint=orbax and a "
                "storage_uri", 500,
            )
        tp = int(opts.get("tensor_parallel", 1))
        mesh = None
        if tp > 1:
            from kubeflow_tpu.serving.engine import make_tp_mesh

            mesh = make_tp_mesh(tp)
        if ckpt_mode == "orbax":
            if not self.path:
                raise InferenceError("checkpoint=orbax requires storage_uri", 500)
            if preset == "auto":
                # Geometry from the converter's kftpu_config.json (written
                # by runtime.convert_hf next to the checkpoint).
                import json as _json

                cfg_path = os.path.join(self.path, "kftpu_config.json")
                if not os.path.exists(cfg_path):
                    raise InferenceError(
                        f"preset=auto needs {cfg_path} (written by "
                        "kubeflow_tpu.runtime.convert_hf)", 500,
                    )
                from kubeflow_tpu.models.llama import LlamaConfig

                with open(cfg_path) as f:
                    config = LlamaConfig(**_json.load(f))
            else:
                from kubeflow_tpu.models.llama import PRESETS

                config = PRESETS[preset]
            params = load_params_from_checkpoint(self.path, config, mesh)
        engine_kw = dict(
            params=params,
            max_slots=int(opts.get("max_slots", 8)),
            max_seq=opts.get("max_seq"),
            decode_block=int(opts.get("decode_block", 8)),
            prefill_chunk=int(opts.get("prefill_chunk", 0)),
            max_prefill_tokens=int(opts.get("max_prefill_tokens", 8192)),
            prefix_cache_mb=int(opts.get("prefix_cache_mb", 0)),
            prefix_block=int(opts.get("prefix_block", 128)),
            prefill_decode_steps=opts.get("prefill_decode_steps"),
            speculative_k=int(opts.get("speculative_k", 0)),
            decode_attn_kernel=bool(opts.get("decode_attn_kernel", False)),
            quantize=opts.get("quantize") or None,
            kv_quant=opts.get("kv_quant") or None,
            # Overlapped decode dispatch (docs/SERVING.md): 0 restores
            # the fully sequential dispatch-sync-consume loop; N >= 2
            # queues deeper lane deques with drain_overshoot_bound
            # capping per-drain discarded tokens.
            pipeline_depth=int(opts.get("pipeline_depth", 1)),
            drain_overshoot_bound=opts.get("drain_overshoot_bound"),
            mesh=mesh,
        )
        if config is not None:
            self.engine = GenerationEngine(config=config, **engine_kw)
        else:
            self.engine = GenerationEngine(preset=preset, **engine_kw)
        # Warm prefill + the full-size decode block (the only block the
        # steady state uses; smaller ones appear only near cache
        # exhaustion) so first request latency is serving-time, not
        # compile-time (SURVEY.md 7.4 #5).
        self.engine.generate(
            [1, 2, 3],
            max_new_tokens=max(2, self.engine.decode_block + 1),
        )
        self.engine.start()
        self.ready = True

    def unload(self) -> None:
        if self.engine is not None:
            self.engine.close()  # eviction must free HBM, not just the thread
            self.engine = None
        self.ready = False

    def _parse_instance(self, inst: Any):
        """Normalize one request instance -> (token_ids, text_out) or an
        error dict (shared by predict and the streaming path)."""
        if not isinstance(inst, dict):
            inst = {"prompt": str(inst)}
        if "token_ids" in inst:
            ids, text_out = list(inst["token_ids"]), False
        elif "prompt" in inst:
            ids, text_out = self.tokenizer.encode(inst["prompt"]), True
        else:
            return {"error": 'instance needs "prompt" or "token_ids"'}, inst
        if not ids:
            return {"error": "empty prompt"}, inst
        return (ids, text_out), inst

    def count_tokens(self, text: str) -> int:
        return len(self.tokenizer.encode(text))

    def render_chat(self, messages) -> Optional[str]:
        return self.tokenizer.chat_prompt(messages)

    def metadata(self) -> dict:
        """V2 model metadata plus a live ``engine`` gauges section, so
        GET /v2/models/{m} answers "is the pipeline actually hiding the
        host gap" without a Prometheus scrape. The extra key is legal
        V2 (unknown fields are ignored) and the gRPC ModelMetadata
        mapper simply drops it."""
        out = super().metadata()
        if self.engine is not None:
            out["engine"] = self.engine_gauges()
        return out

    def engine_gauges(self) -> dict:
        """Cheap pipeline gauges (plain attribute reads -- safe on the
        per-request path, unlike full stats() which walks containers)."""
        eng = self.engine
        gap = eng.host_gap_ms_ema
        return {
            # Router load signals (docs/FLEET.md): queue pressure and
            # the live TTFT EMA, mirrored into /healthz by the server
            # so the activator's load poll is one cheap GET.
            "queue_depth": eng.pending.qsize() + len(eng._backlog),
            "slots_active": len(eng.active),
            "max_slots": eng.max_slots,
            "ttft_ema_ms": (
                round(eng.ttft_ms_ema, 3)
                if eng.ttft_ms_ema is not None else 0.0
            ),
            # Configured depth vs the LIVE queued-lane count: inflight
            # == depth means the pipeline is saturated; 0 at depth > 0
            # means it is draining (admissions/constraints/spec).
            "dispatch_depth": eng.pipeline_depth,
            "dispatch_inflight": len(eng._inflight),
            "decode_dispatches": eng.decode_dispatches,
            # Free slots IF this engine admits prompts chunk-at-a-time
            # inside decode blocks (continuous chunked prefill), else 0.
            # The router's long-prompt steering keys off this: a replica
            # with chunk headroom absorbs a long prompt without stalling
            # its decode lanes, so steering away is pure affinity loss.
            "chunk_headroom": (
                len(eng.free_slots)
                if (eng.prefill_chunk and eng.continuous) else 0
            ),
            "host_gap_ms_ema": round(gap, 3) if gap is not None else 0.0,
            "overshoot_tokens_discarded": eng.overshoot_tokens_discarded,
            "overshoot_max_per_drain": eng.overshoot_max_per_drain,
        }

    def prom_metrics(self) -> List[str]:
        """Engine observability (SURVEY.md 5.5): scheduler gauges +
        TTFT/ITL histograms, per model -- every line rendered through
        the shared obs.registry formatter, so label escaping (a
        dynamically admitted model name with a quote/backslash/newline
        must not corrupt the whole scrape) lives in exactly one place.
        ``*_total`` lines are engine-owned monotone counters exposed by
        value; the per-model registry is rebuilt when the engine is
        (re)loaded so a fresh engine never inherits stale series."""
        if self.engine is None:
            return []
        from kubeflow_tpu.obs import registry as obs_registry

        if self._prom is None or self._prom_engine is not self.engine:
            self._prom = obs_registry.Registry()
            self._prom_engine = self.engine
        reg = self._prom
        lab = {"model": self.name}
        s = self.engine.stats()
        for key, stat in (
            ("kftpu_engine_queue_depth", "queue_depth"),
            ("kftpu_engine_slots_active", "slots_active"),
            ("kftpu_engine_slots_prefilling", "slots_prefilling"),
            ("kftpu_engine_max_slots", "max_slots"),
            ("kftpu_engine_prefill_backlog_tokens",
             "prefill_backlog_tokens"),
            ("kftpu_engine_tokens_generated_total", "tokens_generated"),
            ("kftpu_engine_requests_finished_total", "requests_finished"),
            # Dispatch-pipeline gauges: configured depth + live queued
            # lanes, EMA of the host bubble between a block landing and
            # the next dispatch (~0 when overlapped), tokens decoded
            # past accepted streams (EOS/budget overshoot -- discarded
            # by design), and the worst per-drain queued-lane discard.
            ("kftpu_engine_dispatch_depth", "dispatch_depth"),
            ("kftpu_engine_dispatch_inflight", "dispatch_inflight"),
            ("kftpu_engine_decode_dispatches_total", "decode_dispatches"),
            ("kftpu_engine_host_gap_ms", "host_gap_ms_ema"),
            ("kftpu_engine_overshoot_tokens_total",
             "overshoot_tokens_discarded"),
            ("kftpu_engine_overshoot_max_per_drain",
             "overshoot_max_per_drain"),
            # Live TTFT EMA (ms): the per-replica routing signal
            # (docs/FLEET.md) -- the histogram gives the distribution,
            # this gives the router's one current number.
            ("kftpu_engine_ttft_ema_ms", "ttft_ema_ms"),
            # Continuous chunked prefill: prompts activated mid-decode
            # (chunked admissions that never stalled the batch) and the
            # live chunk headroom the router's long-prompt steering
            # reads (0 when continuous batching is off).
            ("kftpu_engine_prefill_activations_total",
             "prefill_activations"),
            ("kftpu_engine_chunk_headroom", "chunk_headroom"),
        ):
            reg.gauge(key, lab).set(s[stat])
        if "weight_bytes" in s:
            # Present only when quantized (the int8-footprint gauge; the
            # quantize mode itself rides the label).
            reg.gauge(
                "kftpu_engine_weight_bytes",
                {"model": self.name, "quantize": s["quantize"]},
            ).set(s["weight_bytes"])
        if "kv_cache_bytes" in s:
            reg.gauge(
                "kftpu_engine_kv_cache_bytes",
                {"model": self.name, "kv_quant": s["kv_quant"]},
            ).set(s["kv_cache_bytes"])
        sp = s.get("spec")
        if sp is not None:
            reg.gauge("kftpu_engine_spec_steps_total", lab).set(sp["steps"])
            reg.gauge("kftpu_engine_spec_tokens_total",
                      lab).set(sp["emitted"])
            reg.gauge("kftpu_engine_spec_acceptance",
                      lab).set(sp["acceptance"])
            # Info-style gauge: which drafter is live (trained draft
            # model vs n-gram fallback) rides the label, value is 1.
            reg.gauge("kftpu_engine_spec_drafter_info",
                      {"model": self.name,
                       "drafter": sp["drafter"]}).set(1)
        pc = s.get("prefix_cache")
        if pc is not None:
            reg.gauge("kftpu_engine_prefix_cache_entries",
                      lab).set(pc["entries"])
            reg.gauge("kftpu_engine_prefix_cache_bytes",
                      lab).set(pc["bytes"])
            reg.gauge("kftpu_engine_prefix_cache_hits_total",
                      lab).set(pc["hits"])
            reg.gauge("kftpu_engine_prefix_cache_misses_total",
                      lab).set(pc["misses"])
        # Engine-owned histograms join the same exposition walk
        # (register is keyed, so re-registering each scrape is a no-op).
        for hist, hname in (
            (self.engine.ttft_hist, "kftpu_engine_ttft_seconds"),
            (self.engine.itl_hist, "kftpu_engine_itl_seconds"),
        ):
            hist.name, hist.labels = hname, lab
            reg.register(hist)
        return reg.expose()

    def export_prefix_packet(self, prompt: Optional[str] = None,
                             token_ids: Optional[List[int]] = None,
                             ensure: bool = True) -> Optional[bytes]:
        """Prefill-replica half of the disaggregated handoff
        (docs/FLEET.md): prefill the prompt into the prefix cache (when
        ``ensure``) and serialize the covered entry through the
        router wire format. None when nothing is coverable (prompt
        under one prefix block)."""
        from kubeflow_tpu.serving import router as _router

        if self.engine is None or self.engine.prefix_cache is None:
            raise InferenceError(
                "disaggregated handoff needs prefix_cache_mb > 0", 409
            )
        ids = list(token_ids) if token_ids else self.tokenizer.encode(
            prompt or ""
        )
        if not ids:
            raise InferenceError("empty prompt", 400)
        if ensure:
            self.engine.ensure_prefix(ids)
        pkt = self.engine.export_prefix(ids)
        if pkt is None:
            return None
        return _router.pack_kv_packet(
            pkt["tokens"], pkt["k"], pkt["v"],
            block=self.engine.prefix_cache.block,
        )

    def import_prefix_packet(self, buf: bytes) -> int:
        """Decode-replica half: adopt a packed KV prefix so the next
        request sharing it restores instead of prefilling."""
        from kubeflow_tpu.serving import router as _router

        if self.engine is None or self.engine.prefix_cache is None:
            raise InferenceError(
                "disaggregated handoff needs prefix_cache_mb > 0", 409
            )
        try:
            return self.engine.import_prefix(_router.unpack_kv_packet(buf))
        except ValueError as e:
            raise InferenceError(f"bad KV packet: {e}", 400)

    def prefix_inventory(self, top_k: int = 0) -> List[dict]:
        """Hottest-first prefix-cache inventory for the migration
        planner (serving/kv_reshard.plan_prefix_migration); [] when
        the engine runs without a prefix cache."""
        if self.engine is None:
            return []
        return self.engine.prefix_inventory(int(top_k))

    def _json_masks(self):
        """Token-mask table for json_object constrained decoding, built
        once per model from the live tokenizer (byte or BPE) and shared
        across requests (serving/jsonmode.py caches per-state masks)."""
        if self._json_mask_table is None:
            from kubeflow_tpu.serving import jsonmode

            vocab_size = self.engine.cfg.vocab_size
            if isinstance(self.tokenizer, ByteTokenizer):
                vocab = jsonmode.byte_vocab(vocab_size)
            else:
                vocab = jsonmode.tokenizer_vocab_strings(
                    self.tokenizer, vocab_size)
            self._json_mask_table = jsonmode.JsonTokenMasks(
                vocab, vocab_size)
        return self._json_mask_table

    def _build_request(self, inst: dict, ids: List[int], on_token=None):
        from kubeflow_tpu.serving.engine import Request
        from kubeflow_tpu.serving.jsonmode import JsonConstraint

        constraint = None
        rf = inst.get("response_format")
        if rf is not None:
            # Normalize here, not just at the OpenAI route: V1 predict
            # and V2 generate forward instances raw, and an unsupported
            # value must fail loudly, never silently produce free text.
            rtype = rf.get("type") if isinstance(rf, dict) else rf
            if rtype == "json_object":
                constraint = JsonConstraint(self._json_masks())
            elif rtype not in (None, "text"):
                raise InferenceError(
                    f"unsupported response_format {rtype!r} "
                    '(supported: "text", "json_object")', 400)
        stops = _stop_list(inst)
        return Request(
            prompt=ids,
            max_new_tokens=int(inst.get("max_new_tokens", 64)),
            temperature=float(inst.get("temperature", 0.0)),
            top_k=int(inst.get("top_k", 0)),
            top_p=float(inst.get("top_p", 1.0)),
            eos_id=inst.get("eos_id", self.tokenizer.eos_id),
            stop_fn=(make_stop_fn(self.tokenizer.decode, stops)
                     if stops else None),
            logprobs=int(inst.get("logprobs", 0) or 0),
            constraint=constraint,
            on_token=on_token,
        )

    def submit_stream(self, instance: Any, on_token) -> tuple:
        parsed, inst = self._parse_instance(instance)
        if isinstance(parsed, dict):
            raise InferenceError(parsed["error"], 400)
        ids, _ = parsed
        req = self._build_request(inst, ids, on_token)
        fut = self.engine.submit(req)
        fut.kftpu_request = req  # logprob records ride the future
        return fut, self.tokenizer.decode

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        # Per-instance errors become per-instance results: one malformed
        # instance must not fail (or orphan) the other requests the batcher
        # coalesced with it.
        slots: List[Any] = []  # (future, text_out) | {"error": ...}
        for inst in instances:
            parsed, inst = self._parse_instance(inst)
            if isinstance(parsed, dict):
                slots.append(parsed)
                continue
            ids, text_out = parsed
            try:
                req = self._build_request(inst, ids)
            except InferenceError as e:
                # Same per-instance contract as _parse_instance: one bad
                # knob (e.g. response_format) must not fail the batch.
                slots.append({"error": str(e)})
                continue
            slots.append((self.engine.submit(req), text_out))
        out = []
        for slot in slots:
            if isinstance(slot, dict):
                out.append(slot)
                continue
            fut, text_out = slot
            try:
                ids = fut.result(timeout=600)
            except ValueError as e:
                # Engine-side request validation (too long, etc.): a client
                # error for this one instance.
                out.append({"error": str(e)})
                continue
            except Exception as e:  # noqa: BLE001
                # Timeouts / dead scheduler are systemic: surface as 5xx so
                # health checks and retry layers see the failure.
                raise InferenceError(f"generation engine failure: {e}", 500)
            if text_out:
                out.append({"text": self.tokenizer.decode(ids),
                            "token_ids": ids})
            else:
                out.append({"token_ids": ids})
        return out


def main(argv=None) -> int:
    return serve_main(JaxLLMModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
