"""sklearn runtime (KServe sklearnserver equivalent, SURVEY.md 3.3 S5).

Loads a joblib/pickle-serialized estimator and serves ``predict`` (and
``predict_proba`` when the options ask for probabilities). Numeric work is
numpy on host -- sklearn models don't belong on the MXU; this runtime
exists for protocol parity and as the simple end of the S5 matrix.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main

_SUFFIXES = (".joblib", ".pkl", ".pickle")


class SKLearnModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self._model = None

    def load(self) -> None:
        import joblib

        path = self.path
        if path is None:
            raise InferenceError("sklearn runtime requires storage_uri", 500)
        if os.path.isdir(path):
            cands = [f for f in sorted(os.listdir(path)) if f.endswith(_SUFFIXES)]
            if not cands:
                raise InferenceError(f"no {_SUFFIXES} file in {path}", 500)
            path = os.path.join(path, cands[0])
        self._model = joblib.load(path)
        self.ready = True

    def unload(self) -> None:
        self._model = None
        self.ready = False

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        x = np.asarray(instances)
        if self.options.get("probabilities") and hasattr(self._model, "predict_proba"):
            return self._model.predict_proba(x).tolist()
        return np.asarray(self._model.predict(x)).tolist()


def main(argv=None) -> int:
    return serve_main(SKLearnModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
