"""HuggingFace transformers runtime (KServe huggingfaceserver equivalent,
SURVEY.md 3.3 S5).

Serves a local ``save_pretrained`` directory (storage_uri -> file path; no
network -- this environment is egress-gated and the reference's server
also prefers pre-staged models) behind the V1/V2 protocols:

- task=text-generation (default): AutoModelForCausalLM.generate. With a
  tokenizer in the model dir, instances are prompts (str or
  {"text", "max_new_tokens"}) and predictions are strings; without one
  (tokenizer=none), instances are token-id lists and predictions are
  token-id lists -- the hermetic mode tests use.
- task=text-classification: AutoModelForSequenceClassification; returns
  {label, score}.
- task=embedding: AutoModel; mean-pool of last_hidden_state (sequences
  run one at a time, unpadded, truncated to the model's position
  table), L2-normalized (options.normalize=false disables); returns one
  vector per instance -- wire it behind /openai/v1/embeddings or V1
  predict. The TPU-native counterpart is format=jax-embed
  (jax_embed_server), which batches with a padding mask.

Torch runs CPU-side here; the TPU-native LLM path is the ``jax`` format
(serving.engine) -- this runtime exists for HF-ecosystem parity, e.g.
serving a model family the JAX engine does not implement yet.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main


class HuggingFaceModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self.task = options.get("task", "text-generation")
        self.max_new_tokens = int(options.get("max_new_tokens", 32))
        self._model = None
        self._tokenizer = None

    def load(self) -> None:
        if self.path is None:
            raise InferenceError(
                "huggingface runtime requires storage_uri pointing at a "
                "save_pretrained directory", 500,
            )
        import torch  # noqa: F401  -- fail early if torch is unavailable
        from transformers import (
            AutoModel,
            AutoModelForCausalLM,
            AutoModelForSequenceClassification,
            AutoTokenizer,
        )

        if self.task == "text-generation":
            cls = AutoModelForCausalLM
        elif self.task == "text-classification":
            cls = AutoModelForSequenceClassification
        elif self.task in ("embedding", "text_embedding"):
            cls = AutoModel
        else:
            raise InferenceError(f"unsupported task {self.task!r}", 500)
        self._model = cls.from_pretrained(self.path, local_files_only=True)
        self._model.eval()
        if str(self.options.get("tokenizer", "")) != "none":
            try:
                self._tokenizer = AutoTokenizer.from_pretrained(
                    self.path, local_files_only=True
                )
            except Exception as e:  # noqa: BLE001
                raise InferenceError(
                    f"no tokenizer in {self.path}; pass options.tokenizer="
                    f"'none' for token-id mode ({e})", 500,
                )
        self.ready = True

    def unload(self) -> None:
        self._model = None
        self._tokenizer = None
        self.ready = False

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        import torch

        if self.task == "text-classification":
            return [self._classify(i) for i in instances]
        if self.task in ("embedding", "text_embedding"):
            return [self._embed(i) for i in instances]
        out = []
        for inst in instances:
            max_new = self.max_new_tokens
            if isinstance(inst, dict):
                max_new = int(inst.get("max_new_tokens", max_new))
                inst = inst.get("text", inst.get("ids"))
            if self._tokenizer is not None:
                ids = self._tokenizer(inst, return_tensors="pt").input_ids
            else:
                if not isinstance(inst, (list, tuple)):
                    raise InferenceError(
                        "tokenizer-less mode takes token-id lists", 400
                    )
                ids = torch.tensor([list(inst)], dtype=torch.long)
            with torch.no_grad():
                gen = self._model.generate(
                    ids, max_new_tokens=max_new, do_sample=False,
                    pad_token_id=int(self.options.get("pad_token_id", 0)),
                )
            new = gen[0][ids.shape[1]:]
            if self._tokenizer is not None:
                out.append(self._tokenizer.decode(
                    new, skip_special_tokens=True
                ))
            else:
                out.append([int(t) for t in new])
        return out

    def _embed(self, inst: Any) -> list:
        import torch

        # Long documents are the canonical embeddings payload: truncate
        # to the checkpoint's position table instead of crashing on the
        # position-embedding lookup.
        max_len = int(self.options.get(
            "max_seq",
            getattr(self._model.config, "max_position_embeddings", 0)
            or 512,
        ))
        if isinstance(inst, dict):
            inst = inst.get("text", inst.get("token_ids"))
        if self._tokenizer is not None and isinstance(inst, str):
            ids = self._tokenizer(
                inst, return_tensors="pt", truncation=True,
                max_length=max_len,
            ).input_ids
        elif isinstance(inst, (list, tuple)):
            ids = torch.tensor([list(inst)[:max_len]], dtype=torch.long)
        else:
            raise InferenceError(
                "embedding instances are strings (with a tokenizer) or "
                "token-id lists", 400,
            )
        with torch.no_grad():
            h = self._model(ids).last_hidden_state[0]  # [S, H]
        v = h.mean(dim=0)
        if bool(self.options.get("normalize", True)):
            v = v / v.norm().clamp_min(1e-9)
        return [float(x) for x in v]

    def _classify(self, inst: Any) -> dict:
        import torch

        if self._tokenizer is None:
            ids = torch.tensor([list(inst)], dtype=torch.long)
        else:
            ids = self._tokenizer(inst, return_tensors="pt").input_ids
        with torch.no_grad():
            logits = self._model(ids).logits[0]
        probs = torch.softmax(logits, dim=-1)
        idx = int(torch.argmax(probs))
        labels = getattr(self._model.config, "id2label", {}) or {}
        return {"label": labels.get(idx, str(idx)), "score": float(probs[idx])}


def main(argv=None) -> int:
    return serve_main(HuggingFaceModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
