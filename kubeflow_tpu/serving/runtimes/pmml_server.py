"""PMML runtime (KServe pmmlserver equivalent, SURVEY.md 3.3 S5).

Loads a ``.pmml`` model via pypmml and serves predictions. pypmml is an
OPTIONAL dependency in this image (it needs a JVM); the runtime exists
for the reference's format-catalog parity and fails at LOAD time with an
actionable message when the library is absent — the same gating the
xgboost/lightgbm runtimes use.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main


class PMMLModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self._model = None

    def load(self) -> None:
        try:
            from pypmml import Model as PMML  # noqa: PLC0415 - optional
        except ImportError:
            raise InferenceError(
                "the pypmml library (and its JVM dependency) is not "
                "installed in this image; install pypmml to serve "
                "format=pmml, or export the model to sklearn/onnx and "
                "use another runtime", 500,
            )
        path = self.path
        if path is None:
            raise InferenceError("pmml runtime requires storage_uri", 500)
        if os.path.isdir(path):
            cands = [f for f in sorted(os.listdir(path))
                     if f.endswith((".pmml", ".xml"))]
            if not cands:
                raise InferenceError(f"no .pmml file in {path}", 500)
            path = os.path.join(path, cands[0])
        self._model = PMML.load(path)
        self.ready = True

    def unload(self) -> None:
        if self._model is not None:
            self._model.close()
        self._model = None
        self.ready = False

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        # pypmml takes records (dict) or positional lists per its input
        # field order.
        out = []
        for inst in instances:
            if isinstance(inst, dict):
                out.append(self._model.predict(inst))
            else:
                names = [f.name for f in self._model.inputFields]
                out.append(self._model.predict(dict(zip(names, inst))))
        return out


def main(argv=None) -> int:
    return serve_main(PMMLModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
