"""Bundled explainer runtime: serves ``:explain`` with the model-agnostic
feature-ablation explainer (serving.explainer.AblationExplainer). The ISVC
controller spawns this for explainer components that declare no custom
process (SURVEY.md 3.3 S1: the reference ISVC triple is
predictor/transformer/explainer)."""

from __future__ import annotations

from kubeflow_tpu.serving.explainer import AblationExplainer
from kubeflow_tpu.serving.runtimes.common import serve_main


def main(argv=None) -> int:
    return serve_main(
        lambda name, path, opts: AblationExplainer(name, path, opts), argv
    )


if __name__ == "__main__":
    raise SystemExit(main())
