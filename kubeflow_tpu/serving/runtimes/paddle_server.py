"""Paddle inference runtime (KServe paddleserver equivalent, SURVEY.md
3.3 S5).

Loads a Paddle inference model (``*.pdmodel`` + ``*.pdiparams``) and
serves predictions on host CPU. paddlepaddle is an OPTIONAL dependency in
this image; the runtime exists for the reference's format-catalog parity
and fails at LOAD time with an actionable message when the library is
absent — the same gating the xgboost/lightgbm/pmml runtimes use.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from kubeflow_tpu.serving.model import InferenceError, Model
from kubeflow_tpu.serving.runtimes.common import serve_main


class PaddleModel(Model):
    def __init__(self, name: str, path: Optional[str],
                 options: Dict[str, Any]) -> None:
        super().__init__(name)
        self.path = path
        self.options = options
        self._predictor = None

    def load(self) -> None:
        try:
            from paddle import inference  # noqa: PLC0415 - optional
        except ImportError:
            raise InferenceError(
                "the paddlepaddle library is not installed in this "
                "image; install paddlepaddle to serve format=paddle, or "
                "export the model to ONNX/sklearn and use another "
                "runtime", 500,
            )
        path = self.path
        if path is None:
            raise InferenceError("paddle runtime requires storage_uri", 500)
        model_file = params_file = None
        if os.path.isdir(path):
            for f in sorted(os.listdir(path)):
                if f.endswith(".pdmodel"):
                    model_file = os.path.join(path, f)
                elif f.endswith(".pdiparams"):
                    params_file = os.path.join(path, f)
        elif path.endswith(".pdmodel"):
            model_file = path
            params_file = path[: -len(".pdmodel")] + ".pdiparams"
        if not model_file or not params_file or not os.path.exists(params_file):
            raise InferenceError(
                f"paddle runtime needs a .pdmodel + .pdiparams pair "
                f"under {path}", 500,
            )
        config = inference.Config(model_file, params_file)
        config.disable_gpu()
        self._predictor = inference.create_predictor(config)
        self.ready = True

    def unload(self) -> None:
        self._predictor = None
        self.ready = False

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        pred = self._predictor
        batch = np.asarray(instances, dtype=np.float32)
        name = pred.get_input_names()[0]
        handle = pred.get_input_handle(name)
        handle.reshape(batch.shape)
        handle.copy_from_cpu(batch)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        return np.asarray(out.copy_to_cpu()).tolist()


def main(argv=None) -> int:
    return serve_main(PaddleModel, argv)


if __name__ == "__main__":
    raise SystemExit(main())
