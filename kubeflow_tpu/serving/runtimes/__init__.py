"""Bundled server runtimes (KServe-equivalent S5).

Each runtime is a ``python -m`` entrypoint the ISVC controller spawns as a
replica process, with a common flag contract (see ``common.serve_main``):
``--model-name --storage-uri --model-dir --port --options-json``.
"""
