"""Model-server library (KServe-equivalent, SURVEY.md 3.3 S4).

``Model`` is the user-facing base class with the reference's lifecycle
{load, preprocess, predict, postprocess}; ``ModelRepository`` holds served
models with dynamic load/unload (V2 repository API); ``Batcher`` coalesces
concurrent predict calls into one batched call (S6's batcher sidecar,
in-process here).

TPU-first notes: ``predict`` receives the *batched* input list so a JAX
runtime can run one jitted call per batch (static shapes + MXU-sized
batches beat per-request dispatch); the batcher's max_batch/max_latency
trade HBM-resident batch growth against tail latency.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional, Sequence


class InferenceError(RuntimeError):
    """Server-visible failure; mapped to HTTP 4xx/5xx by the server."""

    def __init__(self, message: str, status: int = 500) -> None:
        super().__init__(message)
        self.status = status


class Model:
    """One served model. Subclass and override the lifecycle hooks.

    ``predict`` takes a list of instances and returns a list of outputs of
    the same length -- the server batches; the model sees batches.
    """

    def __init__(self, name: str) -> None:
        self.name = name
        self.ready = False

    def load(self) -> None:
        """Read weights, build/jit the compute fn; set ``self.ready``."""

        self.ready = True

    def unload(self) -> None:
        self.ready = False

    def preprocess(self, payload: Any) -> Any:
        return payload

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        raise NotImplementedError

    def postprocess(self, outputs: Any) -> Any:
        return outputs

    # V2 metadata (optional override).
    def metadata(self) -> Dict[str, Any]:
        return {"name": self.name, "platform": "kftpu", "inputs": [], "outputs": []}


class Batcher:
    """Coalesce concurrent single-instance predicts into batched calls.

    Requests queue up; a worker drains up to ``max_batch`` instances or
    whatever arrived within ``max_latency_ms`` and issues one
    ``model.predict(batch)``. With max_batch=1 this degenerates to
    pass-through (still serialized, which is what a single-chip TPU wants).
    """

    def __init__(self, model: Model, max_batch: int = 32,
                 max_latency_ms: float = 5.0) -> None:
        self.model = model
        self.max_batch = max(1, max_batch)
        self.max_latency = max_latency_ms / 1000.0
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def predict(self, instance: Any) -> Any:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((instance, fut))
        return await fut

    async def _run(self) -> None:
        while True:
            batch = [await self._queue.get()]
            deadline = time.monotonic() + self.max_latency
            while len(batch) < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(self._queue.get(), timeout))
                except asyncio.TimeoutError:
                    break
            instances = [b[0] for b in batch]
            try:
                # predict is sync (jit dispatch); run in default executor so
                # the event loop keeps accepting requests during compute.
                outputs = await asyncio.get_running_loop().run_in_executor(
                    None, self.model.predict, instances
                )
                if len(outputs) != len(instances):
                    raise InferenceError(
                        f"model returned {len(outputs)} outputs for "
                        f"{len(instances)} instances"
                    )
                for (_, fut), out in zip(batch, outputs):
                    if not fut.done():
                        fut.set_result(out)
            except Exception as e:  # noqa: BLE001 - failures propagate per-request
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)


class ModelRepository:
    """Name -> Model registry with dynamic load/unload (V2 repository API)."""

    def __init__(self) -> None:
        self._models: Dict[str, Model] = {}
        self._batchers: Dict[str, Batcher] = {}

    def register(self, model: Model, max_batch: int = 32,
                 max_latency_ms: float = 5.0) -> None:
        self._models[model.name] = model
        self._batchers[model.name] = Batcher(model, max_batch, max_latency_ms)

    def get(self, name: str) -> Model:
        if name not in self._models:
            raise InferenceError(f"model {name} not found", status=404)
        return self._models[name]

    def batcher(self, name: str) -> Batcher:
        self.get(name)
        return self._batchers[name]

    def names(self) -> List[str]:
        return sorted(self._models)

    def load(self, name: str) -> None:
        self.get(name).load()

    def unload(self, name: str) -> None:
        m = self.get(name)
        m.unload()

    def start(self) -> None:
        for b in self._batchers.values():
            b.start()

    async def stop(self) -> None:
        for b in self._batchers.values():
            await b.stop()
