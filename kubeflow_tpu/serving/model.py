"""Model-server library (KServe-equivalent, SURVEY.md 3.3 S4).

``Model`` is the user-facing base class with the reference's lifecycle
{load, preprocess, predict, postprocess}; ``ModelRepository`` holds served
models with dynamic load/unload (V2 repository API); ``Batcher`` coalesces
concurrent predict calls into one batched call (S6's batcher sidecar,
in-process here).

TPU-first notes: ``predict`` receives the *batched* input list so a JAX
runtime can run one jitted call per batch (static shapes + MXU-sized
batches beat per-request dispatch); the batcher's max_batch/max_latency
trade HBM-resident batch growth against tail latency.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)
# KFTPU_SERVING_TRACE=1: log batcher/repository lifecycle + per-request
# stages (diagnosing wedged requests in multi-model replicas).
TRACE = os.environ.get("KFTPU_SERVING_TRACE") == "1"

# Predict batches run here rather than the loop's default executor so the
# CONCURRENT future is visible: eviction needs "has the worker thread
# really finished model.predict?" — the asyncio wrapper future gets
# cancelled with its task and can't answer that.
_PREDICT_POOL = concurrent.futures.ThreadPoolExecutor(
    # Same sizing as asyncio's default executor: a dense multi-model
    # replica must not serialize unrelated models' batches behind a
    # tiny thread cap.
    max_workers=min(32, (os.cpu_count() or 1) + 4),
    thread_name_prefix="kftpu-predict",
)


class InferenceError(RuntimeError):
    """Server-visible failure; mapped to HTTP 4xx/5xx by the server."""

    def __init__(self, message: str, status: int = 500) -> None:
        super().__init__(message)
        self.status = status


class Model:
    """One served model. Subclass and override the lifecycle hooks.

    ``predict`` takes a list of instances and returns a list of outputs of
    the same length -- the server batches; the model sees batches.
    """

    def __init__(self, name: str) -> None:
        self.name = name
        self.ready = False

    def load(self) -> None:
        """Read weights, build/jit the compute fn; set ``self.ready``."""

        self.ready = True

    def unload(self) -> None:
        self.ready = False

    def preprocess(self, payload: Any) -> Any:
        return payload

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        raise NotImplementedError

    def postprocess(self, outputs: Any) -> Any:
        return outputs

    # V2 metadata (optional override).
    def metadata(self) -> Dict[str, Any]:
        return {"name": self.name, "platform": "kftpu", "inputs": [], "outputs": []}

    # Token accounting for the OpenAI usage block. The base is an
    # honest approximation (characters); tokenizer-bearing models
    # override with a real count.
    def count_tokens(self, text: str) -> int:
        return len(text)

    # Explanation (V1 ``:explain``). Explainer components override
    # (serving.explainer.ExplainerModel); a model may also implement it
    # directly, as the reference's kserve.Model.explain hook allows.
    def explain(self, instances: Sequence[Any]) -> List[Any]:
        raise InferenceError(
            f"model {self.name} does not support explanation", 501
        )

    # Streaming generation (V2 generate extension). LLM runtimes override:
    # submit the request, arrange for ``on_token(token_id)`` to be called
    # per generated token (any thread), and return (future-of-token-ids,
    # decode) where ``decode(ids) -> str`` renders a cumulative text. The
    # server owns the SSE framing; models own only token production.
    # Runtimes that track per-request extras (logprobs) additionally set
    # ``fut.kftpu_request`` to the engine request.
    def submit_stream(self, instance: Any, on_token) -> tuple:
        raise InferenceError(
            f"model {self.name} does not support streaming generation", 501
        )

    # Chat rendering for the OpenAI chat surface: return the prompt text
    # for normalized [{"role", "content"}] messages, or None when the
    # model carries no chat template (the server then falls back to its
    # generic role-prefixed rendering). Tokenizer-bearing runtimes
    # override with the checkpoint's own template -- an instruction-tuned
    # model served through /openai/v1/chat/completions must see the
    # format it was trained on.
    def render_chat(self, messages) -> Optional[str]:
        return None

    # Prometheus exposition lines for /metrics (already formatted;
    # engine-bearing runtimes expose queue/slot/latency internals).
    def prom_metrics(self) -> List[str]:
        return []


class Batcher:
    """Coalesce concurrent single-instance predicts into batched calls.

    Requests queue up; a worker drains up to ``max_batch`` instances or
    whatever arrived within ``max_latency_ms`` and issues one
    ``model.predict(batch)``. With max_batch=1 this degenerates to
    pass-through (still serialized, which is what a single-chip TPU wants).
    """

    def __init__(self, model: Model, max_batch: int = 32,
                 max_latency_ms: float = 5.0) -> None:
        self.model = model
        self.max_batch = max(1, max_batch)
        self.max_latency = max_latency_ms / 1000.0
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        # Set by cancel(): the batcher is dead; predicts must fail fast
        # instead of enqueueing onto a queue nobody will ever drain.
        self._closed: Optional[Exception] = None
        # The CONCURRENT future of the batch currently computing, if
        # any: eviction must not unload the model under a running
        # predict, and only this future reports true thread completion.
        self.inflight: Optional[concurrent.futures.Future] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def predict(self, instance: Any) -> Any:
        if self._closed is not None:
            raise self._closed
        if self._task is None:
            # Not started: queueing would hang forever (nobody drains).
            raise InferenceError("batcher is not running", 503)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((instance, fut))
        if TRACE:
            logger.info("TRACE batcher %x enqueue model=%s task=%s "
                        "closed=%s", id(self), self.model.name,
                        self._task, self._closed)
        if self._closed is not None and not fut.done():
            # Evicted between the closed-check and the put: the drain in
            # cancel() ran before our entry landed — fail it ourselves.
            fut.set_exception(self._closed)
        return await fut

    def cancel(self, exc: Exception) -> None:
        """Tear down synchronously (eviction): stop the worker and fail
        queued requests instead of hanging their futures forever."""
        if TRACE:
            logger.info("TRACE batcher %x cancel model=%s qsize=%d",
                        id(self), self.model.name, self._queue.qsize())
        self._closed = exc
        if self._task is not None:
            self._task.cancel()
            self._task = None
        while True:
            try:
                _, fut = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if not fut.done():
                fut.set_exception(exc)

    async def _run(self) -> None:
        # ``batch`` lives OUTSIDE the loop and the cancellation handler
        # wraps the WHOLE loop: eviction can cancel this task at ANY
        # await — including the batching-window wait_for below, which is
        # where a cancel racing a just-popped request usually lands — and
        # every popped-but-unresolved future must be failed, never
        # abandoned (an abandoned future hangs its HTTP request forever).
        batch: List[Any] = []
        try:
            while True:
                batch = [await self._queue.get()]
                if TRACE:
                    logger.info("TRACE batcher %x popped model=%s",
                                id(self), self.model.name)
                # Batching window via non-blocking drain + micro-sleeps:
                # wait_for(queue.get(), t) can DISCARD a popped item when
                # cancellation races the inner get's completion (the
                # documented wait_for caveat) — that lost item's future
                # would hang its HTTP request forever. get_nowait never
                # holds an item across an await, so eviction-cancel at
                # any point leaves undrained items IN the queue for
                # cancel()'s drain to fail.
                deadline = time.monotonic() + self.max_latency
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self._queue.get_nowait())
                        continue
                    except asyncio.QueueEmpty:
                        pass
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    await asyncio.sleep(min(remaining, 0.001))
                instances = [b[0] for b in batch]
                try:
                    # predict is sync (jit dispatch); run in a thread so
                    # the event loop keeps accepting requests during
                    # compute.
                    self.inflight = _PREDICT_POOL.submit(
                        self.model.predict, instances
                    )
                    outputs = await asyncio.wrap_future(self.inflight)
                    if TRACE:
                        logger.info("TRACE batcher %x executor done n=%d",
                                    id(self), len(outputs))
                    if len(outputs) != len(instances):
                        raise InferenceError(
                            f"model returned {len(outputs)} outputs for "
                            f"{len(instances)} instances"
                        )
                    for (_, fut), out in zip(batch, outputs):
                        if not fut.done():
                            fut.set_result(out)
                except Exception as e:  # noqa: BLE001 - per-request failures
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(e)
                finally:
                    self.inflight = None
                batch = []
        except asyncio.CancelledError:
            if TRACE:
                logger.info("TRACE batcher %x cancelled (%d in-flight)",
                            id(self), len(batch))
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        InferenceError("model was unloaded", 503)
                    )
            raise


class ModelRepository:
    """Name -> Model registry with dynamic load/unload (V2 repository API).

    Multi-model mode (ModelMesh analog, S7): constructed with a
    ``factory(name, storage_uri, options) -> Model`` and a ``max_loaded``
    budget, the repository can ADMIT models it has never seen (the V2
    load route passes the model spec) and evicts the least-recently-used
    ready model when the budget is exceeded — high-density serving where
    many models share one replica process."""

    def __init__(self, factory=None, max_loaded: Optional[int] = None,
                 max_batch: int = 32, max_latency_ms: float = 5.0) -> None:
        self._models: Dict[str, Model] = {}
        self._batchers: Dict[str, Batcher] = {}
        self._factory = factory
        self._max_loaded = max_loaded
        # Batching defaults applied to dynamically admitted models.
        self._max_batch = max_batch
        self._max_latency_ms = max_latency_ms
        self._last_used: Dict[str, float] = {}
        self._started = False
        # Created lazily (needs a running loop): serializes dynamic
        # admissions.
        self._load_lock: Optional[asyncio.Lock] = None

    @property
    def multi_model(self) -> bool:
        return self._factory is not None

    def register(self, model: Model, max_batch: int = 32,
                 max_latency_ms: float = 5.0) -> None:
        self._models[model.name] = model
        b = Batcher(model, max_batch, max_latency_ms)
        self._batchers[model.name] = b
        if self._started:
            b.start()

    def get(self, name: str) -> Model:
        if name not in self._models:
            raise InferenceError(f"model {name} not found", status=404)
        return self._models[name]

    def batcher(self, name: str) -> Batcher:
        self.get(name)
        return self._batchers[name]

    def names(self) -> List[str]:
        return sorted(self._models)

    def touch(self, name: str) -> None:
        self._last_used[name] = time.monotonic()

    def load(self, name: str) -> None:
        self.get(name).load()
        self.touch(name)

    async def load_dynamic_async(self, name: str,
                                 storage_uri: Optional[str],
                                 options: Dict[str, Any]) -> None:
        """Admit-and-load a model by spec (multi-model replicas only).

        The HEAVY part (weight read + jit warmup) runs off the event
        loop: a multi-second model load must not freeze every other
        model's predicts and the replica's health probes. BUILD comes
        BEFORE any eviction: a failing load must cost nothing — not the
        old same-name instance (it keeps serving), and never an
        unrelated LRU victim. Admissions are serialized so concurrent
        loads can neither overshoot max_loaded nor double-register."""
        if self._factory is None:
            raise InferenceError(
                "this replica is not multi-model; models are fixed at "
                "spawn", status=409,
            )
        if self._load_lock is None:
            self._load_lock = asyncio.Lock()
        async with self._load_lock:
            loop = asyncio.get_running_loop()

            def build() -> Model:
                m = self._factory(name, storage_uri, options)
                m.load()
                return m

            model = await loop.run_in_executor(None, build)
            if name in self._models:
                # Re-admission: the old instance was built from an older
                # spec — replace it only now that the new one is ready.
                self.evict(name)
            if self._max_loaded is not None:
                loaded = [n for n, m in self._models.items() if m.ready]
                while len(loaded) >= self._max_loaded:
                    victim = min(
                        loaded, key=lambda n: self._last_used.get(n, 0.0)
                    )
                    self.evict(victim)
                    loaded.remove(victim)
            self.register(model, max_batch=self._max_batch,
                          max_latency_ms=self._max_latency_ms)
            self.touch(name)

    def unload(self, name: str) -> None:
        m = self.get(name)
        m.unload()

    def evict(self, name: str) -> None:
        """Unload AND deregister (multi-model LRU / model removal). The
        model's unload() is deferred past any predict batch currently
        computing in the executor — tearing an engine down under a
        running jit dispatch is unsafe."""
        m = self._models.pop(name, None)
        b = self._batchers.pop(name, None)
        self._last_used.pop(name, None)
        if b is not None:
            inflight = b.inflight
            b.cancel(InferenceError(f"model {name} was unloaded", 503))
            # inflight is the CONCURRENT future: it completes only when
            # the worker thread actually leaves model.predict (task
            # cancellation cannot cancel a running thread), so the
            # done-callback is a safe post-compute unload point.
            if (m is not None and inflight is not None
                    and not inflight.done()):
                inflight.add_done_callback(lambda _f: m.unload())
                return
        if m is not None:
            m.unload()

    def start(self) -> None:
        self._started = True
        for b in self._batchers.values():
            b.start()

    async def stop(self) -> None:
        for b in self._batchers.values():
            await b.stop()
