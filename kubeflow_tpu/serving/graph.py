"""InferenceGraph (KServe v1alpha1 InferenceGraph equivalent, SURVEY.md
3.3 S1).

A graph composes InferenceServices into one inference endpoint. Node
router types match the reference:

- ``Sequence``: steps run in order; each step's output ("predictions"
  payload) becomes the next step's instances (or ``data: $request``
  re-sends the original request).
- ``Switch``: the first step whose ``condition`` matches the request
  instance routes it (conditions are ``field=value`` checks on dict
  instances); a step with no condition is the default arm.
- ``Ensemble``: all steps run concurrently; the response maps step name
  -> predictions.
- ``Splitter``: one step is picked by ``weight`` (deterministic hash of
  the request, so identical requests route identically -- canary-style
  traffic splitting).

Steps reference InferenceServices by name (``service``) or other nodes
(``node``). Requests enter at the ``root`` node via
``POST /graphs/{ns}/{name}`` on the control plane; each service hop goes
through the activator, so scale-to-zero applies per service.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field

from kubeflow_tpu.api.types import ObjectMeta

GRAPH_KIND = "InferenceGraph"
ROUTER_TYPES = ("Sequence", "Switch", "Ensemble", "Splitter")


class GraphValidationError(ValueError):
    pass


class GraphStep(BaseModel):
    model_config = ConfigDict(extra="forbid")

    name: Optional[str] = None
    # Exactly one of: an InferenceService name or another node's name.
    service: Optional[str] = None
    node: Optional[str] = None
    # Switch arm: "field=value" matched against dict instances; absent =
    # default arm. Splitter: relative integer weight.
    condition: Optional[str] = None
    weight: Optional[int] = Field(default=None, ge=1)
    # "$request" re-sends the original request instead of the previous
    # step's output (Sequence only; KServe's data field).
    data: Optional[str] = None

    @property
    def label(self) -> str:
        return self.name or self.service or self.node or "step"


class GraphNode(BaseModel):
    model_config = ConfigDict(extra="forbid")

    router_type: str = "Sequence"
    steps: List[GraphStep] = Field(default_factory=list)


class InferenceGraphSpec(BaseModel):
    model_config = ConfigDict(extra="forbid")

    nodes: Dict[str, GraphNode]


class InferenceGraph(BaseModel):
    model_config = ConfigDict(extra="forbid")

    kind: str = GRAPH_KIND
    metadata: ObjectMeta
    spec: InferenceGraphSpec
    status: Dict[str, Any] = Field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "InferenceGraph":
        return cls.model_validate(d)

    def to_dict(self) -> dict:
        return self.model_dump(mode="json")


def validate_graph(g: InferenceGraph) -> None:
    nodes = g.spec.nodes
    if "root" not in nodes:
        raise GraphValidationError("graph needs a 'root' node")
    for name, node in nodes.items():
        if node.router_type not in ROUTER_TYPES:
            raise GraphValidationError(
                f"node {name!r}: router_type {node.router_type!r} not in "
                f"{ROUTER_TYPES}"
            )
        if not node.steps:
            raise GraphValidationError(f"node {name!r} has no steps")
        for s in node.steps:
            if (s.service is None) == (s.node is None):
                raise GraphValidationError(
                    f"node {name!r} step {s.label!r}: exactly one of "
                    "service/node required"
                )
            if s.node is not None and s.node not in nodes:
                raise GraphValidationError(
                    f"node {name!r} references unknown node {s.node!r}"
                )
        if node.router_type == "Ensemble":
            labels = [s_.label for s_ in node.steps]
            if len(set(labels)) != len(labels):
                raise GraphValidationError(
                    f"Ensemble node {name!r}: step labels must be unique "
                    f"(give colliding steps a name:), got {labels}"
                )
        if node.router_type == "Splitter":
            if any(s.weight is None for s in node.steps):
                raise GraphValidationError(
                    f"Splitter node {name!r}: every step needs a weight"
                )
    # Cycle check: DFS from root over node->node edges.
    state: Dict[str, int] = {}

    def visit(n: str, path: tuple) -> None:
        if state.get(n) == 2:
            return
        if state.get(n) == 1:
            raise GraphValidationError(
                f"node cycle: {' -> '.join(path + (n,))}"
            )
        state[n] = 1
        for s in nodes[n].steps:
            if s.node is not None:
                visit(s.node, path + (n,))
        state[n] = 2

    visit("root", ())


def _matches(condition: str, instance: Any) -> bool:
    if "=" not in condition:
        return False
    field, want = condition.split("=", 1)
    if isinstance(instance, dict):
        return str(instance.get(field)) == want
    return False


class GraphRouter:
    """Executes a graph for one request. ``call_service(name, instances)``
    is injected by the server (it proxies through the activator)."""

    def __init__(self, graph: InferenceGraph, call_service) -> None:
        self.graph = graph
        self.call = call_service

    async def execute(self, instances: List[Any]) -> Any:
        return await self._run_node("root", instances, instances)

    async def _run_step(self, step: GraphStep, instances, original):
        feed = original if step.data == "$request" else instances
        if step.service is not None:
            return await self.call(step.service, feed)
        return await self._run_node(step.node, feed, original)

    async def _run_node(self, name: str, instances, original):
        node = self.graph.spec.nodes[name]
        if node.router_type == "Sequence":
            out = instances
            for step in node.steps:
                out = await self._run_step(step, out, original)
            return out
        if node.router_type == "Switch":
            probe = instances[0] if instances else None
            default = None
            for step in node.steps:
                if step.condition is None:
                    default = step
                elif _matches(step.condition, probe):
                    return await self._run_step(step, instances, original)
            if default is not None:
                return await self._run_step(default, instances, original)
            raise GraphValidationError(
                f"switch node {name!r}: no arm matched and no default"
            )
        if node.router_type == "Ensemble":
            import asyncio

            outs = await asyncio.gather(*(
                self._run_step(s, instances, original) for s in node.steps
            ))
            return {s.label: o for s, o in zip(node.steps, outs)}
        # Splitter: deterministic hash of the payload picks the arm, so
        # identical requests are routed identically (stable canarying).
        total = sum(s.weight for s in node.steps)
        digest = hashlib.sha256(
            json.dumps(instances, sort_keys=True, default=str).encode()
        ).digest()
        point = int.from_bytes(digest[:8], "big") % total
        acc = 0
        for step in node.steps:
            acc += step.weight
            if point < acc:
                return await self._run_step(step, instances, original)
        return await self._run_step(node.steps[-1], instances, original)
