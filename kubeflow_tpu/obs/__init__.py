"""Unified observability plane (ISSUE 5).

``obs.trace`` is the span recorder shared by all three planes
(controller reconcile loop, training runtime, serving engine): bounded
ring buffer, context-manager spans, Chrome trace-event JSON export that
loads in Perfetto. ``obs.registry`` is the one Counter/Gauge/Histogram
substrate behind every Prometheus exposition the repo emits -- label
escaping lives in exactly one place.
"""

from kubeflow_tpu.obs import registry, trace

__all__ = ["registry", "trace"]
