"""Unified observability plane (ISSUE 5, ISSUE 20).

``obs.trace`` is the span recorder shared by all three planes
(controller reconcile loop, training runtime, serving engine): bounded
ring buffer, context-manager spans, Chrome trace-event JSON export that
loads in Perfetto. ``obs.registry`` is the one Counter/Gauge/Histogram
substrate behind every Prometheus exposition the repo emits -- label
escaping lives in exactly one place. ``obs.timeseries`` keeps the short
scraped history behind ``/debug/series`` and the SLO burn-rate windows;
``obs.goodput`` is the goodput/badput attribution ledger.
"""

from kubeflow_tpu.obs import goodput, registry, timeseries, trace

__all__ = ["goodput", "registry", "timeseries", "trace"]
