"""Goodput/badput ledger: every second of gang-hold time attributed.

The production question behind the north star: of every wall-clock
second a job owned chips, how many produced tokens? The worker-side
``GoodputLedger`` answers it by construction -- a single monotonic
cursor walks forward through the step loop, and every ``settle(state)``
charges the time since the last settle to exactly one attribution
state. Nothing is ever double-charged or dropped, so

    sum(seconds.values()) == cursor - start        (exactly)

is an arithmetic identity, and conservation against wall-clock reduces
to "the loop settles often enough" (the analysis family's KT-OBS-
CONSERVE check plants a dropped charge to prove the gate trips).

The worker emits cumulative per-state seconds over KFTPU-METRIC
(``gp_compute=... gp_epoch=... gp_wall=...``); the controller-side
``JobGoodput`` aggregator stitches incarnations together across
restarts: an epoch change banks the dead incarnation's final counters
and charges the gap between incarnations -- the time the gang held
chips while nothing ran -- to ``restart_recovery``. Job-level
conservation is then also structural:

    attributed == (last_epoch + last_wall) - first_epoch
"""

from __future__ import annotations

import time
from typing import Dict, Optional

# The attribution states. Every second of a held gang lands in exactly
# one. "compute" is the only goodput; the rest are priced badput.
STATES = ("compute", "checkpoint", "reshard", "restart_recovery",
          "input_wait", "idle")

# KFTPU-METRIC field prefix for the cumulative per-state counters.
FIELD_PREFIX = "gp_"


class GoodputLedger:
    """Worker-side single-cursor attribution ledger.

    ``settle(state)`` charges now - cursor to ``state`` and advances the
    cursor; ``charge(state, dt)`` books an externally measured duration
    (also advancing the cursor, same conservation discipline).
    """

    def __init__(self, clock=time.perf_counter,
                 epoch: Optional[float] = None) -> None:
        self._clock = clock
        self.epoch = float(epoch if epoch is not None else time.time())
        self._start = clock()
        self._cursor = self._start
        self.seconds: Dict[str, float] = {s: 0.0 for s in STATES}

    def settle(self, state: str) -> float:
        """Attribute everything since the last settle to ``state``."""
        if state not in self.seconds:
            raise ValueError(f"unknown goodput state {state!r}")
        now = self._clock()
        dt = max(now - self._cursor, 0.0)
        self.seconds[state] += dt
        self._cursor = now
        return dt

    def charge(self, state: str, dt: float) -> None:
        """Book an externally timed duration (cursor advances with it,
        so the measured span is not re-attributed by the next settle)."""
        if state not in self.seconds:
            raise ValueError(f"unknown goodput state {state!r}")
        dt = max(float(dt), 0.0)
        self.seconds[state] += dt
        self._cursor += dt

    def wall(self) -> float:
        """Attributed wall time: cursor - start. After a settle this is
        also clock-now - start; between settles the unattributed tail is
        deliberately excluded so the identity below never lies."""
        return self._cursor - self._start

    def attributed(self) -> float:
        return sum(self.seconds.values())

    def conservation_error(self) -> float:
        """|attributed - wall| -- zero up to float rounding, by
        construction. The analysis gate asserts this stays ~0 and that
        a planted dropped charge breaks it."""
        return abs(self.attributed() - self.wall())

    def goodput_fraction(self) -> float:
        att = self.attributed()
        return self.seconds["compute"] / att if att > 0 else 0.0

    def fields(self) -> Dict[str, str]:
        """Cumulative KFTPU-METRIC fields (settle('idle') first so the
        emitted wall equals the attributed sum at emit time)."""
        out = {FIELD_PREFIX + s: f"{self.seconds[s]:.3f}" for s in STATES}
        out[FIELD_PREFIX + "epoch"] = f"{self.epoch:.3f}"
        out[FIELD_PREFIX + "wall"] = f"{self.wall():.3f}"
        return out


def parse_fields(sample: Dict[str, str]) -> Optional[dict]:
    """Extract ``{state: seconds}``, epoch and wall from one parsed
    KFTPU-METRIC line; None when the line carries no ledger fields."""
    if FIELD_PREFIX + "epoch" not in sample:
        return None
    try:
        return {
            "epoch": float(sample[FIELD_PREFIX + "epoch"]),
            "wall": float(sample.get(FIELD_PREFIX + "wall", 0.0)),
            "seconds": {
                s: float(sample.get(FIELD_PREFIX + s, 0.0)) for s in STATES
            },
        }
    except (TypeError, ValueError):
        return None


class JobGoodput:
    """Controller-side aggregator over one job's worker incarnations.

    Feed it every scraped ledger sample (cumulative counters). The
    current incarnation is identified by ``gp_epoch``; when the epoch
    moves, the previous incarnation's final counters are banked and the
    wall gap between incarnations is charged to ``restart_recovery`` --
    the crash-to-resume window during which the gang held chips but no
    ledger was running.
    """

    def __init__(self) -> None:
        self.banked: Dict[str, float] = {s: 0.0 for s in STATES}
        self.first_epoch: Optional[float] = None
        self._cur: Optional[dict] = None  # last sample of live incarnation
        self.incarnations = 0

    def observe(self, sample: dict) -> None:
        epoch = sample["epoch"]
        if self.first_epoch is None:
            self.first_epoch = epoch
        cur = self._cur
        if cur is not None and epoch != cur["epoch"]:
            # Bank the dead incarnation at its last observed counters.
            for s in STATES:
                self.banked[s] += cur["seconds"][s]
            gap = epoch - (cur["epoch"] + cur["wall"])
            self.banked["restart_recovery"] += max(gap, 0.0)
            self._cur = None
        if self._cur is None:
            self.incarnations += 1
        # Cumulative counters: keep the newest sample only (monotone
        # within an incarnation; a stale out-of-order line loses).
        if self._cur is None or sample["wall"] >= self._cur["wall"]:
            self._cur = dict(sample)

    def totals(self) -> Dict[str, float]:
        out = dict(self.banked)
        if self._cur is not None:
            for s in STATES:
                out[s] += self._cur["seconds"][s]
        return out

    def attributed(self) -> float:
        return sum(self.totals().values())

    def wall(self) -> float:
        """(last_epoch + last_wall) - first_epoch: the job's ledger-
        covered wall span across every incarnation and every gap."""
        if self._cur is None or self.first_epoch is None:
            return 0.0
        return (self._cur["epoch"] + self._cur["wall"]) - self.first_epoch

    def conservation_error(self) -> float:
        wall = self.wall()
        if wall <= 0:
            return 0.0
        return abs(self.attributed() - wall) / wall

    def goodput_fraction(self) -> float:
        att = self.attributed()
        return self.totals()["compute"] / att if att > 0 else 0.0
