"""Bounded in-process time-series store (the fleet telemetry plane).

Per-replica ``/metrics`` and KFTPU-METRIC lines are instantaneous: they
vanish on scrape, so nobody can ask "what was this job's goodput over
the last ten minutes" or run a burn-rate window over them. This module
keeps a short history: one bounded ring per (name, labels) series, fed
by the controller's scrape loop (controller/telemetry.py), queryable
in-process (the SLO burn-rate evaluator), over ``GET /debug/series``,
and from ``kftpu top``.

Deliberately small: append-mostly rings, O(capacity) memory per series,
no persistence -- history dies with the controller, exactly like the
trace recorder. Downsampling happens at query time (bucketed mean +
last), not at ingest, so the raw short-horizon data stays exact.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from kubeflow_tpu.obs.registry import render_labels

DEFAULT_CAPACITY = 512


class Series:
    """One bounded ring of ``(unix_ts, value)`` points.

    ``stale`` marks a series whose source stopped answering (replica
    died mid-scrape); the points stay queryable but consumers must not
    treat the last value as current. Any successful ``add`` clears it.
    """

    __slots__ = ("name", "labels", "points", "stale", "_lock")

    def __init__(self, name: str, labels: Optional[dict] = None,
                 capacity: int = DEFAULT_CAPACITY) -> None:
        self.name = name
        self.labels = dict(labels or {})
        self.points: deque = deque(maxlen=max(int(capacity), 1))
        self.stale = False
        self._lock = threading.Lock()

    def add(self, value: float, ts: Optional[float] = None) -> None:
        with self._lock:
            self.points.append(
                (float(ts if ts is not None else time.time()), float(value)))
            self.stale = False

    def mark_stale(self) -> None:
        self.stale = True

    @property
    def last(self) -> Optional[Tuple[float, float]]:
        with self._lock:
            return self.points[-1] if self.points else None

    def query(self, since: Optional[float] = None,
              until: Optional[float] = None,
              step: Optional[float] = None) -> List[Tuple[float, float]]:
        """Points in ``[since, until]``; ``step`` buckets them (one
        point per bucket at the bucket's last timestamp, value = mean
        over the bucket) -- the downsampled view long windows read."""
        with self._lock:
            pts = [p for p in self.points
                   if (since is None or p[0] >= since)
                   and (until is None or p[0] <= until)]
        if not step or step <= 0 or not pts:
            return pts
        out: List[Tuple[float, float]] = []
        bucket = None
        acc: List[Tuple[float, float]] = []
        for ts, v in pts:
            b = int(ts // step)
            if bucket is None:
                bucket = b
            if b != bucket:
                out.append((acc[-1][0], sum(x[1] for x in acc) / len(acc)))
                acc = []
                bucket = b
            acc.append((ts, v))
        if acc:
            out.append((acc[-1][0], sum(x[1] for x in acc) / len(acc)))
        return out

    def mean(self, since: Optional[float] = None) -> Optional[float]:
        pts = self.query(since=since)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)


class SeriesStore:
    """Get-or-create registry of Series keyed ``(name, rendered labels)``
    -- the same keying discipline as obs.registry so one (name, labels)
    pair can never split into two rings."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = capacity
        self._series: Dict[Tuple[str, str], Series] = {}
        self._lock = threading.Lock()

    def series(self, name: str, labels: Optional[dict] = None) -> Series:
        key = (name, render_labels(labels))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = Series(name, labels, capacity=self.capacity)
                self._series[key] = s
            return s

    def add(self, name: str, labels: Optional[dict], value: float,
            ts: Optional[float] = None) -> None:
        self.series(name, labels).add(value, ts)

    def get(self, name: str, labels: Optional[dict] = None
            ) -> Optional[Series]:
        return self._series.get((name, render_labels(labels)))

    def all(self) -> Iterable[Series]:
        with self._lock:
            return list(self._series.values())

    def mark_stale(self, labels_subset: Optional[dict] = None) -> int:
        """Mark every series whose labels contain ``labels_subset`` as
        stale (replica death: all its series at once). Returns count."""
        n = 0
        for s in self.all():
            if labels_subset and not all(
                    s.labels.get(k) == v for k, v in labels_subset.items()):
                continue
            s.mark_stale()
            n += 1
        return n

    def snapshot(self, name: Optional[str] = None,
                 since: Optional[float] = None,
                 step: Optional[float] = None) -> dict:
        """JSON-safe dump for ``GET /debug/series`` / ``kftpu top``."""
        out = []
        for s in self.all():
            if name and s.name != name:
                continue
            pts = s.query(since=since, step=step)
            out.append({
                "name": s.name,
                "labels": dict(s.labels),
                "stale": bool(s.stale),
                "points": [[round(ts, 3), v] for ts, v in pts],
            })
        out.sort(key=lambda d: (d["name"], render_labels(d["labels"])))
        return {"series": out, "now": time.time()}

    def clear(self) -> None:
        with self._lock:
            self._series.clear()


# Process-wide store, mirroring obs.registry.REGISTRY: the controller
# scrape loop writes it, /debug/series and the burn-rate evaluator read
# it. Tests construct private SeriesStores instead of resetting this.
STORE = SeriesStore()
