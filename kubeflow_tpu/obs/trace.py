"""Bounded ring-buffer span recorder with Chrome trace-event export.

One recorder per process, shared by all three planes.  Spans are
context managers; ``instant`` records point events; nesting flows
through a contextvar so child spans inherit the enclosing span's plane
and track without threading state through call signatures.  When
tracing is disabled, ``span()`` returns a shared no-op object -- the
whole call is one global load, one attribute check, and a singleton
return, well under the 2 microsecond budget the serving hot paths
demand.

Export is Chrome trace-event JSON (the ``traceEvents`` array form)
loadable in Perfetto / chrome://tracing.  ``pid`` encodes the plane
(controller=1 / runtime=2 / serving=3, offset by the OS pid so merged
multi-process traces never collide), ``tid`` is one track per
component; ``M`` metadata events carry the human-readable names.
Timestamps come from ``time.perf_counter_ns`` (CLOCK_MONOTONIC --
system-wide on Linux), so traces exported by the controller, a spawned
worker, and the serving server merge onto one consistent timeline.

Trace context propagates controller -> worker through the
``KFTPU_TRACE_*`` env vars (see ``propagation_env`` /
``activate_from_env``); ``controller/envvars.py`` injects them into
worker environments and ``runtime/bootstrap.py`` adopts them and opens
the worker's root span.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# Propagation env vars (controller -> worker).
# --------------------------------------------------------------------------
ENV_TRACE = "KFTPU_TRACE"            # "1": enable tracing in this process
ENV_TRACE_ID = "KFTPU_TRACE_ID"      # shared id tying a distributed trace together
ENV_TRACE_DIR = "KFTPU_TRACE_DIR"    # directory for per-process trace dumps
ENV_TRACE_BUFFER = "KFTPU_TRACE_BUFFER"  # ring capacity override (events)

DEFAULT_CAPACITY = 65536

# Plane -> pid base.  The OS pid is folded in so two runtime workers (or
# a controller and a same-plane test process) exporting separately still
# merge without (pid, tid) collisions.
_PLANE_IDS = {"controller": 1, "runtime": 2, "serving": 3}
_OTHER_PLANE_ID = 9

_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "kftpu_trace_current", default=None
)


def _now_us() -> float:
    return time.perf_counter_ns() / 1000.0


class _NullSpan:
    """Shared no-op returned while tracing is disabled (and for nesting
    fallbacks): enter/exit do nothing, annotations vanish."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def annotate(self, **kw: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """A live duration span: records ``B`` on enter, ``E`` on exit."""

    __slots__ = ("_rec", "name", "plane", "track", "_args", "_token", "_extra")

    def __init__(self, rec: "TraceRecorder", name: str, plane: Optional[str],
                 track: Optional[str], args: Optional[Dict[str, Any]]) -> None:
        self._rec = rec
        self.name = name
        self.plane = plane
        self.track = track
        self._args = args
        self._token: Optional[contextvars.Token] = None
        self._extra: Optional[Dict[str, Any]] = None

    def annotate(self, **kw: Any) -> None:
        """Attach args to the closing ``E`` event (e.g. a drain reason
        only known at the end of the block)."""
        if self._extra is None:
            self._extra = kw
        else:
            self._extra.update(kw)

    def __enter__(self) -> "Span":
        parent = _CURRENT.get()
        if parent is not None:
            if self.plane is None:
                self.plane = parent.plane
            if self.track is None:
                self.track = parent.track
        if self.plane is None:
            self.plane = self._rec.default_plane
        if self.track is None:
            self.track = threading.current_thread().name
        self._token = _CURRENT.set(self)
        self._rec._record("B", self.name, self.plane, self.track,
                          _now_us(), self._args)
        return self

    def __exit__(self, *exc: Any) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
        self._rec._record("E", self.name, self.plane, self.track,
                          _now_us(), self._extra)
        return False


class TraceRecorder:
    """Thread-safe bounded event ring.  All mutation is one deque append
    under one lock; exports snapshot and sanitize without stopping the
    recorder."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(16, int(capacity)))
        self._recorded = 0
        self.enabled = False
        self.trace_id: Optional[str] = None
        self.default_plane = "runtime"
        self.process_label = ""

    # -- recording ---------------------------------------------------------
    def _record(self, ph: str, name: str, plane: str, track: str,
                ts: float, args: Optional[Dict[str, Any]]) -> None:
        with self._lock:
            self._events.append((ph, name, plane, track, ts, args))
            self._recorded += 1

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._recorded - len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._recorded = 0

    # -- export ------------------------------------------------------------
    def snapshot(self) -> List[Tuple]:
        with self._lock:
            return list(self._events)

    def export(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (dict form).  The snapshot is
        sanitized so the structural invariants hold regardless of ring
        eviction or still-open spans: every ``B`` has a matching ``E``
        on its tid, orphaned ``E`` events (begin evicted) are dropped,
        and per-tid timestamps are non-decreasing."""
        events = sorted(self.snapshot(), key=lambda e: e[4])
        ospid = os.getpid() % 100000
        pids: Dict[str, int] = {}
        tids: Dict[Tuple[str, str], int] = {}
        out: List[Dict[str, Any]] = []
        meta: List[Dict[str, Any]] = []
        open_stacks: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
        last_ts = 0.0

        def _pid(plane: str) -> int:
            if plane not in pids:
                base = _PLANE_IDS.get(plane, _OTHER_PLANE_ID)
                pids[plane] = base * 100000 + ospid
                label = self.process_label or f"pid {os.getpid()}"
                meta.append({"ph": "M", "name": "process_name",
                             "pid": pids[plane], "tid": 0,
                             "args": {"name": f"{plane}: {label}"}})
                meta.append({"ph": "M", "name": "process_sort_index",
                             "pid": pids[plane], "tid": 0,
                             "args": {"sort_index": base}})
            return pids[plane]

        def _tid(plane: str, track: str) -> int:
            key = (plane, track)
            if key not in tids:
                tids[key] = len(tids) + 1
                meta.append({"ph": "M", "name": "thread_name",
                             "pid": _pid(plane), "tid": tids[key],
                             "args": {"name": track}})
            return tids[key]

        for ph, name, plane, track, ts, args in events:
            last_ts = max(last_ts, ts)
            ev: Dict[str, Any] = {
                "ph": ph, "name": name, "cat": plane, "ts": ts,
                "pid": _pid(plane), "tid": _tid(plane, track),
            }
            if args:
                ev["args"] = dict(args)
            if ph == "B":
                open_stacks.setdefault((plane, track), []).append(ev)
            elif ph == "E":
                stack = open_stacks.get((plane, track))
                if not stack:
                    # Begin fell off the ring: an unmatched E would
                    # break B/E balance -- drop it.
                    continue
                stack.pop()
            elif ph == "i":
                ev["s"] = "t"
            out.append(ev)
        # Close spans still open at export time (root spans of a live
        # process, the ring snapshotted mid-span).
        for (plane, track), stack in open_stacks.items():
            for ev in reversed(stack):
                out.append({"ph": "E", "name": ev["name"], "cat": plane,
                            "ts": last_ts, "pid": ev["pid"],
                            "tid": ev["tid"],
                            "args": {"truncated": True}})
        return {
            "traceEvents": meta + out,
            "displayTimeUnit": "ms",
            "otherData": {
                "trace_id": self.trace_id or "",
                "recorded": self._recorded,
                "dropped": self.dropped,
            },
        }

    def write(self, path: str) -> str:
        data = self.export()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
        return path


_RECORDER = TraceRecorder()


# --------------------------------------------------------------------------
# Module-level API (what instrumentation sites call).
# --------------------------------------------------------------------------
def recorder() -> TraceRecorder:
    return _RECORDER


def enabled() -> bool:
    return _RECORDER.enabled


def span(name: str, plane: Optional[str] = None, track: Optional[str] = None,
         **args: Any):
    """Context-manager span.  Near-free when tracing is off."""
    rec = _RECORDER
    if not rec.enabled:
        return _NULL_SPAN
    return Span(rec, name, plane, track, args or None)


def instant(name: str, plane: Optional[str] = None,
            track: Optional[str] = None, ts: Optional[float] = None,
            **args: Any) -> None:
    """Point event ('i' phase, thread scope)."""
    rec = _RECORDER
    if not rec.enabled:
        return
    parent = _CURRENT.get()
    if parent is not None:
        plane = plane or parent.plane
        track = track or parent.track
    rec._record("i", name, plane or rec.default_plane,
                track or threading.current_thread().name,
                _now_us() if ts is None else ts, args or None)


def begin(name: str, plane: Optional[str] = None,
          track: Optional[str] = None, **args: Any) -> None:
    """Open a span manually (cross-thread pairs, e.g. queue-wait that
    begins on the submitting thread and ends on the engine thread).
    Callers own the matching ``end`` on the SAME track; a begin whose
    end never arrives is closed at export with truncated=True."""
    rec = _RECORDER
    if not rec.enabled:
        return
    rec._record("B", name, plane or rec.default_plane,
                track or threading.current_thread().name, _now_us(),
                args or None)


def end(name: str, plane: Optional[str] = None,
        track: Optional[str] = None, **args: Any) -> None:
    rec = _RECORDER
    if not rec.enabled:
        return
    rec._record("E", name, plane or rec.default_plane,
                track or threading.current_thread().name, _now_us(),
                args or None)


def current_span():
    """The innermost live span in this context (None when untracked)."""
    return _CURRENT.get()


def configure(enabled: Optional[bool] = None, plane: Optional[str] = None,
              label: Optional[str] = None, capacity: Optional[int] = None,
              trace_id: Optional[str] = None) -> TraceRecorder:
    rec = _RECORDER
    if capacity is not None and capacity != rec.capacity:
        with rec._lock:
            rec._events = deque(rec._events, maxlen=max(16, int(capacity)))
    if plane is not None:
        rec.default_plane = plane
    if label is not None:
        rec.process_label = label
    if trace_id is not None:
        rec.trace_id = trace_id
    if enabled is not None:
        if enabled and rec.trace_id is None:
            rec.trace_id = new_trace_id()
        rec.enabled = bool(enabled)
    return rec


def reset() -> None:
    """Test hook: drop all state (including a capacity override) and
    disable."""
    rec = _RECORDER
    rec.enabled = False
    rec.trace_id = None
    rec.default_plane = "runtime"
    rec.process_label = ""
    with rec._lock:
        rec._events = deque(maxlen=DEFAULT_CAPACITY)
        rec._recorded = 0


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def trace_id() -> Optional[str]:
    return _RECORDER.trace_id


# --------------------------------------------------------------------------
# Cross-process propagation.
# --------------------------------------------------------------------------
def propagation_env() -> Dict[str, str]:
    """Env vars a parent injects into children so one distributed trace
    spans controller -> worker.  Empty when tracing is off."""
    rec = _RECORDER
    if not rec.enabled:
        return {}
    env = {ENV_TRACE: "1", ENV_TRACE_ID: rec.trace_id or new_trace_id()}
    tdir = os.environ.get(ENV_TRACE_DIR)
    if tdir:
        env[ENV_TRACE_DIR] = tdir
    return env


def activate_from_env(environ=None, plane: str = "runtime",
                      label: str = "") -> bool:
    """Adopt trace context from the environment (worker side).  Returns
    True when tracing was switched on."""
    environ = os.environ if environ is None else environ
    if environ.get(ENV_TRACE) != "1":
        return False
    cap = None
    raw = environ.get(ENV_TRACE_BUFFER)
    if raw:
        try:
            cap = int(raw)
        except ValueError:
            cap = None
    configure(enabled=True, plane=plane, label=label, capacity=cap,
              trace_id=environ.get(ENV_TRACE_ID) or None)
    return True


def dump_dir(environ=None) -> Optional[str]:
    environ = os.environ if environ is None else environ
    return environ.get(ENV_TRACE_DIR) or None


def write_process_trace(environ=None, name: Optional[str] = None) -> Optional[str]:
    """Write this process's trace into KFTPU_TRACE_DIR (if configured and
    tracing is on).  Workers call this at exit so ``kftpu trace dump``
    can merge per-process files into one timeline."""
    rec = _RECORDER
    if not rec.enabled:
        return None
    tdir = dump_dir(environ)
    if not tdir:
        return None
    fname = name or f"trace-{rec.default_plane}-{os.getpid()}.json"
    return rec.write(os.path.join(tdir, fname))


# --------------------------------------------------------------------------
# Merging (``kftpu trace dump``).
# --------------------------------------------------------------------------
def merge(documents: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Concatenate exported trace documents into one.  Per-process pid
    offsets make this collision-free; perf_counter timestamps share
    CLOCK_MONOTONIC so the merged timeline is consistent on one host."""
    events: List[Dict[str, Any]] = []
    ids: List[str] = []
    recorded = dropped = 0
    for doc in documents:
        events.extend(doc.get("traceEvents", []))
        other = doc.get("otherData", {})
        tid = other.get("trace_id")
        if tid and tid not in ids:
            ids.append(tid)
        recorded += int(other.get("recorded", 0))
        dropped += int(other.get("dropped", 0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": ",".join(ids), "recorded": recorded,
                      "dropped": dropped},
    }


def span_counts(doc: Dict[str, Any]) -> Dict[str, int]:
    """Per-plane completed-span counts for a trace document (used by the
    bench --trace-out summaries)."""
    counts: Dict[str, int] = {}
    total = 0
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "B":
            counts[ev.get("cat", "?")] = counts.get(ev.get("cat", "?"), 0) + 1
            total += 1
    counts["total"] = total
    return counts


def plane_summaries(doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Per-plane roll-up of a merged trace document, for ``kftpu trace
    dump``'s human summary: span + instant counts per plane, plus the
    serving fleet signals -- each engine process's final ``engine-stats``
    snapshot (queue depth, TTFT EMA, tokens) and the router's ``route``
    decision mix (direct/spilled/steered/shed/disagg)."""
    out: Dict[str, Dict[str, Any]] = {}

    def plane_of(ev: Dict[str, Any]) -> Dict[str, Any]:
        return out.setdefault(
            ev.get("cat", "?"), {"spans": 0, "instants": 0}
        )

    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        if ph == "B":
            p = plane_of(ev)
            p["spans"] += 1
            if ev.get("name") == "kv.migrate":
                # Prefix-cache migration roll-up (serving/kv_reshard):
                # span-open args carry src/dst/bytes, so the summary
                # works on truncated traces too (no E event needed).
                args = ev.get("args") or {}
                mig = p.setdefault(
                    "kv_migration",
                    {"entries": 0, "bytes": 0, "pairs": {}})
                mig["entries"] += 1
                mig["bytes"] += int(args.get("bytes", 0) or 0)
                pair = f"{args.get('src', '?')}->{args.get('dst', '?')}"
                mig["pairs"][pair] = mig["pairs"].get(pair, 0) + 1
        elif ph in ("i", "I"):
            p = plane_of(ev)
            p["instants"] += 1
            args = ev.get("args") or {}
            if ev.get("name") == "engine-stats":
                # Latest snapshot wins per emitting process (events are
                # time-ordered within a process dump).
                eng = p.setdefault("engines", {})
                eng[str(ev.get("pid", "?"))] = {
                    "queue_depth": args.get("queue_depth", 0),
                    "slots_active": args.get("slots_active", 0),
                    "ttft_ema_ms": args.get("ttft_ema_ms", 0.0),
                    "tokens_generated": args.get("tokens_generated", 0),
                    "requests_finished": args.get("requests_finished", 0),
                }
            elif ev.get("name") == "route":
                routes = p.setdefault("routes", {})
                kind = str(args.get("kind", "direct"))
                routes[kind] = routes.get(kind, 0) + 1
                if args.get("spilled"):
                    routes["spilled"] = routes.get("spilled", 0) + 1
                if args.get("steered"):
                    routes["steered"] = routes.get("steered", 0) + 1
    return out
