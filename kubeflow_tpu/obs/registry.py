"""Shared Counter/Gauge/Histogram types + the ONE Prometheus formatter.

Every Prometheus exposition the repo emits (serving /metrics, the
control-plane server, reconciler event counters, mirrored training step
metrics) renders through ``sample_line`` below, so label escaping --
backslash, double quote, newline, per the text-format spec -- lives in
exactly one place.  The output shape is deliberately identical to the
hand-formatted lines this module replaced: bare samples, labels joined
with ``,``, histogram ``le`` bounds stringified from the float bound
(``le="0.005"``), ``_sum`` at six decimals.  Existing scrapers
(``hpo/metrics.py``, external Prometheus) see no diff.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

LabelArg = Union[None, str, Mapping[str, Any]]


def escape_label_value(v: Any) -> str:
    """Prometheus text-format label-value escaping.  The single place a
    label value (e.g. a dynamically admitted model name) is sanitized."""
    return (str(v).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))


def render_labels(labels: LabelArg) -> str:
    """``k="v",k2="v2"`` (no braces), keys sorted so one (name, labels)
    pair always renders -- and therefore KEYS -- identically regardless
    of dict insertion order.  Accepts a mapping, an already-rendered
    string (legacy call sites), or None."""
    if labels is None:
        return ""
    if isinstance(labels, str):
        return labels
    return ",".join(
        f'{k}="{escape_label_value(labels[k])}"' for k in sorted(labels)
    )


def format_value(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float) and v != v:  # NaN
        return "NaN"
    return str(v)


def sample_line(name: str, labels: LabelArg, value: Any) -> str:
    lab = render_labels(labels)
    if lab:
        return f"{name}{{{lab}}} {format_value(value)}"
    return f"{name} {format_value(value)}"


class Counter:
    """Monotonic counter.  ``inc`` is a lock-protected add; reads are a
    plain attribute load (ints are torn-read safe in CPython)."""

    kind = "counter"

    def __init__(self, name: str, labels: LabelArg = None,
                 help: str = "") -> None:
        self.name = name
        self.labels = labels
        self.help = help
        self.value: Union[int, float] = 0
        self._lock = threading.Lock()

    def inc(self, n: Union[int, float] = 1) -> None:
        with self._lock:
            self.value += n

    def lines(self) -> List[str]:
        return [sample_line(self.name, self.labels, self.value)]


class Gauge:
    """Settable value; optionally pull-based via ``set_fn`` (sampled at
    exposition time -- how the engine ``stats()`` gauges are ported
    without a background updater)."""

    kind = "gauge"

    def __init__(self, name: str, labels: LabelArg = None,
                 help: str = "") -> None:
        self.name = name
        self.labels = labels
        self.help = help
        self.value: Union[int, float] = 0
        self._fn: Optional[Callable[[], Any]] = None

    def set(self, v: Union[int, float]) -> None:
        self.value = v

    def inc(self, n: Union[int, float] = 1) -> None:
        self.value += n

    def dec(self, n: Union[int, float] = 1) -> None:
        self.value -= n

    def set_fn(self, fn: Callable[[], Any]) -> "Gauge":
        self._fn = fn
        return self

    def lines(self) -> List[str]:
        v = self._fn() if self._fn is not None else self.value
        return [sample_line(self.name, self.labels, v)]


class Histogram:
    """Prometheus cumulative histogram: per-bucket counts, ``_sum`` and
    ``_count``; allocation-free observe (one list walk)."""

    kind = "histogram"

    def __init__(self, buckets: Sequence[float], name: str = "",
                 labels: LabelArg = None, help: str = "") -> None:
        # Upper bounds in ascending order, +Inf implicit.
        self.buckets: Tuple[float, ...] = tuple(buckets)
        assert list(self.buckets) == sorted(self.buckets), \
            "histogram buckets must ascend"
        self.name = name
        self.labels = labels
        self.help = help
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.n = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.n += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def prom_lines(self, name: Optional[str] = None,
                   labels: LabelArg = None) -> List[str]:
        """Cumulative exposition.  ``le`` ascends and ``+Inf`` equals
        ``_count`` by construction."""
        name = name or self.name
        lab = render_labels(self.labels if labels is None else labels)
        sep = f"{lab}," if lab else ""
        out = []
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f'{name}_bucket{{{sep}le="{b}"}} {cum}')
        out.append(f'{name}_bucket{{{sep}le="+Inf"}} {self.n}')
        out.append(sample_line(f"{name}_sum", lab, f"{self.sum:.6f}"))
        out.append(sample_line(f"{name}_count", lab, self.n))
        return out

    def lines(self) -> List[str]:
        return self.prom_lines()


class Registry:
    """Name+labels -> metric store with one exposition walk.

    ``counter``/``gauge``/``histogram`` are get-or-create (idempotent
    across scrapes/restarts of the owning component); ``expose`` renders
    every registered metric through the shared formatter in registration
    order."""

    def __init__(self) -> None:
        self._metrics: Dict[Tuple[str, str], Any] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, labels: LabelArg, help: str, **kw):
        key = (name, render_labels(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name=name, labels=labels, help=help, **kw)
                self._metrics[key] = m
            return m

    def counter(self, name: str, labels: LabelArg = None,
                help: str = "") -> Counter:
        return self._get(Counter, name, labels, help)

    def gauge(self, name: str, labels: LabelArg = None,
              help: str = "") -> Gauge:
        return self._get(Gauge, name, labels, help)

    def histogram(self, name: str, buckets: Sequence[float],
                  labels: LabelArg = None, help: str = "") -> Histogram:
        return self._get(Histogram, name, labels, help, buckets=buckets)

    def register(self, metric) -> None:
        """Adopt an externally-constructed metric (e.g. an engine-owned
        histogram) into this registry's exposition."""
        key = (metric.name, render_labels(metric.labels))
        with self._lock:
            self._metrics[key] = metric

    def expose(self) -> List[str]:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.lines())
        return lines

    def catalog(self) -> List[Tuple[str, str, str]]:
        """(name, kind, rendered-labels) rows -- docs / debug listing."""
        with self._lock:
            return [(m.name, m.kind, render_labels(m.labels))
                    for m in self._metrics.values()]


# Process-wide default registry: runtime step metrics and controller
# event counters land here.  Serving models keep per-instance registries
# (their lifetime follows model load/evict).
REGISTRY = Registry()
