"""TrainTask: the contract between models and the generic train loop.

A task owns its model, optimizer, data, and sharded train step; the entry
loop (runtime.entry) owns bootstrap, checkpoint cadence, metrics, and exit
codes. Adding a model family = implementing this class + registering it
(models.register_task), nothing else.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Iterator, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class TrainTask(abc.ABC):
    name: str = "task"
    #: tokens (LM) or examples (classification) consumed per global step.
    tokens_per_step: int = 0
    #: FLOPs per token for MFU accounting; None disables MFU.
    flops_per_token: Optional[float] = None

    @abc.abstractmethod
    def init_state(self, rng: jax.Array, mesh: Mesh) -> Any:
        """Build the (sharded) train state on the mesh."""

    @abc.abstractmethod
    def train_step_fn(self, mesh: Mesh) -> Callable[..., tuple[Any, dict]]:
        """Return the jitted step: (state, *batch_arrays) -> (state, metrics)."""

    @abc.abstractmethod
    def data_iter(
        self, num_processes: int, process_id: int, mesh: Mesh, seed: int = 0
    ) -> Iterator[tuple[jax.Array, ...]]:
        """Yield device-ready global batch arrays."""

    def reshard_state(self, state: Any, new_mesh: Mesh, **plan_kwargs):
        """Consume a mid-run resize: the SAME logical state, live, on
        ``new_mesh`` -- no checkpoint round-trip (parallel/reshard.py).

        The default transplants every leaf's PartitionSpec onto the new
        mesh, which is correct for any state built from the logical-axis
        rules (models.common.state_shardings). Tasks whose layout is
        mesh-dependent beyond the spec (rare) override this. Returns
        ``(new_state, ReshardPlan)``; raises InfeasibleReshardError when
        the plan is rejected -- the caller then takes the
        checkpoint-restart path. The input state is donated."""
        from kubeflow_tpu.parallel.reshard import reshard

        return reshard(state, new_mesh, donate=True, **plan_kwargs)


def host_to_global(mesh: Mesh, spec: P, local_arr) -> jax.Array:
    """Assemble a global array from this process's local shard.

    Single-process: a plain device_put with the sharding (all shards local).
    Multi-process: each process contributes its slice of the ``data`` axis.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local_arr, sharding)
    return jax.make_array_from_process_local_data(sharding, local_arr)
