"""In-worker training runtime.

What the reference leaves to user containers (SURVEY.md section 1: Kubeflow
never touches tensors), this framework owns: distributed bootstrap from the
injected env, mesh construction, the training loop with MFU/throughput
metric lines, and orbax checkpoint/resume.
"""
