"""Machine-parsable training metrics.

One line per step on stdout (SURVEY.md 5.5): this is simultaneously the
user-facing progress log, the HPO metrics-collector input (scraped by
regex exactly as Katib's stdout collector K5 does), and the source of the
north-star numbers (tokens/sec, MFU).

Format: ``KFTPU-METRIC key=value key=value ...`` -- floats in repr form.
"""

from __future__ import annotations

import re
import sys
import time
from typing import Optional, TextIO

from kubeflow_tpu.obs import registry as _obs_registry
from kubeflow_tpu.obs import trace as _obs_trace

PREFIX = "KFTPU-METRIC"
_LINE_RE = re.compile(rf"^{PREFIX}\s+(.*)$")
_KV_RE = re.compile(r"([A-Za-z0-9_./-]+)=([^\s]+)")

# Peak dense bf16 FLOP/s per chip, for MFU accounting. v5e ("TPU v5 lite"):
# 197 TFLOP/s bf16; v5p: 459. Selected by device_kind at runtime.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "cpu": 1e11,  # nominal, keeps MFU finite in CPU tests
}


def peak_flops_per_chip() -> float:
    import jax

    kind = jax.devices()[0].device_kind
    for name, flops in PEAK_FLOPS.items():
        if name.lower() in kind.lower():
            return flops
    return 197e12


class MetricLogger:
    """Emits metric lines; rank-0 only by default (one line per step/job)."""

    def __init__(
        self,
        enabled: bool = True,
        stream: Optional[TextIO] = None,
        flops_per_token: Optional[float] = None,
        n_chips: int = 1,
    ) -> None:
        self.enabled = enabled
        self.stream = stream or sys.stdout
        self.flops_per_token = flops_per_token
        self.n_chips = max(n_chips, 1)
        self.peak = None
        self._last_time: Optional[float] = None
        self._last_step: Optional[int] = None

    def log_step(self, step: int, loss: float, tokens: int = 0, **extra) -> None:
        """``tokens`` is tokens (or examples) consumed *per step*; the
        logger scales by the number of steps since the previous call."""
        if not self.enabled:
            return
        now = time.perf_counter()
        fields = {"step": step, "loss": f"{loss:.6f}"}
        # Mirror into the shared metrics registry (obs.registry): same
        # numbers a Prometheus scrape of this process would see.  The
        # KFTPU-METRIC stdout line below stays the HPO contract.
        gauge = _obs_registry.REGISTRY.gauge
        gauge("kftpu_train_step").set(step)
        gauge("kftpu_train_loss").set(loss)
        if self._last_time is not None and self._last_step is not None and tokens:
            dsteps = max(step - self._last_step, 1)
            dt = now - self._last_time
            tps = tokens * dsteps / dt
            fields["tokens_per_sec"] = f"{tps:.1f}"
            fields["tokens_per_sec_per_chip"] = f"{tps / self.n_chips:.1f}"
            fields["step_time_ms"] = f"{dt * 1e3 / dsteps:.1f}"
            gauge("kftpu_train_tokens_per_sec").set(round(tps, 1))
            gauge("kftpu_train_step_time_ms").set(round(dt * 1e3 / dsteps, 1))
            if self.flops_per_token:
                if self.peak is None:
                    self.peak = peak_flops_per_chip()
                mfu = (tps * self.flops_per_token) / (self.peak * self.n_chips)
                fields["mfu"] = f"{mfu:.4f}"
                gauge("kftpu_train_mfu").set(round(mfu, 4))
        self._last_time = now
        self._last_step = step
        fields.update({k: v for k, v in extra.items()})
        self.emit(**fields)

    def emit(self, **fields) -> None:
        if not self.enabled:
            return
        # Tie stdout metric lines to the active trace: trace_id is one
        # more k=v token, matched by the same _KV_RE the HPO collector
        # already uses -- the line grammar does not move.
        if _obs_trace.enabled() and "trace_id" not in fields:
            tid = _obs_trace.trace_id()
            if tid:
                fields["trace_id"] = tid
        body = " ".join(f"{k}={v}" for k, v in fields.items())
        print(f"{PREFIX} {body}", file=self.stream, flush=True)


def parse_metric_line(line: str) -> Optional[dict[str, str]]:
    """Parse one stdout line; None if it is not a metric line."""
    m = _LINE_RE.match(line.strip())
    if not m:
        return None
    return dict(_KV_RE.findall(m.group(1)))


def transformer_flops_per_token(n_params: int, seq_len: int = 0, n_layers: int = 0,
                                hidden: int = 0, with_attention: bool = True) -> float:
    """Standard 6N + attention FLOPs-per-token accounting (training:
    forward + backward). Attention term: 12 * L * H * S per token."""
    flops = 6.0 * n_params
    if with_attention and n_layers and hidden and seq_len:
        flops += 12.0 * n_layers * hidden * seq_len
    return flops
