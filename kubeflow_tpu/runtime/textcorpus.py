"""Offline text-corpus + tokenizer pipeline for real-text LM training.

Round-4 verdict: every quality-sensitive serving number (speculative
acceptance, int8 top-1 agreement, prefix-cache benefit) was measured on
RANDOM weights, where greedy decode degenerates into cycles and the
numbers say nothing about a trained model. This module is the fix's
first half: build a real-text corpus from what the machine already has
(this image has zero network egress -- Python source trees and
/usr/share/doc are the in-image text), train a byte-level BPE tokenizer
on it (the `tokenizers` crate ships with transformers), and encode to
the ``.bin`` memmap convention ``runtime.data.file_tokens`` consumes.
The second half is a normal JAXJob: ``model=llama data=<corpus.bin>``.

Upstream parity note: the reference's training stack assumes users bring
tokenized data (its examples shell out to HF datasets + tokenizers); a
first-class corpus pipeline is the TPU-repo equivalent that works in an
air-gapped image.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Iterator, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

# Where real text lives in a stock Python image, in preference order.
# Python source is genuine mixed natural-language/code text (docstrings,
# comments, identifiers) with heavy cross-file repetition -- which is
# exactly the distribution serving features like prompt-lookup
# speculation and prefix caching are designed for.
DEFAULT_ROOTS: tuple[str, ...] = (
    "/opt/venv/lib/python3.12/site-packages",
    "/usr/local/lib",
    "/usr/lib/python3.11",
    "/usr/share/doc",
)

_TEXT_EXTS = (".py", ".txt", ".md", ".rst", ".pyi")

# Generated files are degenerate text (one-line protobufs, minified
# bundles); they teach the model nothing and skew BPE merges.
_SKIP_SUFFIXES = ("_pb2.py", "_pb2_grpc.py", ".min.js")
_SKIP_DIRS = {"__pycache__", "node_modules", ".git", "tests", "test"}


def iter_text_files(
    roots: Sequence[str] = DEFAULT_ROOTS,
    max_file_bytes: int = 512 * 1024,
) -> Iterator[str]:
    """Deterministic walk (sorted dirs/files) over readable text files."""
    for root in roots:
        if not os.path.isdir(root):
            continue
        for dirpath, dirs, files in os.walk(root):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for f in sorted(files):
                if not f.endswith(_TEXT_EXTS):
                    continue
                if any(f.endswith(s) for s in _SKIP_SUFFIXES):
                    continue
                p = os.path.join(dirpath, f)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    continue
                if 0 < size <= max_file_bytes:
                    yield p


def build_corpus(
    out_train: str,
    out_heldout: str,
    roots: Sequence[str] = DEFAULT_ROOTS,
    max_bytes: int = 256 * 1024 * 1024,
    holdout_every: int = 53,
) -> dict:
    """Concatenate files into train/heldout text (every ``holdout_every``-th
    FILE is held out -- document-level holdout, so heldout prompts are
    never literal substrings of the training stream). Documents are
    separated by NUL, which the tokenizer maps to its document-boundary
    token. Returns counts for the manifest."""
    n_train = n_held = b_train = b_held = 0
    with open(out_train, "w", encoding="utf-8", errors="replace") as ft, \
            open(out_heldout, "w", encoding="utf-8", errors="replace") as fh:
        for i, path in enumerate(iter_text_files(roots)):
            if b_train >= max_bytes:
                break
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    text = f.read()
            except OSError:
                continue
            if not text.strip():
                continue
            if i % holdout_every == 0:
                fh.write(text)
                fh.write("\x00")
                n_held += 1
                b_held += len(text)
            else:
                ft.write(text)
                ft.write("\x00")
                n_train += 1
                b_train += len(text)
    return {
        "train_files": n_train, "heldout_files": n_held,
        "train_bytes": b_train, "heldout_bytes": b_held,
    }


def train_bpe(
    corpus_txt: str,
    out_json: str,
    vocab_size: int = 32768,
) -> None:
    """Byte-level BPE over the corpus (GPT-2-style: no unk token, every
    byte reachable). vocab_size defaults to the llama3-1b preset's
    32768 so the trained tokenizer drops straight into that geometry."""
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<doc>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train([corpus_txt], trainer)
    tok.save(out_json)


def encode_to_bin(
    tokenizer_json: str,
    txt_path: str,
    out_bin: str,
    chunk_bytes: int = 8 * 1024 * 1024,
) -> int:
    """Stream-encode text -> uint16 token ids in the ``.bin`` memmap
    convention (runtime.data._load_token_stream). NUL document
    boundaries become the <doc> special token. Splits on boundaries so
    no chunk seam ever lands inside a document's BPE merge window...
    except the pathological single-document-bigger-than-chunk case,
    where the seam cost is one suboptimal merge. Returns token count."""
    from tokenizers import Tokenizer

    tok = Tokenizer.from_file(tokenizer_json)
    doc_id = tok.token_to_id("<doc>")
    assert doc_id is not None and doc_id < 65536
    n = 0
    with open(txt_path, encoding="utf-8") as f, open(out_bin, "wb") as out:
        buf = ""
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk and not buf:
                break
            buf += chunk
            if chunk:
                # Encode only complete documents; carry the tail.
                cut = buf.rfind("\x00")
                if cut < 0:
                    if len(buf) < 4 * chunk_bytes:
                        continue
                    # Oversized single document: flush what we have
                    # WITHOUT a boundary token (the doc continues in the
                    # next chunk; the seam costs one suboptimal merge,
                    # never a dropped char or a false <doc>).
                    ids0 = tok.encode(buf).ids
                    if ids0 and max(ids0) >= 65536:
                        raise ValueError("token id overflows uint16")
                    arr = np.asarray(ids0, np.uint16)
                    arr.tofile(out)
                    n += arr.size
                    buf = ""
                    continue
                docs, buf = buf[:cut], buf[cut + 1:]
            else:
                docs, buf = buf, ""
            ids: list[int] = []
            for doc in docs.split("\x00"):
                if doc:
                    ids.extend(tok.encode(doc).ids)
                ids.append(doc_id)
            arr = np.asarray(ids, np.uint16)
            if ids and max(ids) >= 65536:
                raise ValueError("token id overflows uint16")
            arr.tofile(out)
            n += arr.size
    return n


def prepare(
    out_dir: str,
    roots: Sequence[str] = DEFAULT_ROOTS,
    max_bytes: int = 256 * 1024 * 1024,
    vocab_size: int = 32768,
    force: bool = False,
) -> dict:
    """One-call pipeline: corpus -> tokenizer -> train/heldout .bin +
    a manifest.json. Idempotent unless force (the corpus build is
    minutes of single-core work)."""
    os.makedirs(out_dir, exist_ok=True)
    manifest_path = os.path.join(out_dir, "manifest.json")
    if os.path.exists(manifest_path) and not force:
        with open(manifest_path) as f:
            return json.load(f)
    train_txt = os.path.join(out_dir, "train.txt")
    held_txt = os.path.join(out_dir, "heldout.txt")
    tok_json = os.path.join(out_dir, "tokenizer.json")
    logger.info("building corpus under %s", out_dir)
    stats = build_corpus(train_txt, held_txt, roots, max_bytes)
    logger.info("training BPE tokenizer (vocab %d)", vocab_size)
    train_bpe(train_txt, tok_json, vocab_size)
    stats["train_tokens"] = encode_to_bin(
        tok_json, train_txt, os.path.join(out_dir, "train.bin"))
    stats["heldout_tokens"] = encode_to_bin(
        tok_json, held_txt, os.path.join(out_dir, "heldout.bin"))
    stats["vocab_size"] = vocab_size
    stats["roots"] = list(roots)
    with open(manifest_path, "w") as f:
        json.dump(stats, f, indent=1)
    return stats


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default="data/textlm")
    ap.add_argument("--max-mb", type=int, default=256)
    ap.add_argument("--vocab-size", type=int, default=32768)
    ap.add_argument("--force", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    stats = prepare(args.out_dir, max_bytes=args.max_mb * 1024 * 1024,
                    vocab_size=args.vocab_size, force=args.force)
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
