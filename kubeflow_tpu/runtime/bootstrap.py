"""Worker bootstrap: from injected env to an initialized JAX world.

The in-container half of the rendezvous contract (SURVEY.md 3.5, 5.8): the
controller injects JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID (kubeflow_tpu.controller.envvars); this module reads them
and calls ``jax.distributed.initialize`` -- the entire replacement for
NCCL world-building. Intra-slice collectives need zero further setup: XLA
compiles them over ICI.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

from kubeflow_tpu.obs import trace

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class WorkerContext:
    job_name: str
    namespace: str
    replica_type: str
    replica_index: int
    num_processes: int
    process_id: int
    coordinator: Optional[str]
    checkpoint_dir: Optional[str]
    resume: bool
    # jax.profiler window (SURVEY.md 5.1); profile_steps == 0 -> disabled.
    profile_dir: Optional[str] = None
    profile_start: int = 0
    profile_steps: int = 0
    # Trace context adopted from KFTPU_TRACE_* (obs.trace): tracing=True
    # means this worker records spans into the controller's trace id.
    tracing: bool = False
    trace_id: Optional[str] = None

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def read_context() -> WorkerContext:
    env = os.environ
    return WorkerContext(
        job_name=env.get("KFTPU_JOB_NAME", "standalone"),
        namespace=env.get("KFTPU_JOB_NAMESPACE", "default"),
        replica_type=env.get("KFTPU_REPLICA_TYPE", "Worker"),
        replica_index=int(env.get("KFTPU_REPLICA_INDEX", "0")),
        num_processes=int(env.get("JAX_NUM_PROCESSES", "1")),
        process_id=int(env.get("JAX_PROCESS_ID", "0")),
        coordinator=env.get("JAX_COORDINATOR_ADDRESS"),
        checkpoint_dir=env.get("KFTPU_CHECKPOINT_DIR") or None,
        resume=env.get("KFTPU_RESUME", "1") == "1",
        profile_dir=env.get("KFTPU_PROFILE_DIR") or None,
        profile_start=int(env.get("KFTPU_PROFILE_START", "0")),
        profile_steps=int(env.get("KFTPU_PROFILE_STEPS", "0")),
        tracing=env.get(trace.ENV_TRACE) == "1",
        trace_id=env.get(trace.ENV_TRACE_ID) or None,
    )


def initialize(ctx: Optional[WorkerContext] = None) -> WorkerContext:
    """Form the JAX world. Idempotent; safe for single-process jobs.

    Multi-process: dial the coordinator (worker-0) exactly as the reference's
    torch workers dial MASTER_ADDR -- but afterwards there is no per-op
    transport to configure; the mesh + pjit handle the rest.
    """
    ctx = ctx or read_context()
    if ctx.tracing:
        # Join the controller's trace: same id, runtime plane, one root
        # span that parents everything this worker records.  The root
        # stays open for the process lifetime; export closes it.
        trace.activate_from_env(
            plane="runtime",
            label=f"{ctx.job_name}/{ctx.replica_type.lower()}-"
                  f"{ctx.replica_index}",
        )
        root = trace.span(
            "worker", plane="runtime", track="train-loop",
            job=ctx.job_name, replica=ctx.replica_index,
            replica_type=ctx.replica_type, process_id=ctx.process_id,
        )
        root.__enter__()
    if ctx.num_processes > 1:
        import jax

        logger.info(
            "jax.distributed.initialize coordinator=%s procs=%d id=%d",
            ctx.coordinator, ctx.num_processes, ctx.process_id,
        )
        with trace.span("jax.distributed.initialize", plane="runtime",
                        coordinator=ctx.coordinator or "",
                        procs=ctx.num_processes):
            jax.distributed.initialize(
                coordinator_address=ctx.coordinator,
                num_processes=ctx.num_processes,
                process_id=ctx.process_id,
            )
    return ctx
