"""HF Llama checkpoint conversion.

Bridges the public model ecosystem into this framework: a HuggingFace
``LlamaForCausalLM`` directory (``save_pretrained`` / snapshot) converts
into the flax param pytree the training runtime and the serving engine
share, written as an orbax checkpoint an InferenceService loads directly
(``checkpoint: orbax``). For training warm-starts, load via
``convert_llama_from_hf`` in-process and build a fresh TrainState around
the params (the saved checkpoint carries no optimizer state):

    python -m kubeflow_tpu.runtime.convert_hf \
        --hf /models/llama3-8b --out /ckpt/llama3-8b

RoPE convention: HF stores Q/K projections permuted for its rotate-half
rope; this model applies interleaved (even/odd) rope, so Q/K rows are
un-permuted per head during conversion (the inverse of the well-known
Meta->HF permutation). Correctness oracle: converted logits match the HF
forward (tests/test_convert_hf.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import os
from typing import Any, Dict, Tuple

import numpy as np

from kubeflow_tpu.models.llama import LlamaConfig

logger = logging.getLogger(__name__)


def config_from_hf(hf_cfg) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=hf_cfg.num_key_value_heads,
        intermediate=hf_cfg.intermediate_size,
        max_seq=hf_cfg.max_position_embeddings,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        norm_eps=float(hf_cfg.rms_norm_eps),
    )


def _unpermute_rope(w: np.ndarray, n_heads: int, head_dim: int) -> np.ndarray:
    """[n_heads*head_dim, in] HF-permuted rows -> interleaved rows.

    HF's convention puts each head's rotary pairs as two half-blocks
    (rotate_half); ours interleaves them (even/odd). Row r of a head must
    come from HF row (r//2) if r is even else (head_dim//2 + r//2).
    """
    w = w.reshape(n_heads, 2, head_dim // 2, -1)
    return w.transpose(0, 2, 1, 3).reshape(n_heads * head_dim, -1)


def convert_llama_from_hf(path: str) -> Tuple[LlamaConfig, Dict[str, Any]]:
    """Load a local HF LlamaForCausalLM dir -> (LlamaConfig, variables).

    Returns the ``{"params": ...}`` pytree in scan layout (leaves stacked
    on a leading layer axis), fp32 numpy -- cast/shard downstream.
    """
    import torch  # noqa: F401 -- state_dict tensors
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(path, local_files_only=True)
    cfg = config_from_hf(hf_cfg)
    model = AutoModelForCausalLM.from_pretrained(
        path, local_files_only=True, torch_dtype="float32"
    )
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    del model

    h, nh, nkv, hd = cfg.hidden, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def layer(i: int, name: str) -> np.ndarray:
        return sd[f"model.layers.{i}.{name}.weight"]

    qs, ks, vs, os_, gates, ups, downs, ln1, ln2 = ([] for _ in range(9))
    for i in range(cfg.n_layers):
        # torch Linear stores [out, in]; y = x @ W.T -> our kernel = W.T.
        q = _unpermute_rope(layer(i, "self_attn.q_proj"), nh, hd)
        k = _unpermute_rope(layer(i, "self_attn.k_proj"), nkv, hd)
        qs.append(q.T.reshape(h, nh, hd))
        ks.append(k.T.reshape(h, nkv, hd))
        vs.append(layer(i, "self_attn.v_proj").T.reshape(h, nkv, hd))
        os_.append(layer(i, "self_attn.o_proj").T.reshape(nh, hd, h))
        gates.append(layer(i, "mlp.gate_proj").T)
        ups.append(layer(i, "mlp.up_proj").T)
        downs.append(layer(i, "mlp.down_proj").T)
        ln1.append(sd[f"model.layers.{i}.input_layernorm.weight"])
        ln2.append(sd[f"model.layers.{i}.post_attention_layernorm.weight"])

    stack = lambda xs: np.stack(xs)  # noqa: E731
    lm_head = sd.get("lm_head.weight")
    if lm_head is None:  # tied embeddings
        lm_head = sd["model.embed_tokens.weight"]
    params = {
        "embed": {"embedding": sd["model.embed_tokens.weight"]},
        "final_norm": {"scale": sd["model.norm.weight"]},
        "lm_head": {"kernel": lm_head.T},
        "layers": {"layer": {
            "attn_norm": {"scale": stack(ln1)},
            "mlp_norm": {"scale": stack(ln2)},
            "attn": {
                "q_proj": {"kernel": stack(qs)},
                "k_proj": {"kernel": stack(ks)},
                "v_proj": {"kernel": stack(vs)},
                "o_proj": {"kernel": stack(os_)},
            },
            "mlp": {
                "gate_proj": {"kernel": stack(gates)},
                "up_proj": {"kernel": stack(ups)},
                "down_proj": {"kernel": stack(downs)},
            },
        }},
    }
    return cfg, {"params": params}


def save_as_orbax(variables: Dict[str, Any], out_dir: str,
                  step: int = 0,
                  cfg: "LlamaConfig | None" = None) -> None:
    """Write the converted params as an orbax checkpoint the serving
    runtime loads. When ``cfg`` is given, a ``kftpu_config.json`` lands
    next to it so the server's ``preset: auto`` can reconstruct the
    model geometry without a matching named preset."""
    import json

    import orbax.checkpoint as ocp

    out_dir = os.path.abspath(out_dir)
    # All-numpy leaves: a jax scalar would stamp this host's device into
    # the sharding metadata and block restoring on other hardware (the
    # whole point of a conversion artifact is to move it).
    state = {
        "params": variables,
        # 0-d ndarray, not np.int64: orbax's StandardCheckpointHandler
        # accepts ndarrays but rejects bare numpy scalar types.
        "step": np.asarray(step, dtype=np.int64),
        "opt_state": {},
    }
    mgr = ocp.CheckpointManager(out_dir)
    mgr.save(step, args=ocp.args.StandardSave(state), force=True)
    mgr.wait_until_finished()
    mgr.close()
    if cfg is not None:
        with open(os.path.join(out_dir, "kftpu_config.json"), "w") as f:
            json.dump(dataclasses.asdict(cfg), f, indent=1)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("kftpu hf llama converter")
    p.add_argument("--hf", required=True, help="HF LlamaForCausalLM dir")
    p.add_argument("--out", required=True, help="orbax checkpoint dir")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    cfg, variables = convert_llama_from_hf(args.hf)
    save_as_orbax(variables, args.out, cfg=cfg)
    n = sum(np.asarray(x).size for x in _leaves(variables))
    logger.info(
        "converted %s -> %s (%.2fB params, config %s)",
        args.hf, args.out, n / 1e9, dataclasses.asdict(cfg),
    )
    return 0


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree


if __name__ == "__main__":
    raise SystemExit(main())
