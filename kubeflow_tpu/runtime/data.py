"""Data pipelines.

Deterministic, infinite iterators. Two source families:

- **Synthetic** (default): generated on host -- the target environment
  has zero egress (SURVEY.md 7.0), so benches and e2e tests need no
  staged data.
- **File-backed** (``file_tokens``): pre-tokenized corpora from disk --
  a ``.npy``/``.npz`` of token ids, a raw memmap (``.bin`` = uint16, the
  nanoGPT convention; ``.bin32`` = uint32 for >64k vocabs), or a
  ``datasets.save_to_disk`` directory with an ``input_ids``/``tokens``
  column. This is the replacement for the reference SDK's
  dataset-download init containers: stage once, point
  ``--arg data=<path>`` at it.

Each pipeline yields process-local shards sized global_batch/N. The
synthetic pipelines slice one deterministic global batch (process i gets
the i-th slice); file_tokens instead gives each process an independent
random-window stream -- shards are i.i.d. draws from the corpus, not
slices of a single enumerated batch.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterator

import numpy as np


@dataclasses.dataclass
class Batch:
    """Host-side numpy batch; .inputs/.targets semantics per task."""

    inputs: np.ndarray
    targets: np.ndarray


def synthetic_images(
    global_batch: int,
    shape: tuple[int, ...] = (28, 28, 1),
    n_classes: int = 10,
    num_processes: int = 1,
    process_id: int = 0,
    seed: int = 0,
) -> Iterator[Batch]:
    """MNIST-shaped synthetic data with a learnable signal: the label is
    encoded in the mean brightness, so loss decreases if training works."""
    if global_batch % num_processes:
        raise ValueError(f"batch {global_batch} % processes {num_processes} != 0")
    local = global_batch // num_processes
    rng = np.random.default_rng(seed * 1000003 + process_id)
    while True:
        labels = rng.integers(0, n_classes, size=(local,))
        imgs = rng.normal(0.0, 0.3, size=(local, *shape)).astype(np.float32)
        imgs += (labels / n_classes).reshape((local,) + (1,) * len(shape))
        yield Batch(inputs=imgs, targets=labels.astype(np.int32))


def synthetic_tokens(
    global_batch: int,
    seq_len: int,
    vocab_size: int,
    num_processes: int = 1,
    process_id: int = 0,
    seed: int = 0,
) -> Iterator[Batch]:
    """LM token streams with local structure (next token correlates with
    current), so cross-entropy is reducible below log(V)."""
    if global_batch % num_processes:
        raise ValueError(f"batch {global_batch} % processes {num_processes} != 0")
    local = global_batch // num_processes
    rng = np.random.default_rng(seed * 7340033 + process_id)
    while True:
        base = rng.integers(0, vocab_size, size=(local, 1))
        steps = rng.integers(0, 17, size=(local, seq_len))
        toks = (base + np.cumsum(steps, axis=1)) % vocab_size
        toks = toks.astype(np.int32)
        yield Batch(inputs=toks[:, :-1], targets=toks[:, 1:])


def _load_token_stream(path: str) -> np.ndarray:
    """Load a 1-D token-id array from any supported on-disk format.

    .bin stays a memmap (a 10 GB corpus must not be materialized in RAM;
    slicing a memmap yields plain ndarray windows, and batches are cast
    to int32 per window anyway)."""
    if os.path.isdir(path):
        # datasets.save_to_disk directory.
        import datasets  # local import: torch-adjacent, slow

        ds = datasets.load_from_disk(path)
        if isinstance(ds, datasets.DatasetDict):
            if len(ds) != 1:
                raise ValueError(
                    f"dataset at {path} has splits {sorted(ds)}; point at "
                    "one split's subdirectory"
                )
            ds = next(iter(ds.values()))
        for col in ("input_ids", "tokens"):
            if col in ds.column_names:
                return np.concatenate(
                    [np.asarray(row).ravel() for row in ds[col]]
                )
        raise ValueError(
            f"dataset at {path} has no input_ids/tokens column "
            f"(columns: {ds.column_names})"
        )
    if path.endswith(".npz"):
        with np.load(path) as z:
            return np.asarray(z[z.files[0]]).ravel()
    if path.endswith(".npy"):
        return np.load(path, mmap_mode="r").ravel()
    if path.endswith(".bin"):
        # nanoGPT-style raw memmap: uint16 by convention.
        return np.memmap(path, dtype=np.uint16, mode="r")
    if path.endswith(".bin32"):
        # uint32 variant for vocabs past 65535 (e.g. Llama-3's 128k).
        return np.memmap(path, dtype=np.uint32, mode="r")
    raise ValueError(
        f"unsupported token file {path!r} (want .npy/.npz/.bin/.bin32 or a "
        "datasets.save_to_disk directory)"
    )


def file_tokens(
    path: str,
    global_batch: int,
    seq_len: int,
    num_processes: int = 1,
    process_id: int = 0,
    seed: int = 0,
    vocab_size: int | None = None,
) -> Iterator[Batch]:
    """LM batches from a pre-tokenized corpus on disk.

    Infinite: each epoch draws random windows of ``seq_len`` (the
    standard packed-LM recipe -- no document boundaries, matching how
    the .bin convention is consumed). Deterministic per (seed, process);
    different processes draw disjoint random streams.
    """
    if global_batch % num_processes:
        raise ValueError(
            f"batch {global_batch} % processes {num_processes} != 0"
        )
    stream = _load_token_stream(path)
    if stream.size < seq_len + 1:
        raise ValueError(
            f"corpus {path} has {stream.size} tokens < seq_len+1="
            f"{seq_len + 1}"
        )
    if vocab_size is not None:
        # Fail fast on a vocab mismatch: out-of-range ids would silently
        # clamp in the embedding lookup and train on garbage. One O(N)
        # scan at iterator construction (memmap-friendly).
        top = int(np.max(stream))
        if top >= vocab_size:
            raise ValueError(
                f"corpus {path} contains token id {top} >= model vocab "
                f"{vocab_size} (retokenize or pick a bigger-vocab preset)"
            )
    local = global_batch // num_processes
    rng = np.random.default_rng(seed * 9176213 + process_id)
    hi = stream.size - seq_len - 1
    while True:
        starts = rng.integers(0, hi + 1, size=(local,))
        toks = np.stack([stream[s: s + seq_len + 1] for s in starts])
        toks = toks.astype(np.int32)
        yield Batch(inputs=toks[:, :-1], targets=toks[:, 1:])
