"""Data pipelines.

Synthetic, deterministic, infinite iterators -- the target environment has
zero egress (SURVEY.md 7.0), so benchmark/training data is generated on
host and staged to device. Each pipeline yields process-local shards: with
N data-parallel processes, process i gets the i-th slice of the global
batch, matching how jax.make_array_from_process_local_data assembles the
global array.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np


@dataclasses.dataclass
class Batch:
    """Host-side numpy batch; .inputs/.targets semantics per task."""

    inputs: np.ndarray
    targets: np.ndarray


def synthetic_images(
    global_batch: int,
    shape: tuple[int, ...] = (28, 28, 1),
    n_classes: int = 10,
    num_processes: int = 1,
    process_id: int = 0,
    seed: int = 0,
) -> Iterator[Batch]:
    """MNIST-shaped synthetic data with a learnable signal: the label is
    encoded in the mean brightness, so loss decreases if training works."""
    if global_batch % num_processes:
        raise ValueError(f"batch {global_batch} % processes {num_processes} != 0")
    local = global_batch // num_processes
    rng = np.random.default_rng(seed * 1000003 + process_id)
    while True:
        labels = rng.integers(0, n_classes, size=(local,))
        imgs = rng.normal(0.0, 0.3, size=(local, *shape)).astype(np.float32)
        imgs += (labels / n_classes).reshape((local,) + (1,) * len(shape))
        yield Batch(inputs=imgs, targets=labels.astype(np.int32))


def synthetic_tokens(
    global_batch: int,
    seq_len: int,
    vocab_size: int,
    num_processes: int = 1,
    process_id: int = 0,
    seed: int = 0,
) -> Iterator[Batch]:
    """LM token streams with local structure (next token correlates with
    current), so cross-entropy is reducible below log(V)."""
    if global_batch % num_processes:
        raise ValueError(f"batch {global_batch} % processes {num_processes} != 0")
    local = global_batch // num_processes
    rng = np.random.default_rng(seed * 7340033 + process_id)
    while True:
        base = rng.integers(0, vocab_size, size=(local, 1))
        steps = rng.integers(0, 17, size=(local, seq_len))
        toks = (base + np.cumsum(steps, axis=1)) % vocab_size
        toks = toks.astype(np.int32)
        yield Batch(inputs=toks[:, :-1], targets=toks[:, 1:])
