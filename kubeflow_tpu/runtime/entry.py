"""Generic training entrypoint: ``python -m kubeflow_tpu.runtime.entry``.

What runs inside every training worker (the analog of the user container's
torchrun script in the reference, SURVEY.md call stack 4.1): bootstrap the
world from injected env, build the mesh, run the task's train loop with
metric lines and orbax checkpointing, exit 0 on completion.

Fault injection (SURVEY.md 5.3): KFTPU_FAULT_STEP/KFTPU_FAULT_RANK make a
chosen rank die with exit code 137 at a chosen step -- the deterministic
stand-in for a preempted worker in restart/resume tests.
"""

from __future__ import annotations

import argparse
import contextlib
import logging
import os
import sys
import time

from kubeflow_tpu.obs import trace
from kubeflow_tpu.obs.goodput import GoodputLedger

# The command-file reader lives in the shared protocol module (one
# implementation for the worker poller, the controller writer, and the
# Tier C model checker's conformance pass); re-exported here because
# this is the seam the worker step loop and its tests import it from.
from kubeflow_tpu.controller.reshard_protocol import (  # noqa: F401
    read_resize_command,
)

logger = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser("kubeflow_tpu worker")
    p.add_argument("--model", required=True)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tensor", type=int, default=1)
    p.add_argument("--sequence", type=int, default=1)
    p.add_argument("--expert", type=int, default=1)
    p.add_argument("--pipe", type=int, default=1)
    p.add_argument("--num-slices",
                   default=os.environ.get("KFTPU_NUM_SLICES", "1"),
                   help="multislice: data axis spans slices over DCN. "
                        "'auto' = one slice per worker process, which "
                        "makes elastic replica re-formation a "
                        "slice-count resize (resharded restore)")
    p.add_argument(
        "--arg", action="append", default=[],
        help="task kwargs, key=value (int/float autocast)", metavar="K=V",
    )
    return p.parse_args(argv)


def resolve_num_slices(value, num_processes: int) -> int:
    """'auto' -> one slice per process: the reconciler's elastic
    re-formation (fewer replicas after a failure or metric resize) then
    IS slice-count elasticity -- the restarted workers rebuild the DCN
    mesh at the surviving slice count and orbax reshards the restore
    (SURVEY.md 5.3). Any int is an explicit override."""
    if value == "auto":
        return num_processes
    try:
        return int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"--num-slices must be an int or 'auto', got {value!r}"
        ) from None


def _cast(v: str):
    for t in (int, float):
        try:
            return t(v)
        except ValueError:
            pass
    return v


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    args = parse_args(argv)
    # Goodput ledger opens at process birth: bootstrap, mesh build and
    # the checkpoint restore are all restart-recovery badput. gp_epoch
    # (unix time) identifies this incarnation to the controller-side
    # aggregator, which charges the gap between incarnations -- the
    # crash-to-respawn window -- to restart_recovery as well.
    ledger = GoodputLedger()

    from kubeflow_tpu.runtime import bootstrap

    ctx = bootstrap.initialize()

    import jax

    # Numerics debugging (SURVEY.md 5.2: the TPU analog of the reference's
    # `go test -race` CI switch): KFTPU_DEBUG_NANS=1 makes every jitted
    # computation re-run un-jitted on NaN and raise with the culprit op;
    # KFTPU_CHECK_LEAKS=1 errors on tracer leaks. Both are debug-only --
    # they disable async dispatch and must stay off in production runs.
    if os.environ.get("KFTPU_DEBUG_NANS", "") == "1":
        jax.config.update("jax_debug_nans", True)
    if os.environ.get("KFTPU_CHECK_LEAKS", "") == "1":
        jax.config.update("jax_check_tracer_leaks", True)

    from kubeflow_tpu.models import get_task
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.runtime.checkpoint import Checkpointer
    from kubeflow_tpu.runtime.metrics import MetricLogger

    task_kwargs = dict(kv.split("=", 1) for kv in args.arg)
    task_kwargs = {k: _cast(v) for k, v in task_kwargs.items()}
    task = get_task(args.model, **task_kwargs)

    cfg = MeshConfig(data=-1, fsdp=args.fsdp, sequence=args.sequence,
                     tensor=args.tensor, expert=args.expert, pipe=args.pipe)
    num_slices = resolve_num_slices(args.num_slices, ctx.num_processes)
    if num_slices > 1:
        from kubeflow_tpu.parallel.mesh import build_multislice_mesh

        mesh = build_multislice_mesh(cfg, num_slices=num_slices)
    else:
        mesh = build_mesh(cfg)
    n_chips = len(jax.devices())
    logger.info(
        "worker %s/%s rank %d/%d mesh %s devices %d",
        ctx.job_name, ctx.replica_index, ctx.process_id, ctx.num_processes,
        dict(mesh.shape), n_chips,
    )

    fault_step = int(os.environ.get("KFTPU_FAULT_STEP", "-1"))
    fault_rank = int(os.environ.get("KFTPU_FAULT_RANK", "0"))

    with mesh:
        rng = jax.random.PRNGKey(args.seed)
        state = task.init_state(rng, mesh)
        step_fn = task.train_step_fn(mesh)
        ckpt = Checkpointer(
            ctx.checkpoint_dir,
            interval_steps=int(os.environ.get("KFTPU_CKPT_INTERVAL", "100")),
            keep=int(os.environ.get("KFTPU_CKPT_KEEP", "3")),
        )
        start_step = 0
        if ckpt.enabled and ctx.resume:
            from kubeflow_tpu.runtime.checkpoint import ReshardHandoff

            has_handoff = (
                ckpt.directory is not None
                and ReshardHandoff.peek_step(ckpt.directory) is not None
            )
            if has_handoff or ckpt.latest_step() is not None:
                # Fast path: a live handoff published in this process
                # reshards in memory; otherwise the orbax (resharding)
                # restore -- same blessed values either way.
                state, hstep = ckpt.restore_or_handoff(None, state, mesh)
                if hstep is None:
                    # Fell back to orbax (or an infeasible handoff with
                    # no checkpoint behind it: start fresh).
                    latest = ckpt.latest_step()
                    hstep = int(latest) if latest is not None else -1
                start_step = hstep + 1
                logger.info(
                    "resumed at step %d via %s", start_step,
                    "reshard handoff" if hstep is not None else "orbax",
                )

        mlog = MetricLogger(
            enabled=ctx.process_id == 0,
            flops_per_token=task.flops_per_token,
            n_chips=jax.device_count(),  # global chips across the world
        )
        ledger.settle("restart_recovery")
        mlog.emit(event="train_start", model=task.name, start_step=start_step,
                  steps=args.steps, world=ctx.num_processes)

        # jax.profiler window (SURVEY.md 5.1): rank 0 traces steps
        # [profile_start, profile_start + profile_steps); the trace is
        # TensorBoard/Perfetto-viewable from profile_dir.
        profiling = ctx.profile_steps > 0 and ctx.process_id == 0
        profile_dir = ctx.profile_dir or os.path.join(
            os.environ.get("KFTPU_LOG_DIR", "/tmp/kftpu"),
            "profile", ctx.job_name,
        )
        prof_active = False

        data = task.data_iter(ctx.num_processes, ctx.process_id, mesh, args.seed)
        metrics = {}
        # Reshard-in-place resize (parallel/reshard.py): the reconciler
        # writes a command file instead of tearing the gang down; the
        # step loop applies it between steps as a live device-to-device
        # state transfer and acks over KFTPU-METRIC. The DATA STREAM is
        # mesh-independent (same seeded host batches, only their
        # sharding changes), so fast-forwarding a fresh iterator by the
        # batches already consumed keeps the loss curve bit-exact
        # against the checkpoint-restart path onto the same mesh.
        resize_file = os.environ.get("KFTPU_RESIZE_FILE")
        resize_seq = 0
        batches_seen = 0
        resize_cm = contextlib.ExitStack()
        for step in range(start_step, args.steps):
            cmd = read_resize_command(resize_file, resize_seq)
            if cmd is not None:
                resize_seq = int(cmd.get("seq", 0))
                t0 = time.perf_counter()
                n_slices = int(cmd.get("num_slices", num_slices))
                n_devs = int(cmd.get("devices", 0))
                devs = jax.devices()[:n_devs] if n_devs else None
                try:
                    if n_slices > 1:
                        from kubeflow_tpu.parallel.mesh import (
                            build_multislice_mesh,
                        )

                        new_mesh = build_multislice_mesh(
                            cfg, num_slices=n_slices, devices=devs)
                    else:
                        new_mesh = build_mesh(cfg, devices=devs)
                    state, plan = task.reshard_state(state, new_mesh)
                except Exception as e:  # infeasible plan, bad geometry
                    # Keep training on the old mesh; the nack tells the
                    # controller to fall back to checkpoint-restart.
                    logger.warning("in-place resize failed: %s", e)
                    mlog.emit(event="reshard", reshard_seq=resize_seq,
                              reshard_ok=0, step=step)
                else:
                    mesh = new_mesh
                    num_slices = n_slices
                    resize_cm.close()
                    resize_cm.enter_context(mesh)
                    step_fn = task.train_step_fn(mesh)
                    data = task.data_iter(
                        ctx.num_processes, ctx.process_id, mesh, args.seed)
                    for _ in range(batches_seen):
                        next(data)
                    dt = time.perf_counter() - t0
                    logger.info(
                        "live reshard at step %d: %s in %.3fs "
                        "(%d B moved, %d B host-staged)", step,
                        plan.transition, dt, plan.bytes_moved,
                        plan.host_staged_bytes,
                    )
                    mlog.emit(
                        event="reshard", reshard_seq=resize_seq,
                        reshard_ok=1, reshard_seconds=f"{dt:.3f}",
                        reshard_transition=plan.transition,
                        reshard_bytes_moved=plan.bytes_moved,
                        reshard_host_staged_bytes=plan.host_staged_bytes,
                        step=step,
                    )
                # Ack or nack, the time went to the resize attempt.
                ledger.settle("reshard")
            with trace.span("step", plane="runtime", step=step):
                # >= not ==: a checkpoint resume landing inside (or past the
                # start of) the window still traces the remaining steps.
                if (profiling and not prof_active
                        and step >= ctx.profile_start
                        and step < ctx.profile_start + ctx.profile_steps):
                    os.makedirs(profile_dir, exist_ok=True)
                    jax.profiler.start_trace(profile_dir)
                    prof_active = True
                    mlog.emit(event="profile_start", step=step,
                              dir=profile_dir)
                with trace.span("data-wait"):
                    batch = next(data)
                    batches_seen += 1
                ledger.settle("input_wait")
                # Transient-fault semantics: the injected death fires only
                # in a fresh (non-resumed) incarnation, so restart+resume
                # recovers -- the scenario SURVEY.md 5.3 tests. A permanent
                # fault is just a crashing entrypoint; backoff_limit covers
                # that path.
                if (step == fault_step and ctx.process_id == fault_rank
                        and start_step == 0):
                    logger.error("fault injection: rank %d dying at step %d",
                                 ctx.process_id, step)
                    ckpt.wait()
                    os._exit(137)
                with trace.span("dispatch"):
                    state, metrics = step_fn(state, *batch)
                ledger.settle("compute")
                if (prof_active
                        and step >= ctx.profile_start + ctx.profile_steps - 1):
                    # Sync so the trace includes real device work, not just
                    # dispatch (transfer = sync on this backend, bench.py
                    # note).
                    float(metrics["loss"])
                    jax.profiler.stop_trace()
                    prof_active = False
                    mlog.emit(event="profile_end", step=step,
                              dir=profile_dir)
                ckpt.maybe_save(step, state)
                ledger.settle("checkpoint")
                if step % args.log_every == 0 or step == args.steps - 1:
                    # The float() is where the host blocks on the device
                    # step -- the device-sync share of the breakdown.
                    with trace.span("device-sync"):
                        loss = float(metrics["loss"])
                        extra = {k: f"{float(v):.4f}"
                                 for k, v in metrics.items() if k != "loss"}
                    # The sync blocked on the device step: compute, not
                    # overhead. The cumulative gp_* ledger fields ride
                    # the same metric line the controller already tails.
                    ledger.settle("compute")
                    extra.update(ledger.fields())
                    mlog.log_step(step, loss, tokens=task.tokens_per_step,
                                  **extra)
        resize_cm.close()
        if prof_active:  # window extended past the last step
            jax.profiler.stop_trace()
            mlog.emit(event="profile_end", step=args.steps - 1, dir=profile_dir)
        if ckpt.enabled:
            ckpt.maybe_save(args.steps - 1, state, force=True)
            ckpt.close()  # waits for the async save to land
            ledger.settle("checkpoint")
        final_loss = float(metrics["loss"]) if metrics else float("nan")
        ledger.settle("idle")  # teardown tail: attributed, not dropped
        mlog.emit(event="train_end", final_step=args.steps - 1,
                  final_loss=f"{final_loss:.6f}", **ledger.fields())
    # Per-process trace dump (KFTPU_TRACE_DIR): merged by `kftpu trace
    # dump` into the controller's timeline.
    trace.write_process_trace()
    return 0


if __name__ == "__main__":
    sys.exit(main())
