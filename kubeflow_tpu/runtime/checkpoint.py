"""Checkpoint/resume via orbax (SURVEY.md 5.4).

First-class in this framework (the reference delegates checkpointing to
user code): the runtime saves sharded checkpoints on an interval and the
reconciler's restart path simply re-runs the worker, which restores the
latest step here -- including *resharding* restores after an elastic
resize (orbax restores to whatever sharding the new mesh dictates).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

from kubeflow_tpu.obs import trace

logger = logging.getLogger(__name__)


class ReshardHandoff:
    """Process-local live-state handoff beside the orbax path.

    A component about to trigger a resize publishes its live state here
    keyed by the checkpoint directory; the restore side takes it and
    reshards it onto the new mesh in memory (parallel/reshard.py) --
    seconds of device transfers instead of an orbax disk round-trip.
    The store is process-local by design: it covers in-process resizes
    (runtime.entry's reshard-in-place path), co-located restart tests,
    and Podracer-style learner->actor weight publication; a cold process
    finds nothing here and falls back to orbax, which is exactly the
    checkpoint-restart path the controller expects."""

    _store: dict = {}

    @classmethod
    def publish(cls, key: str, step: int, state: Any) -> None:
        cls._store[key] = (int(step), state)

    @classmethod
    def take(cls, key: str) -> Optional[tuple]:
        """Pop and return ``(step, state)`` or None. Single-consumer:
        the state may be donated by the resharder, so it must not stay
        referenced here."""
        return cls._store.pop(key, None)

    @classmethod
    def peek_step(cls, key: str) -> Optional[int]:
        item = cls._store.get(key)
        return item[0] if item else None

    @classmethod
    def clear(cls) -> None:
        cls._store.clear()


class Checkpointer:
    """Thin orbax CheckpointManager wrapper bound to one job's directory."""

    def __init__(self, directory: Optional[str], interval_steps: int = 100,
                 keep: int = 3, enable_async: bool = True) -> None:
        self.directory = directory
        self._mgr = None
        if directory:
            import orbax.checkpoint as ocp

            os.makedirs(directory, exist_ok=True)
            self._mgr = ocp.CheckpointManager(
                os.path.abspath(directory),
                options=ocp.CheckpointManagerOptions(
                    save_interval_steps=interval_steps,
                    max_to_keep=keep,
                    enable_async_checkpointing=enable_async,
                    create=True,
                ),
            )

    @property
    def enabled(self) -> bool:
        return self._mgr is not None

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step() if self._mgr else None

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save if the interval policy says so. Async: returns immediately."""
        if not self._mgr:
            return False
        import orbax.checkpoint as ocp

        # Async save: this span covers the dispatch, not the background
        # write -- the visible cost the step loop actually pays.
        with trace.span("ckpt.save", plane="runtime", step=step,
                        force=force) as sp:
            saved = self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )
            sp.annotate(saved=bool(saved))
        return saved

    def restore(self, step: Optional[int], target: Any) -> Any:
        """Restore ``step`` (or latest) into the sharding/structure of
        ``target`` -- the resharding path for elastic resize."""
        if not self._mgr:
            return target
        step = self.latest_step() if step is None else step
        if step is None:
            return target
        import orbax.checkpoint as ocp

        logger.info("restoring checkpoint step=%d from %s", step, self.directory)
        with trace.span("ckpt.restore", plane="runtime", step=int(step)):
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target)
            )

    def restore_or_handoff(self, step: Optional[int], target: Any,
                           mesh=None) -> tuple[Any, Optional[int]]:
        """Reshard-handoff fast path beside ``restore()``.

        If a live state was published for this directory (ReshardHandoff)
        at a step no older than the latest on-disk checkpoint, reshard it
        onto ``mesh`` in memory -- no orbax round-trip -- and return
        ``(state, handoff_step)``. Otherwise fall back to the plain
        ``restore()`` and return ``(state, None)``; an infeasible
        handoff plan (lost shards, OOM) also falls back. ``target`` must
        be the freshly initialized state on the new mesh, exactly as
        ``restore()`` wants it."""
        if self.directory and mesh is not None:
            item = ReshardHandoff.take(self.directory)
            if item is not None:
                hstep, hstate = item
                latest = self.latest_step()
                if latest is None or hstep >= latest:
                    from kubeflow_tpu.parallel.reshard import (
                        InfeasibleReshardError,
                        reshard,
                    )

                    try:
                        state, plan = reshard(hstate, mesh, donate=True)
                        logger.info(
                            "reshard handoff: step=%d %s (%d B moved, "
                            "%d B host-staged) -- no orbax round-trip",
                            hstep, plan.transition, plan.bytes_moved,
                            plan.host_staged_bytes,
                        )
                        return state, hstep
                    except InfeasibleReshardError as e:
                        logger.warning(
                            "reshard handoff infeasible (%s); falling "
                            "back to orbax restore", e,
                        )
        return self.restore(step, target), None

    def wait(self) -> None:
        if self._mgr:
            self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr:
            self._mgr.wait_until_finished()
            self._mgr.close()
