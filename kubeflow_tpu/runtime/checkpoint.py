"""Checkpoint/resume via orbax (SURVEY.md 5.4).

First-class in this framework (the reference delegates checkpointing to
user code): the runtime saves sharded checkpoints on an interval and the
reconciler's restart path simply re-runs the worker, which restores the
latest step here -- including *resharding* restores after an elastic
resize (orbax restores to whatever sharding the new mesh dictates).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

from kubeflow_tpu.obs import trace

logger = logging.getLogger(__name__)


class Checkpointer:
    """Thin orbax CheckpointManager wrapper bound to one job's directory."""

    def __init__(self, directory: Optional[str], interval_steps: int = 100,
                 keep: int = 3, enable_async: bool = True) -> None:
        self.directory = directory
        self._mgr = None
        if directory:
            import orbax.checkpoint as ocp

            os.makedirs(directory, exist_ok=True)
            self._mgr = ocp.CheckpointManager(
                os.path.abspath(directory),
                options=ocp.CheckpointManagerOptions(
                    save_interval_steps=interval_steps,
                    max_to_keep=keep,
                    enable_async_checkpointing=enable_async,
                    create=True,
                ),
            )

    @property
    def enabled(self) -> bool:
        return self._mgr is not None

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step() if self._mgr else None

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save if the interval policy says so. Async: returns immediately."""
        if not self._mgr:
            return False
        import orbax.checkpoint as ocp

        # Async save: this span covers the dispatch, not the background
        # write -- the visible cost the step loop actually pays.
        with trace.span("ckpt.save", plane="runtime", step=step,
                        force=force) as sp:
            saved = self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )
            sp.annotate(saved=bool(saved))
        return saved

    def restore(self, step: Optional[int], target: Any) -> Any:
        """Restore ``step`` (or latest) into the sharding/structure of
        ``target`` -- the resharding path for elastic resize."""
        if not self._mgr:
            return target
        step = self.latest_step() if step is None else step
        if step is None:
            return target
        import orbax.checkpoint as ocp

        logger.info("restoring checkpoint step=%d from %s", step, self.directory)
        with trace.span("ckpt.restore", plane="runtime", step=int(step)):
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target)
            )

    def wait(self) -> None:
        if self._mgr:
            self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr:
            self._mgr.wait_until_finished()
            self._mgr.close()
