"""Checkpoint/resume via orbax (SURVEY.md 5.4).

First-class in this framework (the reference delegates checkpointing to
user code): the runtime saves sharded checkpoints on an interval and the
reconciler's restart path simply re-runs the worker, which restores the
latest step here -- including *resharding* restores after an elastic
resize (orbax restores to whatever sharding the new mesh dictates).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Dict, Optional

from kubeflow_tpu import chaos
from kubeflow_tpu.controller.reshard_protocol import write_json_atomic
from kubeflow_tpu.obs import registry as obs_registry
from kubeflow_tpu.obs import trace

logger = logging.getLogger(__name__)

MANIFEST_PREFIX = "manifest-"


def _hash_file(path: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ReshardHandoff:
    """Process-local live-state handoff beside the orbax path.

    A component about to trigger a resize publishes its live state here
    keyed by the checkpoint directory; the restore side takes it and
    reshards it onto the new mesh in memory (parallel/reshard.py) --
    seconds of device transfers instead of an orbax disk round-trip.
    The store is process-local by design: it covers in-process resizes
    (runtime.entry's reshard-in-place path), co-located restart tests,
    and Podracer-style learner->actor weight publication; a cold process
    finds nothing here and falls back to orbax, which is exactly the
    checkpoint-restart path the controller expects."""

    _store: dict = {}

    @classmethod
    def publish(cls, key: str, step: int, state: Any) -> None:
        cls._store[key] = (int(step), state)

    @classmethod
    def take(cls, key: str) -> Optional[tuple]:
        """Pop and return ``(step, state)`` or None. Single-consumer:
        the state may be donated by the resharder, so it must not stay
        referenced here."""
        return cls._store.pop(key, None)

    @classmethod
    def peek_step(cls, key: str) -> Optional[int]:
        item = cls._store.get(key)
        return item[0] if item else None

    @classmethod
    def clear(cls) -> None:
        cls._store.clear()


class Checkpointer:
    """Thin orbax CheckpointManager wrapper bound to one job's directory."""

    def __init__(self, directory: Optional[str], interval_steps: int = 100,
                 keep: int = 3, enable_async: bool = True) -> None:
        self.directory = directory
        self._mgr = None
        if directory:
            import orbax.checkpoint as ocp

            os.makedirs(directory, exist_ok=True)
            self._mgr = ocp.CheckpointManager(
                os.path.abspath(directory),
                options=ocp.CheckpointManagerOptions(
                    save_interval_steps=interval_steps,
                    max_to_keep=keep,
                    enable_async_checkpointing=enable_async,
                    create=True,
                ),
            )

    @property
    def enabled(self) -> bool:
        return self._mgr is not None

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step() if self._mgr else None

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save if the interval policy says so. Async: returns immediately."""
        if not self._mgr:
            return False
        import orbax.checkpoint as ocp

        # Async save: this span covers the dispatch, not the background
        # write -- the visible cost the step loop actually pays.
        import time as _time

        t0 = _time.perf_counter()
        with trace.span("ckpt.save", plane="runtime", step=step,
                        force=force) as sp:
            saved = self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )
            sp.annotate(saved=bool(saved))
        if saved:
            # Goodput-ledger companion metrics: the scrape loop and the
            # badput breakdown both read the save cadence from here.
            obs_registry.REGISTRY.counter("kftpu_ckpt_saves_total").inc()
            obs_registry.REGISTRY.gauge(
                "kftpu_ckpt_last_save_seconds"
            ).set(round(_time.perf_counter() - t0, 6))
            # The manager admits one outstanding async save: dispatching
            # THIS one means every earlier step is durable -- checksum
            # them now so a crash never leaves an unmanifested step.
            self._flush_manifests(exclude=int(step))
            fault = chaos.should("ckpt.write", str(step))
            if fault is not None and fault.kind == "torn_ckpt":
                # Deterministic torn/corrupted write: finalize this step
                # (manifest records the GOOD hashes), then mangle the
                # payload -- exactly the bitrot/torn-write shape the
                # verified restore must catch and fall back from.
                self.wait()
                self._mangle_step(int(step), fault)
        return saved

    # -- checksum manifests (corruption-safe restore) --------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(os.path.abspath(self.directory),
                            f"{MANIFEST_PREFIX}{int(step)}.json")

    def _step_dir(self, step: int) -> Optional[str]:
        root = os.path.abspath(self.directory)
        cand = os.path.join(root, str(int(step)))
        if os.path.isdir(cand):
            return cand
        # Step-name formats vary across orbax versions (zero padding);
        # fall back to scanning for a dir whose name parses to ``step``.
        try:
            for name in os.listdir(root):
                full = os.path.join(root, name)
                if os.path.isdir(full):
                    try:
                        if int(name) == int(step):
                            return full
                    except ValueError:
                        continue
        except OSError:
            pass
        return None

    def _flush_manifests(self, exclude: Optional[int] = None) -> None:
        """Write ``manifest-<step>.json`` (per-file size + blake2b,
        KT-ATOMIC01 staged write) for every durable step that lacks
        one, and drop manifests whose step was garbage-collected."""
        if not self._mgr:
            return
        live = {int(s) for s in (self._mgr.all_steps() or [])}
        root = os.path.abspath(self.directory)
        try:
            names = os.listdir(root)
        except OSError:
            return
        for name in names:
            if name.startswith(MANIFEST_PREFIX) and name.endswith(".json"):
                try:
                    s = int(name[len(MANIFEST_PREFIX):-len(".json")])
                except ValueError:
                    continue
                if s not in live:
                    try:
                        os.unlink(os.path.join(root, name))
                    except OSError:
                        pass
        for s in sorted(live):
            if exclude is not None and s == exclude:
                continue
            mpath = self._manifest_path(s)
            if os.path.exists(mpath):
                continue
            sdir = self._step_dir(s)
            if sdir is None:
                continue
            files: Dict[str, Dict[str, Any]] = {}
            for dirpath, _dirs, fnames in os.walk(sdir):
                for fn in sorted(fnames):
                    full = os.path.join(dirpath, fn)
                    rel = os.path.relpath(full, sdir)
                    try:
                        files[rel] = {
                            "size": os.path.getsize(full),
                            "blake2b": _hash_file(full),
                        }
                    except OSError:
                        # A file vanishing mid-walk means the step is
                        # being GC'd; skip the manifest this round.
                        files = {}
                        break
                if not files:
                    break
            if files:
                write_json_atomic(
                    mpath, {"version": 1, "step": s, "files": files}
                )

    def verify_step(self, step: int) -> Optional[bool]:
        """True: manifest present and every file matches (size + hash).
        False: corruption detected (missing/resized/bit-flipped file).
        None: no manifest to judge by (pre-manifest checkpoint or a
        save that never finalized) -- the caller decides trust."""
        mpath = self._manifest_path(step)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        sdir = self._step_dir(step)
        if sdir is None:
            return False
        for rel, meta in (manifest.get("files") or {}).items():
            full = os.path.join(sdir, rel)
            try:
                if os.path.getsize(full) != int(meta["size"]):
                    return False
                if _hash_file(full) != meta["blake2b"]:
                    return False
            except (OSError, KeyError, TypeError, ValueError):
                return False
        return True

    def _mangle_step(self, step: int, fault: Any) -> None:
        sdir = self._step_dir(step)
        if sdir is None:
            return
        best, best_size = None, -1
        for dirpath, _dirs, fnames in os.walk(sdir):
            for fn in fnames:
                full = os.path.join(dirpath, fn)
                try:
                    size = os.path.getsize(full)
                except OSError:
                    continue
                if size > best_size:
                    best, best_size = full, size
        if best is not None:
            chaos.inject.mangle_file(best, fault)

    def restore(self, step: Optional[int], target: Any) -> Any:
        """Restore ``step`` (or latest) into the sharding/structure of
        ``target`` -- the resharding path for elastic resize.

        Every candidate is verified against its checksum manifest
        first; a corrupt step logs the event and FALLS BACK to the next
        newest intact step instead of crashing mid-restore or silently
        loading garbage. All candidates corrupt raises -- resuming from
        a fabricated state is worse than an honest failure."""
        if not self._mgr:
            return target
        self.wait()  # finalize any in-flight save + its manifest
        step = self.latest_step() if step is None else step
        if step is None:
            return target
        import orbax.checkpoint as ocp

        steps = sorted(
            {int(s) for s in (self._mgr.all_steps() or [])} | {int(step)},
            reverse=True,
        )
        candidates = [s for s in steps if s <= int(step)]
        corrupt: list = []
        for s in candidates:
            ok = self.verify_step(s)
            if ok is False:
                corrupt.append(s)
                obs_registry.REGISTRY.counter(
                    "kftpu_ckpt_corrupt_total").inc()
                logger.error(
                    "checkpoint step=%d in %s FAILED checksum "
                    "verification; falling back to the next intact step",
                    s, self.directory,
                )
                trace.instant("ckpt.corrupt-fallback", plane="runtime",
                              step=s)
                continue
            if ok is None:
                logger.warning(
                    "checkpoint step=%d has no checksum manifest; "
                    "restoring unverified", s,
                )
            logger.info("restoring checkpoint step=%d from %s",
                        s, self.directory)
            with trace.span("ckpt.restore", plane="runtime", step=s,
                            verified=bool(ok), fallback=bool(corrupt)):
                obs_registry.REGISTRY.counter(
                    "kftpu_ckpt_restores_total").inc()
                return self._mgr.restore(
                    s, args=ocp.args.StandardRestore(target)
                )
        raise ValueError(
            f"no intact checkpoint in {self.directory}: steps "
            f"{corrupt} all failed checksum verification"
        )

    def restore_or_handoff(self, step: Optional[int], target: Any,
                           mesh=None) -> tuple[Any, Optional[int]]:
        """Reshard-handoff fast path beside ``restore()``.

        If a live state was published for this directory (ReshardHandoff)
        at a step no older than the latest on-disk checkpoint, reshard it
        onto ``mesh`` in memory -- no orbax round-trip -- and return
        ``(state, handoff_step)``. Otherwise fall back to the plain
        ``restore()`` and return ``(state, None)``; an infeasible
        handoff plan (lost shards, OOM) also falls back. ``target`` must
        be the freshly initialized state on the new mesh, exactly as
        ``restore()`` wants it."""
        if self.directory and mesh is not None:
            item = ReshardHandoff.take(self.directory)
            if item is not None:
                hstep, hstate = item
                latest = self.latest_step()
                if latest is None or hstep >= latest:
                    from kubeflow_tpu.parallel.reshard import (
                        InfeasibleReshardError,
                        reshard,
                    )

                    try:
                        state, plan = reshard(hstate, mesh, donate=True)
                        logger.info(
                            "reshard handoff: step=%d %s (%d B moved, "
                            "%d B host-staged) -- no orbax round-trip",
                            hstep, plan.transition, plan.bytes_moved,
                            plan.host_staged_bytes,
                        )
                        return state, hstep
                    except InfeasibleReshardError as e:
                        logger.warning(
                            "reshard handoff infeasible (%s); falling "
                            "back to orbax restore", e,
                        )
        return self.restore(step, target), None

    def wait(self) -> None:
        if self._mgr:
            self._mgr.wait_until_finished()
            # Everything is durable now -- including the newest step,
            # whose manifest maybe_save deliberately deferred.
            self._flush_manifests()

    def close(self) -> None:
        if self._mgr:
            self.wait()
            self._mgr.close()
