"""Pallas TPU decode attention: bounded-span KV-cache reads.

The serving engine's decode step attends over the FULL [Smax] cache slab
every step at every context length -- bounding the span in XLA (attend
``ck[:, :klen]``) regressed ~5x because slicing the scan-carried cache
materializes a per-layer copy instead of fusing into the attention reads
(measured 2026-07-30, note in serving/engine.py:_decode). This kernel is
the fix that note prescribes: the cache stays IN PLACE in HBM, and the
kernel manually DMAs only ceil(span/block) key/value blocks per slot into
VMEM, so HBM traffic scales with the LIVE context, not Smax.

Shapes (one layer's slice of the engine cache, layout unchanged):
  q         [B, KV, G, D]   query heads grouped under their KV head
  cache_k/v [B, Smax, KV, D]
  positions [B]             query position per slot (span = pos + 1)
  -> out    [B, KV, G, D]

Grid = (B,): per slot, a fori_loop with DATA-DEPENDENT trip count
cdiv(span, block) runs online-softmax flash attention over contiguous
[block, KV, D] cache chunks (the Smax dimension is the contiguous one,
so each DMA is one dense HBM burst). Rows past ``span`` in the final
block are masked; rows past a slot's span hold garbage by the engine's
masked-until-overwritten invariant, which this mask re-implements.

Numerics match ops.attention/xla paths: f32 scores and softmax
accumulation, output cast to the cache dtype.

MEASURED (2026-07-31, v5e, llama3-8b-proxy, 16 slots, decode_block=32,
Smax=2048, engine A/B via decode_attn_kernel): correctness exact to bf16
(max diff 1 ulp vs XLA full-span), but throughput is PARITY at short
contexts (622 vs 616 tok/s at 128-token prompts, where the span bound
saves ~90% of cache reads) and 9% WORSE at 1024-token prompts (439 vs
483, then single-buffered). Why: on this proxy the full-span cache read
is only ~19% of a decode step's HBM traffic (weights dominate at ~4.5
GB/step vs ~1.1 GB cache), capping the theoretical win at ~17%; DMA
serialization, per-KV-head narrow [G, D] matmuls, and pallas_call
overhead inside the layer scan consume that margin. The DMA is now
DOUBLE-BUFFERED (compute block j while j+1 streams -- see the r4
paragraph below for the measured recovery); the residual deficit vs
XLA is the narrow matmuls' MXU utilization (G=4 rows on a 128x128
array) plus pallas_call overhead, and head-batched matmuls remain the
known next step if a config makes the span bound matter. The engine
keeps full-span XLA as the default (decode_attn_kernel=False).

int8-cache variant, MEASURED (r4, same chip, 64 slots, 1024-token
prompts, 256 new): double-buffering (compute block j while j+1
streams) recovered +10% bf16 / +5.5% int8 over single-buffered, and
head-BATCHED matmuls (_flash_update_batched, on by default) a further
+5-7% -- 871 bf16 / 851 int8 tok/s vs 934/987 for XLA full-span where
XLA fits; the remaining gap is pallas_call overhead in the layer scan
plus the block-diagonal redundancy. Where the
kernel WINS is capacity: the XLA int8-KV read materializes a bf16 copy
of the cache as a temp (12.3 GB for a 128-slot Smax=2048 decode block
-- memory_analysis r4), so 128 slots @ 2048 OOMs in every XLA config
('Used 22.24G of 15.75G hbm'); this kernel's VMEM dequant runs it at
1,125 tok/s (SERVING_BENCH.json kv_capacity). The engine rule of
thumb: kv_quant + decode_attn_kernel when the bf16 cache wouldn't fit;
plain XLA otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Cache rows fetched per DMA. 256 rows x KV x D bf16 at KV=8, D=128 is
# 512 KiB -- large enough to amortize DMA issue cost, small enough that
# double-buffering two of them fits VMEM comfortably.
DEFAULT_BLOCK = 256

# Head-batched matmuls (see _flash_update_batched): one MXU op over all
# KV heads instead of KV narrow ones. A/B-gated per CALL: the public
# entry points take batch_heads=None meaning "read the env var now", so
# tests and A/B harnesses can flip KFTPU_DECODE_BATCH_HEADS (or pass the
# kwarg) after import -- an import-time read froze the gate process-wide.
import os as _os


def _batch_heads_default() -> bool:
    return _os.environ.get("KFTPU_DECODE_BATCH_HEADS", "1") != "0"


# jax renamed TPUCompilerParams -> CompilerParams across releases;
# accept either so the kernel imports under both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams",
                           getattr(pltpu, "TPUCompilerParams", None))


def _kernel(pos_ref, q_ref, k_hbm, v_hbm, o_ref,
            k_vmem, v_vmem, sem_k, sem_v, *, block: int,
            batch_heads: bool):
    b = pl.program_id(0)
    span = pos_ref[b] + 1
    nb = pl.cdiv(span, block)
    q = q_ref[0].astype(jnp.float32)            # [KV, G, D]
    kv_heads, g, d = q.shape
    scale = 1.0 / (d ** 0.5)

    # Double-buffered: VMEM scratch carries TWO [block, KV, D] buffers;
    # iteration j computes on buffer j%2 while block j+1 streams into
    # the other -- the DMA latency the single-buffered kernel exposed
    # serially (its measured ~20% deficit vs XLA full-span) overlaps
    # with the flash update.
    def _copies(j, slot):
        return (
            pltpu.make_async_copy(
                k_hbm.at[b, pl.ds(j * block, block)],
                k_vmem.at[slot], sem_k.at[slot]),
            pltpu.make_async_copy(
                v_hbm.at[b, pl.ds(j * block, block)],
                v_vmem.at[slot], sem_v.at[slot]),
        )

    for c in _copies(0, 0):
        c.start()

    def body(j, carry):
        m, l, acc = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nb)
        def _():
            for c in _copies(j + 1, 1 - slot):
                c.start()

        for c in _copies(j, slot):
            c.wait()
        kblk = k_vmem[slot].astype(jnp.float32)  # [block, KV, D]
        vblk = v_vmem[slot].astype(jnp.float32)
        mask = j * block + jax.lax.broadcasted_iota(
            jnp.int32, (g, block), 1
        ) < span
        upd = (_flash_update_batched if batch_heads else _flash_update)
        return upd(q, kblk, vblk, mask, m, l, acc, kv_heads, scale)

    m0 = jnp.full((kv_heads, g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((kv_heads, g, 1), jnp.float32)
    a0 = jnp.zeros((kv_heads, g, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _int8_kernel(pos_ref, q_ref, k_hbm, ks_hbm, v_hbm, vs_hbm, o_ref,
                 k_vmem, ks_vmem, v_vmem, vs_vmem,
                 sem_k, sem_ks, sem_v, sem_vs, *, block: int,
                 batch_heads: bool):
    """int8-cache variant: DMAs int8 rows (HALF the bf16 kernel's HBM
    traffic) plus their [block, KV] f32 scales, dequantizes in VMEM.
    This is the fix for the XLA int8-KV path's materialization: under
    jit the astype+scale of a scan-carried cache materializes a full
    bf16 copy as a temp (measured: 12.3 GB temp for a 128-slot
    Smax=2048 8B-proxy decode block -- worse than the bf16 cache it
    replaced); here the dequant never leaves VMEM."""
    b = pl.program_id(0)
    span = pos_ref[b] + 1
    nb = pl.cdiv(span, block)
    q = q_ref[0].astype(jnp.float32)            # [KV, G, D]
    kv_heads, g, d = q.shape
    scale = 1.0 / (d ** 0.5)

    # Scales arrive [B, KV, Smax] -- since the lane-aligned layout
    # refactor this IS the engine's storage layout (no per-step
    # transpose): Smax as the minor dim makes the [KV, block] slice
    # lane-aligned; a [block, KV] slice of the old [B,Smax,KV] layout
    # is not DMA-able (KV=8 < the 128-lane tile).
    # Double-buffered like _kernel: compute on j%2, stream j+1.
    def _copies(j, slot):
        return (
            pltpu.make_async_copy(
                k_hbm.at[b, pl.ds(j * block, block)],
                k_vmem.at[slot], sem_k.at[slot]),
            pltpu.make_async_copy(
                ks_hbm.at[b, :, pl.ds(j * block, block)],
                ks_vmem.at[slot], sem_ks.at[slot]),
            pltpu.make_async_copy(
                v_hbm.at[b, pl.ds(j * block, block)],
                v_vmem.at[slot], sem_v.at[slot]),
            pltpu.make_async_copy(
                vs_hbm.at[b, :, pl.ds(j * block, block)],
                vs_vmem.at[slot], sem_vs.at[slot]),
        )

    for c in _copies(0, 0):
        c.start()

    def body(j, carry):
        m, l, acc = carry
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < nb)
        def _():
            for c in _copies(j + 1, 1 - slot):
                c.start()

        for c in _copies(j, slot):
            c.wait()
        kblk = (k_vmem[slot].astype(jnp.float32)
                * ks_vmem[slot].T[..., None])   # [block, KV, D]
        vblk = (v_vmem[slot].astype(jnp.float32)
                * vs_vmem[slot].T[..., None])
        mask = j * block + jax.lax.broadcasted_iota(
            jnp.int32, (g, block), 1
        ) < span
        upd = (_flash_update_batched if batch_heads else _flash_update)
        return upd(q, kblk, vblk, mask, m, l, acc, kv_heads, scale)

    m0 = jnp.full((kv_heads, g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((kv_heads, g, 1), jnp.float32)
    a0 = jnp.zeros((kv_heads, g, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_update_batched(q, kblk, vblk, mask, m, l, acc, kv_heads,
                          scale):
    """Head-BATCHED flash update: all KV heads fold into ONE
    [KV*G, D] x [D, KV*block] matmul via the block-diagonal trick --
    the cross-head products are computed (KVx the needed FLOPs) and
    masked away, trading redundant FLOPs for MXU utilization (KV*G=32
    rows per op instead of G=4) and one dot issue instead of KV. Same
    for the probs @ V side, with the probs scattered block-diagonally.
    Numerics identical to _flash_update (verified exact in f32)."""
    blk, _, d = kblk.shape
    g = q.shape[1]
    qa = q.reshape(kv_heads * g, d)
    kcat = kblk.transpose(1, 0, 2).reshape(kv_heads * blk, d)
    s_full = jax.lax.dot_general(
        qa, kcat,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).reshape(kv_heads, g, kv_heads, blk) * scale
    eye = (jax.lax.broadcasted_iota(jnp.int32, (kv_heads, kv_heads), 0)
           == jax.lax.broadcasted_iota(jnp.int32, (kv_heads, kv_heads), 1)
           ).astype(jnp.float32)
    s = (s_full * eye[:, None, :, None]).sum(axis=2)       # [KV, G, blk]
    s = jnp.where(mask[None], s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1, keepdims=True)
    p_full = (p[:, :, None, :] * eye[:, None, :, None]).reshape(
        kv_heads * g, kv_heads * blk
    )
    vcat = vblk.transpose(1, 0, 2).reshape(kv_heads * blk, d)
    pv = jax.lax.dot_general(
        p_full, vcat,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).reshape(kv_heads, g, d)
    return m_new, l_new, acc * alpha + pv


def _flash_update(q, kblk, vblk, mask, m, l, acc, kv_heads, scale):
    """One online-softmax flash-attention update over a dequantized
    [block, KV, D] f32 chunk (shared by the bf16 and int8 kernels).
    Per-KV-head 2D matmuls, python-unrolled: Mosaic rejects the batched
    dot_general form ("batch dims must be equal"). HIGHEST keeps f32
    operands exact (the default would downcast them to bf16)."""
    ms, ls, accs = [], [], []
    for kv in range(kv_heads):
        s = jax.lax.dot_general(
            q[kv], kblk[:, kv, :],              # [G,D] x [block,D]
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        ) * scale                               # [G, block]
        s = jnp.where(mask, s, -jnp.inf)
        m_new = jnp.maximum(m[kv], s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m[kv] - m_new)
        ls.append(l[kv] * alpha + p.sum(axis=-1, keepdims=True))
        pv = jax.lax.dot_general(
            p, vblk[:, kv, :],                  # [G,block] x [block,D]
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )                                       # [G, D]
        ms.append(m_new)
        accs.append(acc[kv] * alpha + pv)
    return jnp.stack(ms), jnp.stack(ls), jnp.stack(accs)


def decode_attention(q, cache_k, cache_v, positions,
                     block: int = DEFAULT_BLOCK,
                     interpret: bool = False,
                     batch_heads: bool | None = None):
    """Bounded-span GQA decode attention over the in-place cache.

    q [B, KV, G, D]; cache_k/v [B, Smax, KV, D]; positions [B].
    Returns [B, KV, G, D] in q's dtype. Smax must be a multiple of
    ``block`` (engine max_seq is a power of two; pad otherwise).
    batch_heads=None reads KFTPU_DECODE_BATCH_HEADS *here*, outside
    jit -- resolving it inside the jitted impl would bake the first
    call's env value into the trace cache and ignore later flips.
    """
    if batch_heads is None:
        batch_heads = _batch_heads_default()
    return _decode_attention_jit(q, cache_k, cache_v, positions,
                                 block=block, interpret=interpret,
                                 batch_heads=batch_heads)


@functools.partial(
    jax.jit, static_argnames=("block", "interpret", "batch_heads")
)
def _decode_attention_jit(q, cache_k, cache_v, positions,
                          block, interpret, batch_heads):
    b, smax, kv_heads, d = cache_k.shape
    if smax % block:
        raise ValueError(f"Smax={smax} not a multiple of block={block}")
    g = q.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, kv_heads, g, d), lambda i, pos: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # cache_k stays HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # cache_v stays HBM
        ],
        out_specs=pl.BlockSpec((1, kv_heads, g, d),
                               lambda i, pos: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, block, kv_heads, d), cache_k.dtype),
            pltpu.VMEM((2, block, kv_heads, d), cache_v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(_kernel, block=block,
                               batch_heads=batch_heads)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
        ),
    )(positions.astype(jnp.int32), q, cache_k, cache_v)


def decode_attention_int8(q, ck_q, ck_s, cv_q, cv_s, positions,
                          block: int = DEFAULT_BLOCK,
                          interpret: bool = False,
                          batch_heads: bool | None = None):
    """Bounded-span GQA decode attention over an int8-quantized cache
    (engine kv_quant="int8": rows int8 [B, Smax, KV, D], scales in the
    engine's lane-aligned STORAGE layout [B, KV, Smax] -- the layout
    contract is asserted below, since a transposed [B, Smax, KV] scale
    would silently dequantize garbage). DMAs int8 rows -- half the bf16
    kernel's cache traffic -- and dequantizes in VMEM, which is the
    only way to read a quantized cache without XLA materializing the
    bf16 copy (see _int8_kernel's docstring for the measured temp
    blowup). batch_heads resolves from the env OUTSIDE jit, like
    decode_attention."""
    b, smax, kv_heads, _ = ck_q.shape
    want = (b, kv_heads, smax)
    if tuple(ck_s.shape) != want or tuple(cv_s.shape) != want:
        raise ValueError(
            "decode_attention_int8: scales must be lane-aligned "
            f"[B, KV, Smax] = {want}; got k {tuple(ck_s.shape)} / "
            f"v {tuple(cv_s.shape)}. The engine stores scales in this "
            "layout (no per-step transpose on the decode path)."
        )
    if batch_heads is None:
        batch_heads = _batch_heads_default()
    return _decode_attention_int8_jit(q, ck_q, ck_s, cv_q, cv_s,
                                      positions, block=block,
                                      interpret=interpret,
                                      batch_heads=batch_heads)


@functools.partial(
    jax.jit, static_argnames=("block", "interpret", "batch_heads")
)
def _decode_attention_int8_jit(q, ck_q, ck_s, cv_q, cv_s, positions,
                               block, interpret, batch_heads):
    b, smax, kv_heads, d = ck_q.shape
    if smax % block:
        raise ValueError(f"Smax={smax} not a multiple of block={block}")
    g = q.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, kv_heads, g, d), lambda i, pos: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # ck_q stays HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # ck_s [B, KV, Smax]
            pl.BlockSpec(memory_space=pltpu.ANY),   # cv_q
            pl.BlockSpec(memory_space=pltpu.ANY),   # cv_s [B, KV, Smax]
        ],
        out_specs=pl.BlockSpec((1, kv_heads, g, d),
                               lambda i, pos: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, block, kv_heads, d), jnp.int8),
            pltpu.VMEM((2, kv_heads, block), jnp.float32),
            pltpu.VMEM((2, block, kv_heads, d), jnp.int8),
            pltpu.VMEM((2, kv_heads, block), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(_int8_kernel, block=block,
                               batch_heads=batch_heads)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
        ),
    )(positions.astype(jnp.int32), q, ck_q, ck_s, cv_q, cv_s)
