"""Pallas TPU decode attention: bounded-span KV-cache reads.

The serving engine's decode step attends over the FULL [Smax] cache slab
every step at every context length -- bounding the span in XLA (attend
``ck[:, :klen]``) regressed ~5x because slicing the scan-carried cache
materializes a per-layer copy instead of fusing into the attention reads
(measured 2026-07-30, note in serving/engine.py:_decode). This kernel is
the fix that note prescribes: the cache stays IN PLACE in HBM, and the
kernel manually DMAs only ceil(span/block) key/value blocks per slot into
VMEM, so HBM traffic scales with the LIVE context, not Smax.

Shapes (one layer's slice of the engine cache, layout unchanged):
  q         [B, KV, G, D]   query heads grouped under their KV head
  cache_k/v [B, Smax, KV, D]
  positions [B]             query position per slot (span = pos + 1)
  -> out    [B, KV, G, D]

Grid = (B,): per slot, a fori_loop with DATA-DEPENDENT trip count
cdiv(span, block) runs online-softmax flash attention over contiguous
[block, KV, D] cache chunks (the Smax dimension is the contiguous one,
so each DMA is one dense HBM burst). Rows past ``span`` in the final
block are masked; rows past a slot's span hold garbage by the engine's
masked-until-overwritten invariant, which this mask re-implements.

Numerics match ops.attention/xla paths: f32 scores and softmax
accumulation, output cast to the cache dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Cache rows fetched per DMA. 256 rows x KV x D bf16 at KV=8, D=128 is
# 512 KiB -- large enough to amortize DMA issue cost, small enough that
# double-buffering two of them fits VMEM comfortably.
DEFAULT_BLOCK = 256


def _kernel(pos_ref, q_ref, k_hbm, v_hbm, o_ref,
            k_vmem, v_vmem, sem_k, sem_v, *, block: int, smax: int):
    b = pl.program_id(0)
    span = pos_ref[b] + 1
    nb = pl.cdiv(span, block)
    q = q_ref[0].astype(jnp.float32)            # [KV, G, D]
    kv_heads, g, d = q.shape
    scale = 1.0 / (d ** 0.5)

    def body(j, carry):
        m, l, acc = carry
        ck = pltpu.make_async_copy(
            k_hbm.at[b, pl.ds(j * block, block)], k_vmem, sem_k
        )
        cv = pltpu.make_async_copy(
            v_hbm.at[b, pl.ds(j * block, block)], v_vmem, sem_v
        )
        ck.start()
        cv.start()
        ck.wait()
        cv.wait()
        kblk = k_vmem[...].astype(jnp.float32)  # [block, KV, D]
        vblk = v_vmem[...].astype(jnp.float32)
        # scores [KV, G, block]: contract D per KV head. HIGHEST keeps
        # f32 operands exact (the default would downcast them to bf16);
        # production bf16 caches are unaffected.
        s = jax.lax.dot_general(
            q, kblk,
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        ) * scale
        idx = j * block + jax.lax.broadcasted_iota(
            jnp.int32, (kv_heads, g, block), 2
        )
        s = jnp.where(idx < span, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                  # [KV, G, block]
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, vblk,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )                                       # [KV, G, D]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((kv_heads, g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((kv_heads, g, 1), jnp.float32)
    a0 = jnp.zeros((kv_heads, g, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block", "interpret")
)
def decode_attention(q, cache_k, cache_v, positions,
                     block: int = DEFAULT_BLOCK,
                     interpret: bool = False):
    """Bounded-span GQA decode attention over the in-place cache.

    q [B, KV, G, D]; cache_k/v [B, Smax, KV, D]; positions [B].
    Returns [B, KV, G, D] in q's dtype. Smax must be a multiple of
    ``block`` (engine max_seq is a power of two; pad otherwise).
    """
    b, smax, kv_heads, d = cache_k.shape
    if smax % block:
        raise ValueError(f"Smax={smax} not a multiple of block={block}")
    g = q.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, kv_heads, g, d), lambda i, pos: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # cache_k stays HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # cache_v stays HBM
        ],
        out_specs=pl.BlockSpec((1, kv_heads, g, d),
                               lambda i, pos: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((block, kv_heads, d), cache_k.dtype),
            pltpu.VMEM((block, kv_heads, d), cache_v.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = functools.partial(_kernel, block=block, smax=smax)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
    )(positions.astype(jnp.int32), q, cache_k, cache_v)
