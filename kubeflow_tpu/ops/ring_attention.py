"""Ring attention: context parallelism over the ``sequence`` mesh axis.

Long-context training shards the sequence dimension across devices
(SURVEY.md 5.7). GSPMD alone would all-gather K/V for the attention
einsum -- O(S) memory spike per device, defeating the point of sharding.
Ring attention instead keeps K/V sharded and rotates blocks around the
``sequence`` axis with ``ppermute`` (ICI neighbor traffic), accumulating
the softmax online exactly as flash attention does across tiles:

    step s: device r attends its local Q block against the K/V block
    originally owned by device (r - s) mod n, then passes K/V to r+1.

Compute and the collective permute overlap on TPU (async collectives), so
the ring costs ~one K/V block of HBM and hides the wire time behind the
per-block matmuls.

Causality is exact across blocks: masks are built from *global* positions
(block_index * block_len + offset), so a fully-masked future block simply
contributes zero probability mass (the online-softmax ``where`` keeps
those rows finite).

Entry points:
- ``ring_attention``         -- per-shard body; call inside shard_map.
- ``ring_attention_sharded`` -- shard_map wrapper over a mesh; drop-in for
  ``xla_attention`` on [B, S, H, D] global arrays.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.compat import axis_size, shard_map
from kubeflow_tpu.ops.attention import _repeat_kv

_NEG_INF = -1e30  # finite "minus infinity": exp() underflows cleanly


def ring_attention(
    q: jax.Array,  # [B, Sq_local, H, D]
    k: jax.Array,  # [B, Sk_local, Hkv, D]
    v: jax.Array,  # [B, Sk_local, Hkv, D]
    axis_name: str = "sequence",
    causal: bool = True,
) -> jax.Array:
    """Per-shard ring attention; must run inside shard_map over
    ``axis_name``. Local blocks are contiguous slices of the global
    sequence in axis order (device r owns positions [r*C, (r+1)*C))."""

    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    # GQA expansion happens per-block INSIDE the loop: the ppermute carry
    # rotates the narrow [.., Hkv, D] blocks, so the wire/HBM cost keeps
    # GQA's n_rep-fold savings.
    n_rep = q.shape[2] // k.shape[2]

    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    q32 = q.astype(jnp.float32)

    q_pos = my_idx * sq + jnp.arange(sq)  # global query positions

    # Online-softmax state (fp32): running max, normalizer, weighted sum.
    # Derived from 0*q (not jnp.zeros): fresh constants are device-INvariant
    # under shard_map's varying-axes tracking, but the loop writes
    # device-varying values into them and fori_loop requires carry types to
    # agree; inheriting q's variance sidesteps hand-listing mesh axes.
    zero_bhq = 0.0 * q32[..., 0].transpose(0, 2, 1)  # [B, H, Sq]
    m0 = zero_bhq + _NEG_INF
    l0 = zero_bhq
    acc0 = 0.0 * q32

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        k_blk, v_blk, m, l, acc = carry
        src = (my_idx - s) % n  # original owner of the block now held
        k_use = _repeat_kv(k_blk, n_rep).astype(jnp.float32)
        v_use = _repeat_kv(v_blk, n_rep).astype(jnp.float32)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_use) * scale
        if causal:
            k_pos = src * sk + jnp.arange(sk)
            visible = k_pos[None, :] <= q_pos[:, None]  # [Sq, Sk] global causal
            scores = jnp.where(visible[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # where (not bare exp): when every key so far is masked, m_new is
        # still _NEG_INF and exp(scores - m_new) would be exp(0)=1 for
        # masked entries -- probability mass out of thin air.
        p = jnp.where(
            scores > _NEG_INF / 2, jnp.exp(scores - m_new[..., None]), 0.0
        )
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_use
        )
        # Rotate K/V to the next device; skip the final (useless) hop.
        k_blk, v_blk = jax.lax.cond(
            s < n - 1,
            lambda kv: tuple(
                jax.lax.ppermute(x, axis_name, perm) for x in kv
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return k_blk, v_blk, m_new, l_new, acc_new

    _, _, _, l, acc = jax.lax.fori_loop(0, n, body, (k, v, m0, l0, acc0))
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,  # [B, S, H, D] global
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "sequence",
    batch_axes=None,
    head_axis: str = "tensor",
) -> jax.Array:
    """shard_map wrapper: global [B, S, H, D] arrays -> ring attention with
    S sharded over ``axis_name``, heads over ``head_axis``, batch over
    ``batch_axes`` (default: the rules table's batch axes, so the ring's
    layout always agrees with DEFAULT_RULES). Falls through to the
    per-shard body with n=1 when the sequence axis is trivial."""

    if batch_axes is None:
        from kubeflow_tpu.parallel.sharding import DEFAULT_RULES

        batch_axes = DEFAULT_RULES["batch"]
    qspec = P(batch_axes, axis_name, head_axis, None)
    fn = partial(ring_attention, axis_name=axis_name, causal=causal)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec,
    )(q, k, v)
