"""Ulysses-style all-to-all sequence parallelism.

The second of the two standard context-parallel schemes (the other is
ring attention, ops/ring_attention.py):

- Activations arrive sequence-sharded ([B, S/n, H, D] per device).
- One ``all_to_all`` re-shards heads instead of sequence
  ([B, S, H/n, D]): every device then holds the FULL sequence for a
  subset of heads, so plain (flash) attention runs locally with exact
  causal semantics and no per-step communication.
- A second ``all_to_all`` restores the sequence layout.

Trade-off vs the ring: Ulysses moves Q/K/V/O once per layer over
all-to-all (great on ICI's bisection bandwidth) but needs
``n_heads % n == 0`` (untileable KV head counts are broadcast to the
query width first; tileable ones ride at native width); the ring
has no head constraint but overlaps compute with P2P transfers. Pick per
model geometry: ``attention_impl="ulysses"`` opts in.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.compat import axis_size, shard_map


def _local_attention(q, k, v, causal):
    from kubeflow_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=causal)


def ulysses_attention(
    q: jax.Array,  # [B, S/n, H, D] per device (sequence-sharded)
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    axis_name: str = "sequence",
) -> jax.Array:
    """Per-shard body (already inside shard_map over ``axis_name``)."""
    n = axis_size(axis_name)
    if n == 1:
        return _local_attention(q, k, v, causal)
    # seq-sharded -> head-sharded: split heads, gather sequence.
    a2a = partial(
        jax.lax.all_to_all, axis_name=axis_name,
        split_axis=2, concat_axis=1, tiled=True,
    )
    qh, kh, vh = a2a(q), a2a(k), a2a(v)  # [B, S, H/n, D]
    out = _local_attention(qh, kh, vh, causal)
    # head-sharded -> seq-sharded: split sequence, gather heads.
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_shardable(q: jax.Array, k: jax.Array, mesh: Mesh) -> bool:
    """Exact-tiling gate for the global [B, S, H, D] arrays.

    Only the query head count matters: K/V are broadcast to it whenever
    their own heads would not tile (ulysses_attention_sharded), so if q
    tiles, the wrapper can always make K/V tile.
    """
    from kubeflow_tpu.ops.attention import _cp_shardable_base

    n = mesh.shape.get("sequence", 1)
    heads_ax = mesh.shape.get("tensor", 1)
    return (
        _cp_shardable_base(q, k, mesh)
        and q.shape[2] % heads_ax == 0
        and (q.shape[2] // heads_ax) % n == 0
    )


def ulysses_attention_sharded(
    q: jax.Array,  # [B, S, H, D] global
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "sequence",
    batch_axes=None,
    head_axis: str = "tensor",
) -> jax.Array:
    """shard_map wrapper: S sharded over ``axis_name``, heads over
    ``head_axis``, batch over the rules table's batch axes.

    GQA: narrow K/V ride the all_to_all at their native width whenever
    they tile (the per-layer all-to-all is Ulysses' whole cost; the
    local flash kernel broadcasts KV heads itself). Only untileable KV
    head counts are broadcast to the query width first.
    """
    if batch_axes is None:
        from kubeflow_tpu.parallel.sharding import DEFAULT_RULES

        batch_axes = DEFAULT_RULES["batch"]
    n = mesh.shape[axis_name]
    heads_ax = mesh.shape.get(head_axis, 1)
    kv = k.shape[2]
    kv_tiles = kv % heads_ax == 0 and (kv // heads_ax) % n == 0
    if not kv_tiles and q.shape[2] != kv:
        from kubeflow_tpu.ops.attention import _repeat_kv

        n_rep = q.shape[2] // kv
        k = _repeat_kv(k, n_rep)
        v = _repeat_kv(v, n_rep)
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = partial(ulysses_attention, causal=causal, axis_name=axis_name)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
