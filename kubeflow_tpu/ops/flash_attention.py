"""Pallas TPU flash attention.

Tiled online-softmax attention (forward + backward kernels) via
``jax.experimental.pallas.ops.tpu.flash_attention`` -- O(S) HBM traffic
instead of materializing the S x S score matrix. GQA is handled by
broadcasting KV heads to the query head count before the kernel (K/V are
small relative to scores; the broadcast is fused by XLA).

Layout contract matches kubeflow_tpu.ops.attention: [B, S, H, D] in/out
(the kernel itself wants [B, H, S, D]). Falls back to XLA attention off
TPU or for shapes the kernel cannot tile; callers go through
``dot_product_attention(impl="auto")`` which also gates on seq length.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

# Tiling floor: the kernel wants 128-multiples in seq and head_dim.
_MIN_BLOCK = 128


@functools.cache
def _kernel():
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    return fa


def _block_sizes(seq_q: int, seq_k: int, block: Optional[int] = None):
    fa = _kernel()
    # Largest 128-multiple <= 512 dividing both seqs (the kernel requires
    # exact tiling; e.g. seq 640 must use 128, not 512). An explicit
    # ``block`` (the tuner's knob) caps the choice instead of replacing
    # it, so an untileable request degrades to the best legal tile
    # rather than a kernel error.
    cands = (512, 384, 256, 128)
    if block is not None:
        cands = tuple(c for c in cands if c <= block) or (128,)
    b = next(c for c in cands if seq_q % c == 0 and seq_k % c == 0)
    return fa.BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=b, block_k_major_dkv=b, block_k_dkv=b,
        block_q_dkv=b,
        block_k_major_dq=b, block_k_dq=b, block_q_dq=b,
    )


def flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    block: Optional[int] = None,
) -> jax.Array:
    from kubeflow_tpu.ops.attention import xla_attention

    n_rep = q.shape[2] // k.shape[2]
    if (
        jax.default_backend() != "tpu"
        # Self-attention only: the kernel's causal mask is zero-aligned,
        # xla_attention tail-aligns Sq < Sk (decode/chunked prefill) --
        # different semantics, same guard as the ring path.
        or q.shape[1] != k.shape[1]
        or q.shape[1] < _MIN_BLOCK
        or q.shape[1] % _MIN_BLOCK
        or q.shape[-1] % _MIN_BLOCK
    ):
        return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    fa = _kernel()
    if n_rep > 1:
        from kubeflow_tpu.ops.attention import _repeat_kv

        k = _repeat_kv(k, n_rep)
        v = _repeat_kv(v, n_rep)
    # [B, S, H, D] -> [B, H, S, D]
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    seg = None
    if segment_ids is not None:
        seg = fa.SegmentIds(q=segment_ids, kv=segment_ids)
    out = fa.flash_attention(
        qt, kt, vt,
        causal=causal,
        segment_ids=seg,
        sm_scale=1.0 / (q.shape[-1] ** 0.5),
        block_sizes=_block_sizes(q.shape[1], k.shape[1], block),
    )
    return out.transpose(0, 2, 1, 3)
