"""Pallas TPU flash attention (placeholder until the kernel milestone).

Falls back to XLA attention; replaced by the tiled online-softmax Pallas
kernel in the long-context milestone.
"""

from __future__ import annotations

from typing import Optional

import jax


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    from kubeflow_tpu.ops.attention import xla_attention

    return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
