"""Attention kernels.

``dot_product_attention`` is the single entry point; it dispatches to:

- ``xla``: plain einsum attention -- correct everywhere (CPU tests), XLA
  fuses softmax; O(S^2) memory.
- ``flash``: Pallas TPU flash attention (tiled online-softmax, O(S) HBM
  traffic) -- used on TPU for long sequences.

GQA (grouped-query attention) is supported natively: K/V have
``n_kv_heads`` heads, queries have ``n_heads``; kv heads are broadcast in
groups of ``n_heads // n_kv_heads``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] broadcasting kv heads."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d))
    return x.reshape(b, s, h * n_rep, d)


def xla_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,  # [B, Sk, Hkv, D]
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    depth = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(depth).astype(q.dtype)
    sq, sk = q.shape[1], k.shape[1]
    if causal:
        # Offset supports decode (Sq < Sk with query at the tail).
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(mask[None, None], scores, jnp.finfo(scores.dtype).min)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        scores = jnp.where(
            seg_mask[:, None, -sq:, :], scores, jnp.finfo(scores.dtype).min
        )
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    impl: str = "auto",
    flash_block: Optional[int] = None,
) -> jax.Array:
    """Attention entry point. impl: auto | xla | flash | ring | ulysses.
    ``flash_block`` caps the flash kernel's tile size (tuner knob; None
    keeps the kernel's largest-legal-tile default, other impls ignore
    it).

    ``ring`` shards the sequence dim over the mesh's ``sequence`` axis via
    shard_map + ppermute (context parallelism); ``ulysses`` uses one
    all-to-all per direction to re-shard heads instead (needs the
    per-tensor-shard head count divisible by the sequence axis). ``auto``
    picks the ring whenever the
    active mesh has a non-trivial sequence axis, because otherwise GSPMD
    would all-gather K/V for the S x S einsum.
    """
    if impl == "ulysses":
        from kubeflow_tpu.parallel.mesh import active_mesh
        from kubeflow_tpu.ops.ulysses import (
            ulysses_attention_sharded,
            ulysses_shardable,
        )

        mesh = active_mesh()
        if (
            mesh is not None
            and mesh.shape.get("sequence", 1) > 1
            and segment_ids is None
            and not _inside_manual_region()
            and ulysses_shardable(q, k, mesh)
        ):
            return ulysses_attention_sharded(q, k, v, mesh, causal=causal)
        # Untileable for Ulysses: fall through to auto, which may still
        # pick the ring (no head constraint) before plain attention.
        impl = "auto"
    if impl in ("auto", "ring"):
        from kubeflow_tpu.parallel.mesh import active_mesh

        mesh = active_mesh()
        # Segment packing across a ring is not implemented; packed batches
        # fall back to GSPMD attention (correct, just not ring-overlapped).
        # Shapes that don't divide the mesh (e.g. the batch-1 dummy of
        # model.init traces) also fall back.
        seq_parallel = (
            mesh is not None
            and "sequence" in mesh.shape
            and mesh.shape["sequence"] > 1
            and segment_ids is None
            and _ring_shardable(q, k, mesh)
            and not _inside_manual_region()
        )
        if impl == "ring" or seq_parallel:
            if not seq_parallel:
                # ring requested but no sequence axis: plain attention is
                # the n=1 special case of the ring.
                return xla_attention(q, k, v, causal=causal,
                                     segment_ids=segment_ids)
            from kubeflow_tpu.ops.ring_attention import ring_attention_sharded

            return ring_attention_sharded(q, k, v, mesh, causal=causal)
    if impl == "auto":
        impl = "flash" if _flash_available(q) else "xla"
    if impl == "flash":
        from kubeflow_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids, block=flash_block)
    return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)


def _inside_manual_region() -> bool:
    """The ring's own full-mesh shard_map cannot nest inside a manual
    region (e.g. the gpipe pipeline body), so auto dispatch falls back
    to GSPMD attention (correct; K/V all-gathered within the stage)."""
    from kubeflow_tpu.compat import inside_manual_region

    return inside_manual_region()


def _cp_shardable_base(q: jax.Array, k: jax.Array, mesh) -> bool:
    """Tiling preconditions shared by every context-parallel scheme
    (ring, Ulysses): self-attention shapes only (zero-aligned causal
    masks; xla_attention tail-aligns decode masks -- different
    semantics), batch divisible by the batch axes, sequence divisible by
    the sequence axis."""
    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES

    batch = 1
    for ax in DEFAULT_RULES["batch"]:
        batch *= mesh.shape.get(ax, 1)
    return (
        q.shape[1] == k.shape[1]
        and q.shape[0] % batch == 0
        and q.shape[1] % mesh.shape["sequence"] == 0
    )


def _ring_shardable(q: jax.Array, k: jax.Array, mesh) -> bool:
    heads = mesh.shape.get("tensor", 1)
    return (
        _cp_shardable_base(q, k, mesh)
        and q.shape[2] % heads == 0
        and k.shape[2] % heads == 0
    )


def _flash_available(q: jax.Array) -> bool:
    if jax.default_backend() != "tpu":
        return False
    # Flash tiles need seq multiples of the block size; fall back otherwise.
    return q.shape[1] >= 128 and q.shape[1] % 128 == 0
