"""int8 (AQT-style) training matmuls for the v5e MXU.

The round-4 profile pinned the training plateau on the matmuls
themselves (73-77% of device time at ~87% of their own bf16 roofline);
the one untried lever the trace left open is the MXU's 2x int8
throughput (394.9 vs 197.4 TOP/s on v5e). This module is that lever:
a drop-in ``dot_general`` for ``flax.linen.DenseGeneral`` that

- dynamically quantizes both operands symmetric-int8 with per-row /
  per-column scales over the CONTRACTING dims (AQT's "dynamic
  quantization" recipe -- no calibration state to carry),
- runs the dot as int8 x int8 -> int32 (``preferred_element_type``),
  which XLA lowers onto the int8 MXU path,
- rescales the int32 accumulator by the outer product of the scales,
- and backpropagates STRAIGHT-THROUGH: the custom_vjp's backward is the
  exact bf16 dot_general vjp, so gradients are what the unquantized
  layer would produce (dgrad/wgrad FLOPs stay bf16 -- this measures the
  FORWARD int8 win first; quantizing the backward only makes sense if
  the forward shows one).

Used by ``LlamaConfig(int8_matmul=True)`` -> the BENCH_INT8_MM A/B in
bench.py (fresh-process pair, same batch).

MEASURED (2026-07-31, v5e, 8B-proxy, batch 4 x seq 1024, fresh
subprocess per side): **negative result -- parity.** 9,167 int8 vs
9,121 bf16 tokens/s/chip (ratio 1.005, far inside the tunnel's spread)
at exact loss parity (12.263 both). Why the 2x MXU peak doesn't show:
(1) the dynamic-quant prologue is pure HBM-bound elementwise work --
absmax-reduce + round + clip over BOTH operands every matmul, with the
weights re-quantized every step because they train; (2) the int8
operand copies + f32 absmax/rescale temps add ~1 GB of program memory
("Used 16.74G of 15.75G" at the headline batch 5 -- the A/B runs at
batch 4 for this reason), costing batch headroom; (3) the backward
stays bf16 by design (STE), capping the theoretical win at the
forward's ~1/3 share of matmul FLOPs. A real win here needs static
(calibrated) weight scales carried in the train state so the weight
quantization leaves the step, plus an int8 backward -- recorded as the
follow-up, not attempted blind.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _q8(x, contract_dims):
    """Symmetric int8 with scales over the contracting dims."""
    a = jnp.abs(x.astype(jnp.float32))
    amax = jnp.max(a, axis=contract_dims, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


def _q8_forward(lhs, rhs, dimension_numbers, out_dtype):
    (lc, rc), (lb, rb) = dimension_numbers
    if lb or rb:
        raise NotImplementedError("q8_dot_general: no batch dims "
                                  "(DenseGeneral never passes any)")
    lq, ls = _q8(lhs, tuple(lc))
    rq, rs = _q8(rhs, tuple(rc))
    y = lax.dot_general(lq, rq, dimension_numbers,
                        preferred_element_type=jnp.int32)
    # Output layout = lhs free dims then rhs free dims; the kept-dims
    # scales squeeze onto exactly those axes.
    ls_free = jnp.squeeze(ls, axis=tuple(lc))
    rs_free = jnp.squeeze(rs, axis=tuple(rc))
    scale = ls_free.reshape(ls_free.shape + (1,) * rs_free.ndim) * rs_free
    return (y.astype(jnp.float32) * scale).astype(out_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _q8_dg(lhs, rhs, dimension_numbers, out_dtype):
    return _q8_forward(lhs, rhs, dimension_numbers, out_dtype)


def _q8_dg_fwd(lhs, rhs, dimension_numbers, out_dtype):
    return _q8_forward(lhs, rhs, dimension_numbers, out_dtype), (lhs, rhs)


def _q8_dg_bwd(dimension_numbers, out_dtype, res, g):
    lhs, rhs = res

    def ref(l, r):
        return lax.dot_general(l, r, dimension_numbers)

    _, vjp = jax.vjp(ref, lhs, rhs)
    dl, dr = vjp(g.astype(lhs.dtype))
    return dl, dr


_q8_dg.defvjp(_q8_dg_fwd, _q8_dg_bwd)


def q8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                   preferred_element_type=None):
    """flax ``DenseGeneral(dot_general=...)``-compatible signature.
    precision/preferred_element_type from the caller are ignored: the
    quantized path fixes int32 accumulation and returns the layer's
    compute dtype (bf16 in training)."""
    out_dtype = jnp.result_type(lhs, rhs)
    return _q8_dg(lhs, rhs, dimension_numbers, out_dtype)
