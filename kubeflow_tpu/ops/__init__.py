"""TPU-friendly ops: attention, rotary embeddings, norms.

The hot-path building blocks for the model zoo. Everything here is written
to map onto the MXU (large batched matmuls, bf16) and to let XLA fuse the
elementwise epilogues; the Pallas flash-attention kernel is selected at
runtime when available (SURVEY.md 7.4 #2).
"""

from kubeflow_tpu.ops.attention import dot_product_attention  # noqa: F401
