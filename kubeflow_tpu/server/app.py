"""aiohttp control-plane server.

Routes (kind is a CRD-like name: JAXJob, TFJob, ..., Experiment,
InferenceService):

- ``POST   /apis/{kind}``                 apply (defaulted + validated)
- ``GET    /apis/{kind}``                 list (?namespace=)
- ``GET    /apis/{kind}/{ns}/{name}``     get
- ``DELETE /apis/{kind}/{ns}/{name}``     delete
- ``GET    /logs/{ns}/{name}``            worker log (?replica=worker-0)
- ``GET    /events/{ns}/{name}``          events for an object
- ``GET    /healthz``, ``GET /metrics``   liveness + control-plane metrics

Validation/defaulting happens server-side on POST, mirroring the
reference's admission webhooks: the stored spec is always complete.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import time
from typing import Optional

from aiohttp import web

from kubeflow_tpu.api import TrainJob, apply_defaults, validate_job
from kubeflow_tpu.api.types import JobKind
from kubeflow_tpu.api.validation import ValidationError
from kubeflow_tpu.controller import (
    ControllerLease,
    GangScheduler,
    JobController,
    ProcessLauncher,
    RuntimeJournal,
    TelemetryPlane,
)
from kubeflow_tpu.hpo import HPOController
from kubeflow_tpu.hpo.obsdb import ObservationDB
from kubeflow_tpu.hpo.types import Experiment, validate_experiment
from kubeflow_tpu.obs import registry as obs_registry
from kubeflow_tpu.server import webapps as _webapps
from kubeflow_tpu.platform import (
    PlatformValidationError,
    PodDefault,
    Profile,
    apply_pod_defaults,
    validate_pod_default,
    validate_profile,
)
from kubeflow_tpu.pipelines import (
    Pipeline,
    PipelineController,
    PipelineValidationError,
    validate_pipeline,
)
from kubeflow_tpu.platform.controller import PlatformController
from kubeflow_tpu.platform.kfam import AccessManager
from kubeflow_tpu.platform.workbench import (
    Notebook,
    Tensorboard,
    WorkbenchController,
    validate_notebook,
    validate_tensorboard,
)
from kubeflow_tpu.serving.controller import Activator, ISVCController
from kubeflow_tpu.serving.graph import (
    GRAPH_KIND,
    GraphRouter,
    GraphValidationError,
    InferenceGraph,
    validate_graph,
)
from kubeflow_tpu.serving.types import (
    InferenceService,
    ServingValidationError,
    validate_isvc,
)
from kubeflow_tpu.store import ObjectStore

logger = logging.getLogger(__name__)

JOB_KINDS = {k.value for k in JobKind}


class ControlPlane:
    """Store + controllers + HTTP app, one event loop."""

    def __init__(
        self,
        state_dir: str,
        total_chips: int = 8,
        launcher: Optional[object] = None,
    ) -> None:
        os.makedirs(state_dir, exist_ok=True)
        self.state_dir = state_dir
        self.store = ObjectStore(os.path.join(state_dir, "state.db"))
        self.log_dir = os.path.join(state_dir, "logs")
        self.launcher = launcher or ProcessLauncher(log_dir=self.log_dir)
        self.gang = GangScheduler(total_chips=total_chips)
        # Crash resilience (docs/CONTROLPLANE.md): the journal shadows live
        # runtimes into the store so a restarted control plane adopts its
        # orphaned workers instead of respawning them; the lease fences
        # actuation to one controller process at a time (a standby blocks
        # in run() until the incumbent's lease expires).
        self.journal = RuntimeJournal(self.store)
        self.lease = ControllerLease(
            self.store,
            duration_seconds=float(
                os.environ.get("KFTPU_LEASE_SECONDS", "15")
            ),
        )
        # Fleet telemetry plane: the controller's scrape loop feeds the
        # bounded series store; burn-rate alerts push shed pressure onto
        # the matching serving router (registered below, after isvc).
        self.telemetry = TelemetryPlane()
        self.controller = JobController(
            self.store, self.launcher, self.gang, log_dir=self.log_dir,
            journal=self.journal, lease=self.lease,
            telemetry=self.telemetry,
        )
        self.obs_db = ObservationDB(os.path.join(state_dir, "observations.db"))
        self.hpo = HPOController(
            self.store, log_dir=self.log_dir, obs_db=self.obs_db
        )
        self.isvc = ISVCController(
            self.store, self.launcher, log_dir=self.log_dir,
            state_dir=state_dir, gang=self.gang,
            on_capacity_released=self.controller.kick_pending,
        )
        self.activator = Activator(self.isvc)
        self.platform = PlatformController(
            self.store, self.gang, job_controller=self.controller
        )
        self.pipelines = PipelineController(
            self.store,
            artifacts_dir=os.path.join(state_dir, "artifacts"),
        )
        self.workbench = WorkbenchController(
            self.store, self.launcher, log_dir=self.log_dir
        )
        # KFAM-equivalent authz (P7): enforced when auth_enabled (or env
        # KFTPU_AUTH=1); identity comes from the X-Kftpu-User header.
        self.access = AccessManager(
            self.store, admin=os.environ.get("KFTPU_ADMIN", "admin")
        )
        self.auth_enabled = os.environ.get("KFTPU_AUTH", "") == "1"

        # Worker exits fan out: serving replicas first (on_worker_exit
        # returns False for non-server workers), then training jobs. Bound
        # to the controllers directly -- independent of who called
        # set_exit_callback first.
        async def dispatch_exit(ref, code):
            if await self.isvc.on_worker_exit(ref, code):
                return
            if await self.workbench.on_worker_exit(ref, code):
                return
            await self.controller._on_worker_exit(ref, code)

        self.launcher.set_exit_callback(dispatch_exit)

        # Burn-rate alert -> router shed pressure: when the alerting job
        # key names an InferenceService, tighten its router's effective
        # TTFT shed threshold for the duration of the alert.
        def slo_pressure(job_key: str, active: bool) -> None:
            router = self.isvc._routers.get(job_key)
            if router is not None:
                router.set_slo_pressure(active)

        self.telemetry.pressure_callbacks.append(slo_pressure)
        self.extra_controllers: list = [
            self.hpo, self.isvc, self.platform, self.pipelines,
            self.workbench,
        ]
        self._tasks: list[asyncio.Task] = []
        self.started_at = time.time()

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        self._tasks.append(asyncio.create_task(self.controller.run()))
        for c in self.extra_controllers:
            self._tasks.append(asyncio.create_task(c.run()))

    async def stop(self) -> None:
        for c in self.extra_controllers:
            stop = getattr(c, "stop", None)
            if stop:
                await stop()
        await self.controller.stop()
        for t in self._tasks:
            try:
                await asyncio.wait_for(t, 5)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                t.cancel()
        self.obs_db.close()
        self.store.close()

    # -- HTTP app ---------------------------------------------------------

    def build_app(self) -> web.Application:
        # Sized to match ModelServer's limit: the activator proxies predict
        # bodies, so the ingress must accept what the replicas accept.
        middlewares = [self._auth_middleware] if self.auth_enabled else []
        app = web.Application(
            client_max_size=256 * 1024 * 1024, middlewares=middlewares
        )
        app.add_routes(
            [
                web.post("/apis/{kind}", self.h_apply),
                web.get("/apis/{kind}", self.h_list),
                web.get("/apis/{kind}/{ns}/{name}", self.h_get),
                web.delete("/apis/{kind}/{ns}/{name}", self.h_delete),
                web.get("/logs/{ns}/{name}", self.h_logs),
                web.get("/events/{ns}/{name}", self.h_events),
                web.get("/observations/{ns}/{name}", self.h_observations),
                web.get("/healthz", self.h_healthz),
                web.get("/metrics", self.h_metrics),
                web.get("/debug/trace", self.h_debug_trace),
                web.get("/debug/series", self.h_debug_series),
                # Central-dashboard equivalent (P5): one page over /apis/.
                web.get("/dashboard", self.h_dashboard),
                web.get("/", self.h_dashboard),
                # Per-resource CRUD web apps (P6): notebooks /
                # tensorboards / volumes, one focused app each over the
                # same /apis routes (server/webapps.py).
                web.get("/apps/{app}", _webapps.handle_app),
                # Katib-UI-equivalent experiment drill-down (K8): trial
                # table + objective plot for one experiment.
                web.get("/dashboard/isvc/{ns}/{name}",
                        self.h_isvc_detail),
                web.get("/dashboard/experiment/{ns}/{name}",
                        self.h_experiment_detail),
                # Pipeline drill-down (P9's run view): per-step/expansion
                # phases, retries, outputs, conditions.
                web.get("/dashboard/pipeline/{ns}/{name}",
                        self.h_pipeline_detail),
                # KFAM-equivalent access management API (P7).
                web.get("/kfam/v1/bindings", self.h_kfam_list),
                web.post("/kfam/v1/bindings", self.h_kfam_add),
                web.delete("/kfam/v1/bindings", self.h_kfam_delete),
                # Activator: data-plane ingress for InferenceServices.
                web.route("*", "/serving/{ns}/{name}/{tail:.*}",
                          self.activator.handle),
                # InferenceGraph ingress: composes ISVCs per request.
                web.post("/graphs/{ns}/{name}", self.h_graph_infer),
            ]
        )

        async def on_startup(app):
            await self.start()

        async def on_cleanup(app):
            await self.stop()

        app.on_startup.append(on_startup)
        app.on_cleanup.append(on_cleanup)
        return app

    # -- handlers ---------------------------------------------------------

    async def h_apply(self, req: web.Request) -> web.Response:
        kind = req.match_info["kind"]
        if "parsed_json" in req:  # auth middleware already parsed it
            obj = req["parsed_json"]
        else:
            try:
                obj = await req.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "body is not JSON"}, status=400
                )
        if not isinstance(obj, dict):
            return web.json_response(
                {"error": "body must be a JSON object"}, status=400
            )

        def parse_job(o):
            # Mutating-webhook analog: PodDefaults first, then defaulting
            # and validation on the mutated spec (reference's P4 ordering).
            o = apply_pod_defaults(self.store, o)
            job = apply_defaults(TrainJob.from_dict(o))
            validate_job(job)
            return job.to_dict()

        def parse_experiment(o):
            exp = Experiment.from_dict(o)
            validate_experiment(exp)
            return exp.to_dict()

        def parse_isvc(o):
            isvc = InferenceService.from_dict(o)
            validate_isvc(isvc)
            return isvc.to_dict()

        def parse_trained_model(o):
            from kubeflow_tpu.serving.types import (
                TrainedModel,
                validate_trained_model,
            )

            tm = TrainedModel.from_dict(o)
            validate_trained_model(tm)
            return tm.to_dict()

        def parse_profile(o):
            prof = Profile.from_dict(o)
            validate_profile(prof)
            return prof.to_dict()

        def parse_pod_default(o):
            pd = PodDefault.from_dict(o)
            validate_pod_default(pd)
            return pd.to_dict()

        def parse_pipeline(o):
            pl = Pipeline.from_dict(o)
            validate_pipeline(pl)
            return pl.to_dict()

        def parse_notebook(o):
            nb = Notebook.from_dict(o)
            validate_notebook(nb)
            return nb.to_dict()

        def parse_tensorboard(o):
            tb = Tensorboard.from_dict(o)
            validate_tensorboard(tb)
            return tb.to_dict()

        def parse_volume_viewer(o):
            from kubeflow_tpu.platform.workbench import (
                VolumeViewer,
                validate_volume_viewer,
            )

            vv = VolumeViewer.from_dict(o)
            validate_volume_viewer(vv)
            return vv.to_dict()

        def parse_graph(o):
            g = InferenceGraph.from_dict(o)
            validate_graph(g)
            return g.to_dict()

        parser = (
            parse_job if kind in JOB_KINDS
            else {"Experiment": parse_experiment,
                  "InferenceService": parse_isvc,
                  "TrainedModel": parse_trained_model,
                  "Profile": parse_profile,
                  "PodDefault": parse_pod_default,
                  "Pipeline": parse_pipeline,
                  "Notebook": parse_notebook,
                  "Tensorboard": parse_tensorboard,
                  "VolumeViewer": parse_volume_viewer,
                  GRAPH_KIND: parse_graph}.get(kind)
        )
        if parser is not None:
            # Admission-webhook analog: parse + default + validate, then
            # preserve the controller-owned status across re-applies.
            # pydantic's ValidationError subclasses ValueError, so one
            # clause covers model parsing and semantic validation.
            try:
                obj.setdefault("kind", kind)
                if obj["kind"] != kind:
                    raise ValidationError(
                        f"body kind {obj['kind']} != URL kind {kind}"
                    )
                stored = obj_with_preserved_status(self.store, kind, parser(obj))
            except (ValidationError, ServingValidationError,
                    PlatformValidationError, PipelineValidationError,
                    ValueError) as e:
                return web.json_response({"error": str(e)}, status=422)
        else:
            # Unknown kinds are validated by their controllers; only
            # structural metadata is checked here.
            if not obj.get("metadata", {}).get("name"):
                return web.json_response(
                    {"error": "metadata.name is required"}, status=422
                )
            stored = obj
        try:
            saved = self.store.put(kind, stored)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=422)
        return web.json_response(saved)

    async def h_list(self, req: web.Request) -> web.Response:
        kind = req.match_info["kind"]
        ns = req.query.get("namespace")
        return web.json_response({"items": self.store.list(kind, ns)})

    async def h_get(self, req: web.Request) -> web.Response:
        kind = req.match_info["kind"]
        obj = self.store.get(
            kind, req.match_info["name"], req.match_info["ns"]
        )
        if obj is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(obj)

    async def h_delete(self, req: web.Request) -> web.Response:
        kind = req.match_info["kind"]
        ok = self.store.delete(
            kind, req.match_info["name"], req.match_info["ns"]
        )
        # 200 either way: "wasn't there" is a successful delete outcome the
        # client inspects via the body, not an HTTP error.
        return web.json_response({"deleted": ok})

    async def h_logs(self, req: web.Request) -> web.Response:
        ns, name = req.match_info["ns"], req.match_info["name"]
        replica = req.query.get("replica", "worker-0")
        path = os.path.join(
            self.log_dir, f"{ns}_{name}_{replica}.log"
        )
        if not os.path.exists(path):
            return web.json_response(
                {"error": f"no log for {ns}/{name}/{replica}"}, status=404
            )
        tail = int(req.query.get("tail", "0"))

        def _read() -> str:
            with open(path, "r", errors="replace") as f:
                return f.read()

        # Worker logs grow unbounded; a sync read here would stall every
        # other handler and watch stream for the whole file's duration.
        text = await asyncio.to_thread(_read)
        if tail:
            text = "\n".join(text.splitlines()[-tail:])
        return web.Response(text=text)

    async def h_events(self, req: web.Request) -> web.Response:
        ns, name = req.match_info["ns"], req.match_info["name"]
        key = f"{ns}/{name}"
        events = [
            e for e in self.store.list("Event", ns) if e.get("involved") == key
        ]
        events.sort(key=lambda e: e.get("time", 0))
        return web.json_response({"items": events})

    async def h_observations(self, req: web.Request) -> web.Response:
        """Full metric history for a trial (K6's GetObservationLog)."""
        key = f"{req.match_info['ns']}/{req.match_info['name']}"
        try:
            start_step = (int(req.query["start_step"])
                          if "start_step" in req.query else None)
            end_step = (int(req.query["end_step"])
                        if "end_step" in req.query else None)
        except ValueError:
            return web.json_response(
                {"error": "start_step/end_step must be integers"}, status=400
            )
        rows = self.obs_db.get_observation_log(
            key,
            metric_name=req.query.get("metric"),
            start_step=start_step,
            end_step=end_step,
        )
        return web.json_response({"trial": key, "observations": rows})

    async def h_graph_infer(self, req: web.Request) -> web.Response:
        """Run one request through an InferenceGraph: V1-shaped body in
        ({"instances": [...]}), composed result out. Each service hop goes
        through the activator (scale-to-zero per service applies)."""
        ns, name = req.match_info["ns"], req.match_info["name"]
        raw = self.store.get(GRAPH_KIND, name, ns)
        if raw is None:
            return web.json_response(
                {"error": f"inference graph {ns}/{name} not found"},
                status=404,
            )
        try:
            graph = InferenceGraph.from_dict(raw)
            body = await req.json()
            instances = body.get("instances")
            if not isinstance(instances, list):
                raise ValueError('body must have "instances": [...]')
        except (ValueError, json.JSONDecodeError) as e:
            return web.json_response({"error": str(e)}, status=400)

        async def call_service(svc_name: str, insts):
            # In-process hop through the activator core (same path as
            # /serving/, without re-entering the HTTP stack).
            status, payload, _ = await self.activator.proxy(
                ns, svc_name, f"v1/models/{svc_name}:predict",
                body=json.dumps({"instances": insts}).encode(),
            )
            try:
                data = json.loads(payload or b"{}")
            except json.JSONDecodeError:
                # Non-JSON upstream bodies (plain-text error pages) must
                # surface as 502, not crash the graph handler.
                raise GraphValidationError(
                    f"service {svc_name} returned {status} with non-JSON "
                    f"body: {payload[:120]!r}"
                )
            if status != 200:
                raise GraphValidationError(
                    f"service {svc_name} returned {status}: "
                    f"{str(data.get('error', ''))[:200]}"
                )
            return data.get("predictions")

        try:
            result = await GraphRouter(graph, call_service).execute(instances)
        except GraphValidationError as e:
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response({"predictions": result})

    # -- KFAM (P7): access bindings + authz middleware ---------------------

    @web.middleware
    async def _auth_middleware(self, req: web.Request, handler):
        """Namespace authorization from the X-Kftpu-User header (the
        reference's Istio RBAC layer, reduced to its semantics).
        Namespaces without a governing Profile are open; Profile objects
        themselves are cluster-scoped and write-gated to their owner or
        the admin (or anyone could apply a Profile naming themselves
        owner and take a namespace over). Object routes deny by default:
        anything under /apis/ without a resolvable namespace requires the
        admin."""
        gated = ("/apis/", "/logs/", "/events/", "/observations/",
                 "/serving/")
        if not req.path.startswith(gated):
            return await handler(req)
        user = req.headers.get("X-Kftpu-User")
        kind = req.match_info.get("kind")
        name = req.match_info.get("name")
        ns = req.match_info.get("ns") or req.query.get("namespace")
        body = None
        if req.method == "POST" and req.path.startswith("/apis/"):
            try:
                body = await req.json()
            except Exception as e:  # noqa: BLE001 -- malformed -> handler
                # 400s; log the parse error so client bugs are diagnosable
                # from the server side instead of vanishing.
                logger.debug("malformed JSON body on %s %s: %s",
                             req.method, req.path, e)
                body = None
            else:
                if not isinstance(body, dict):
                    return web.json_response(
                        {"error": "body must be a JSON object"}, status=400
                    )
                # Parsed once here; h_apply reuses it (bodies can be MBs).
                req["parsed_json"] = body
        if kind == "Profile":
            # Cluster-scoped: the governed namespace is the object NAME.
            governed = name or (
                ((body or {}).get("metadata") or {}).get("name")
            )
            if req.method in ("POST", "DELETE"):
                ok = governed is not None and self.access.can_manage(
                    user, governed
                )
            elif governed is not None:
                ok = self.access.can_access(user, governed)
            else:  # list all profiles: admin only
                ok = user == self.access.admin
            if not ok:
                return web.json_response(
                    {"error": f"user {user!r} may not access Profile "
                              f"{governed!r}"},
                    status=403,
                )
            return await handler(req)
        if ns is None and body is not None:
            ns = ((body.get("metadata") or {}).get("namespace", "default"))
        if ns is None:
            # Cross-namespace list (or unparseable body): admin only --
            # deny by default rather than leak every namespace's objects.
            if user != self.access.admin:
                return web.json_response(
                    {"error": "cross-namespace access requires the admin; "
                              "pass ?namespace="},
                    status=403,
                )
        elif not self.access.can_access(user, ns):
            return web.json_response(
                {"error": f"user {user!r} may not access namespace "
                          f"{ns!r}"},
                status=403,
            )
        return await handler(req)

    async def h_kfam_list(self, req: web.Request) -> web.Response:
        ns = req.query.get("namespace")
        bindings = self.access.bindings(ns)
        if self.auth_enabled:
            # Non-admins see only bindings for namespaces they can access
            # (the full map is a targeting aid for takeover attempts).
            user = req.headers.get("X-Kftpu-User")
            if user != self.access.admin:
                bindings = [
                    b for b in bindings
                    if self.access.can_access(user, b["namespace"])
                ]
        return web.json_response(bindings)

    async def h_kfam_add(self, req: web.Request) -> web.Response:
        try:
            body = await req.json()
            user, ns = body["user"], body["namespace"]
        except Exception:  # noqa: BLE001
            return web.json_response(
                {"error": "body needs user and namespace"}, status=422
            )
        if not (isinstance(user, str) and user
                and isinstance(ns, str) and ns):
            # A non-string contributor would bypass pydantic (we mutate
            # the stored dict) and poison every later Profile parse.
            return web.json_response(
                {"error": "user and namespace must be non-empty strings"},
                status=422,
            )
        caller = req.headers.get("X-Kftpu-User")
        if self.auth_enabled and not self.access.can_manage(caller, ns):
            return web.json_response(
                {"error": f"user {caller!r} may not manage bindings for "
                          f"{ns!r}"},
                status=403,
            )
        try:
            return web.json_response(self.access.add_binding(user, ns))
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)

    async def h_kfam_delete(self, req: web.Request) -> web.Response:
        user = req.query.get("user")
        ns = req.query.get("namespace")
        if not user or not ns:
            return web.json_response(
                {"error": "query needs user and namespace"}, status=422
            )
        caller = req.headers.get("X-Kftpu-User")
        if self.auth_enabled and not self.access.can_manage(caller, ns):
            return web.json_response(
                {"error": f"user {caller!r} may not manage bindings for "
                          f"{ns!r}"},
                status=403,
            )
        deleted = self.access.delete_binding(user, ns)
        return web.json_response({"deleted": deleted})

    async def h_dashboard(self, req: web.Request) -> web.Response:
        """Central-dashboard equivalent (SURVEY.md 3.4 P5): a single
        self-contained page aggregating every kind's objects and phases
        over the /apis/ routes (so it sees exactly what the CLI sees,
        authorization included)."""
        return web.Response(text=_DASHBOARD_PAGE, content_type="text/html")

    async def h_isvc_detail(self, req: web.Request) -> web.Response:
        """InferenceService drill-down (SURVEY.md 5.5): component/replica
        status plus LIVE engine metrics scraped from each replica's
        /metrics -- queue depth, slot occupancy, prefill backlog,
        TTFT/ITL histograms land where an operator looks for them."""
        import html as _html

        import aiohttp

        ns, name = req.match_info["ns"], req.match_info["name"]
        raw = self.store.get("InferenceService", name, ns)
        if raw is None:
            return web.Response(status=404, text="inferenceservice not found")
        status = raw.get("status", {})

        async def scrape(session, port):
            try:
                async with session.get(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=aiohttp.ClientTimeout(total=2),
                ) as r:
                    return await r.text()
            except Exception as e:  # noqa: BLE001 - dead replica
                return f"(scrape failed: {e})"

        sections = []
        # One session, all replicas scraped CONCURRENTLY: hung replicas
        # bound the page at ~one timeout, not timeouts x replicas.
        async with aiohttp.ClientSession() as session:
            for comp in ("predictor", "transformer", "explainer"):
                cstat = status.get(comp) or {}
                reps = cstat.get("replicas") or []
                if not reps and comp != "predictor":
                    continue
                head = (
                    f"<h2>{comp} "
                    f"({cstat.get('ready_replicas', 0)}/"
                    f"{cstat.get('desired_replicas', 0)} ready)</h2>"
                )
                texts = await asyncio.gather(*[
                    scrape(session, rep.get("port"))
                    if rep.get("port") and rep.get("state") == "Ready"
                    else asyncio.sleep(0, result="")
                    for rep in reps
                ])
                blocks = []
                for rep, text in zip(reps, texts):
                    blocks.append(
                        f"<h3>replica {rep.get('index')} · port "
                        f"{rep.get('port')} · "
                        f"{_html.escape(str(rep.get('state', '?')))}</h3>"
                        f"<pre>{_html.escape(text)}</pre>"
                    )
                sections.append(head + "".join(blocks))
        conds = " · ".join(
            f"{c.get('type')}={c.get('status')}"
            for c in status.get("conditions", [])
        )
        page = (
            "<!doctype html><html><head><title>isvc "
            f"{_html.escape(name)}</title><style>"
            "body{font-family:monospace;margin:2em;background:#fafafa}"
            "pre{background:#fff;border:1px solid #ccc;padding:8px;"
            "font-size:12px;overflow-x:auto}"
            "</style></head><body>"
            f"<h1>inferenceservice {_html.escape(ns)}/{_html.escape(name)}"
            f"</h1><p>{_html.escape(conds)}</p>"
            + "".join(sections) +
            '<p><a href="/dashboard">back</a></p></body></html>'
        )
        return web.Response(text=page, content_type="text/html")

    async def h_experiment_detail(self, req: web.Request) -> web.Response:
        """Experiment drill-down (Katib UI analog, SURVEY.md 3.2 K8):
        parameters, budget, per-trial assignments + objective values, the
        optimal trial, and an inline SVG of objective vs. trial index."""
        import html as _html

        ns, name = req.match_info["ns"], req.match_info["name"]
        raw = self.store.get("Experiment", name, ns)
        if raw is None:
            return web.Response(status=404, text="experiment not found")
        spec = raw.get("spec", {})
        status = raw.get("status", {})
        objective = spec.get("objective", {})
        metric = objective.get("objective_metric_name",
                               objective.get("metric", "loss"))
        goal_type = objective.get("type", "minimize")

        from kubeflow_tpu.hpo.controller import EXPERIMENT_LABEL

        trials = [
            t for t in self.store.list("Trial")
            if t["metadata"].get("namespace", "default") == ns
            and t["metadata"].get("labels", {}).get(EXPERIMENT_LABEL) == name
        ]
        trials.sort(key=lambda t: t["metadata"]["name"])

        from kubeflow_tpu.hpo.types import Trial as TrialModel

        def trial_value(t):
            # Canonical extraction (Observation.value_of / status.phase)
            # so the page can never disagree with the API's view.
            try:
                return TrialModel.model_validate(t).status.observation \
                    .value_of(metric)
            except ValueError:
                return None

        def trial_phase(t):
            try:
                return TrialModel.model_validate(t).status.phase
            except ValueError:
                return "Pending"

        rows = []
        values = []
        for i, t in enumerate(trials):
            v = trial_value(t)
            if v is not None:
                values.append((i, float(v)))
            assigns = ", ".join(
                f"{k}={v}" for k, v in
                t.get("spec", {}).get("assignments", {}).items()
            )
            rows.append(
                f"<tr><td>{_html.escape(t['metadata']['name'])}</td>"
                f"<td>{_html.escape(assigns)}</td>"
                f"<td>{trial_phase(t)}</td>"
                f"<td>{'' if v is None else f'{float(v):.6g}'}</td></tr>"
            )

        # Inline SVG scatter: objective vs trial index.
        svg = ""
        if values:
            w, h, pad = 520, 160, 28
            vs = [v for _, v in values]
            vmin, vmax = min(vs), max(vs)
            span = (vmax - vmin) or 1.0
            n = max(len(trials) - 1, 1)
            pts = []
            for i, v in values:
                x = pad + (w - 2 * pad) * i / n
                y = h - pad - (h - 2 * pad) * (v - vmin) / span
                pts.append(f'<circle cx="{x:.1f}" cy="{y:.1f}" r="3" '
                           'fill="#36c"/>')
            svg = (
                f'<svg width="{w}" height="{h}" '
                'style="background:#fff;border:1px solid #ccc">'
                f'<text x="{pad}" y="14" font-size="11">{_html.escape(metric)}'
                f' ({goal_type}); min={vmin:.6g} max={vmax:.6g}</text>'
                + "".join(pts) + "</svg>"
            )

        optimal = status.get("current_optimal_trial", {})
        opt_txt = ""
        if optimal.get("name"):
            opt_assigns = ", ".join(
                f"{k}={v}" for k, v in optimal.get("assignments", {}).items()
            )
            opt_txt = (
                f"<p><b>optimal:</b> {_html.escape(optimal['name'])} "
                f"({_html.escape(opt_assigns)})</p>"
            )
        counts = " ".join(
            f"{k.split('_', 1)[1]}={status.get(k, 0)}"
            for k in ("trials_created", "trials_running",
                      "trials_succeeded", "trials_failed",
                      "trials_early_stopped")
        )
        page = (
            "<!doctype html><html><head><title>experiment "
            f"{_html.escape(name)}</title><style>"
            "body{font-family:monospace;margin:2em;background:#fafafa}"
            "table{border-collapse:collapse}"
            "td,th{border:1px solid #ccc;padding:3px 8px;font-size:13px}"
            "</style></head><body>"
            f"<h1>experiment {_html.escape(ns)}/{_html.escape(name)}</h1>"
            f"<p>algorithm: {_html.escape(str(spec.get('algorithm', {}).get('name', '?')))}"
            f" · objective: {_html.escape(metric)} ({goal_type}) · {counts}</p>"
            + opt_txt + svg +
            "<h2>trials</h2><table><tr><th>trial</th><th>assignments</th>"
            "<th>phase</th><th>" + _html.escape(metric) + "</th></tr>"
            + "".join(rows) + "</table>"
            '<p><a href="/dashboard">back</a></p></body></html>'
        )
        return web.Response(text=page, content_type="text/html")

    async def h_pipeline_detail(self, req: web.Request) -> web.Response:
        """Pipeline run drill-down (the kfp run-detail page's role,
        SURVEY.md 3.4 P9): DAG steps in topological order with per-unit
        (step and fan-out expansion) phase, dependencies, when/items,
        retries, and captured outputs, plus the run's conditions."""
        import html as _html

        ns, name = req.match_info["ns"], req.match_info["name"]
        raw = self.store.get("Pipeline", name, ns)
        if raw is None:
            return web.Response(status=404, text="pipeline not found")
        spec = raw.get("spec", {})
        status = raw.get("status", {})
        phases = status.get("step_phases", {})
        outputs = status.get("step_outputs", {})
        retries = status.get("step_retries", {})

        def out_snip(k: str) -> str:
            v = outputs.get(k, "")
            v = v if len(v) <= 80 else v[:77] + "..."
            return _html.escape(v)

        rows = []
        for s in spec.get("steps", []):
            sname = s["name"]
            deps = ", ".join(s.get("dependencies", []))
            flags = []
            if s.get("when"):
                flags.append("when")
            if s.get("with_items") is not None:
                par = s.get("parallelism") or ""
                flags.append(f"fan-out{f' (par {par})' if par else ''}")
            if s.get("cache"):
                flags.append("cache")
            if s.get("retry"):
                flags.append(f"retry {s['retry']}")
            rows.append(
                f"<tr><td><b>{_html.escape(sname)}</b></td>"
                f"<td>{_html.escape(deps)}</td>"
                f"<td>{_html.escape(', '.join(flags))}</td>"
                f"<td>{_html.escape(phases.get(sname, 'Pending'))}</td>"
                f"<td>{retries.get(sname, '')}</td>"
                f"<td>{out_snip(sname)}</td></tr>"
            )
            # Expansion units, in index order under their logical
            # step. Gate on with_items like the controller's owned():
            # a plain sibling step legally named "<step>-<i>" is NOT an
            # expansion and must not render twice.
            units = [] if s.get("with_items") is None else sorted(
                (k for k in phases
                 if k.rpartition("-")[0] == sname
                 and k.rpartition("-")[2].isdigit()),
                key=lambda k: int(k.rpartition("-")[2]),
            )
            for u in units:
                rows.append(
                    f"<tr><td>&nbsp;&nbsp;{_html.escape(u)}</td><td></td>"
                    "<td></td>"
                    f"<td>{_html.escape(phases.get(u, ''))}</td>"
                    f"<td>{retries.get(u, '')}</td>"
                    f"<td>{out_snip(u)}</td></tr>"
                )
        eh = spec.get("exit_handler")
        if eh:
            u = eh["name"]
            rows.append(
                f"<tr><td><i>{_html.escape(u)} (exit handler)</i></td>"
                "<td></td><td></td>"
                f"<td>{_html.escape(phases.get(u, 'Pending'))}</td>"
                f"<td>{retries.get(u, '')}</td>"
                f"<td>{out_snip(u)}</td></tr>"
            )
        conds = "".join(
            f"<li>{_html.escape(c.get('type', ''))}"
            f" ({_html.escape(c.get('reason', ''))})"
            f" {_html.escape(c.get('message', ''))}</li>"
            for c in status.get("conditions", [])
        )
        params = ", ".join(
            f"{_html.escape(str(k))}={_html.escape(str(v))}"
            for k, v in spec.get("parameters", {}).items()
        )
        page = (
            "<!doctype html><html><head><title>pipeline "
            f"{_html.escape(name)}</title><style>"
            "body{font-family:monospace;margin:2em;background:#fafafa}"
            "table{border-collapse:collapse}"
            "td,th{border:1px solid #ccc;padding:3px 8px;font-size:13px}"
            "</style></head><body>"
            f"<h1>pipeline {_html.escape(ns)}/{_html.escape(name)}</h1>"
            f"<p>parameters: {params or '(none)'}</p>"
            "<h2>steps</h2><table><tr><th>step</th><th>deps</th>"
            "<th>flags</th><th>phase</th><th>retries</th><th>output</th>"
            "</tr>" + "".join(rows) + "</table>"
            "<h2>conditions</h2><ul>" + conds + "</ul>"
            '<p><a href="/dashboard">back</a></p></body></html>'
        )
        return web.Response(text=page, content_type="text/html")

    async def h_healthz(self, req: web.Request) -> web.Response:
        return web.json_response({"ok": True, "uptime": time.time() - self.started_at})

    async def h_debug_trace(self, req: web.Request) -> web.Response:
        """Live Chrome trace-event export of this process's span ring
        (controller plane); `kftpu trace dump --serving` merges it."""
        from kubeflow_tpu.obs import trace as obs_trace

        return web.json_response(obs_trace.recorder().export())

    async def h_debug_series(self, req: web.Request) -> web.Response:
        """Time-series store snapshot + goodput/SLO summary (the data
        behind ``kftpu top``). Query params: ``name`` filters series by
        exact name, ``since`` is a lookback in seconds, ``step`` a
        downsampling bucket in seconds."""
        q = req.rel_url.query
        try:
            lookback = float(q["since"]) if "since" in q else None
            step = float(q["step"]) if "step" in q else None
        except ValueError:
            return web.json_response(
                {"error": "since/step must be numbers"}, status=400)
        since = time.time() - lookback if lookback else None
        tele = self.telemetry
        snap = tele.series.snapshot(
            name=q.get("name") or None, since=since, step=step)
        snap["goodput"] = {
            key: {
                "fraction": round(jg.goodput_fraction(), 4),
                "attributed_seconds": {
                    st: round(s, 3) for st, s in jg.totals().items()
                },
                "wall_seconds": round(jg.wall(), 3),
                "conservation_error": round(jg.conservation_error(), 6),
                "incarnations": jg.incarnations,
            }
            for key, jg in sorted(tele.goodput.items())
        }
        snap["alerts"] = tele.alerting()
        return web.json_response(snap)

    async def h_metrics(self, req: web.Request) -> web.Response:
        sample = obs_registry.sample_line
        lines = [
            sample("kftpu_chips_total", None, self.gang.total_chips),
            sample("kftpu_chips_used", None, self.gang.used_chips),
            sample("kftpu_gangs_pending", None, len(self.gang.pending())),
            sample("kftpu_uptime_seconds", None,
                   f"{time.time() - self.started_at:.0f}"),
        ]
        for kind in self.store.kinds():
            lines.append(sample("kftpu_objects", {"kind": kind},
                                len(self.store.list(kind))))
        # Process-wide registry: reconciler event counters (and anything
        # else this process registered) share the scrape.
        lines.extend(obs_registry.REGISTRY.expose())
        return web.Response(text="\n".join(lines) + "\n")


def obj_with_preserved_status(store: ObjectStore, kind: str, obj: dict) -> dict:
    """Re-apply keeps the controller-owned status, like a spec-only PATCH."""
    existing = store.get(
        kind, obj["metadata"]["name"], obj["metadata"].get("namespace", "default")
    )
    if existing and "status" in existing:
        obj = dict(obj)
        obj["status"] = existing["status"]
    return obj


_DASHBOARD_PAGE = """<!doctype html>
<html><head><title>kftpu dashboard</title><style>
body{font-family:monospace;margin:2em;background:#fafafa}
h1{font-size:1.3em} h2{font-size:1.05em;margin:1.2em 0 .3em}
table{border-collapse:collapse;min-width:40em}
td,th{border:1px solid #ccc;padding:3px 8px;text-align:left;font-size:13px}
th{background:#eee}
.Succeeded,.Ready{color:#0a0} .Failed{color:#c00}
.Running{color:#06c} .Pending,.Unready,.Stopped{color:#b60}
#err{color:#c00}
button{font-family:monospace;font-size:12px;margin-left:4px}
form.create{margin:.3em 0 .8em}
form.create input{font-family:monospace;font-size:12px;margin-right:4px}
details{margin:.2em 0}
</style></head><body>
<h1>kftpu control plane</h1>
<div id="err"></div><div id="root">loading...</div>
<script>
const KINDS = ["JAXJob","TFJob","PyTorchJob","MPIJob","XGBoostJob",
  "PaddleJob","Experiment","Trial","InferenceService","TrainedModel",
  "Pipeline",
  "Notebook","Tensorboard","VolumeViewer","Profile","PodDefault"];
const PHASE_ORDER = ["Failed","Succeeded","Suspended","Restarting",
  "Running","Ready","Unready","Created"];
const STOP_ANN = "kftpu.io/stopped";
function phaseOf(o){
  const active = (o.status && o.status.conditions || [])
    .filter(c=>c.status).map(c=>c.type);
  for (const t of PHASE_ORDER) if (active.includes(t))
    return t === "Created" ? "Pending" : t;
  return "Pending";
}
function esc(s){
  return String(s).replace(/[&<>"']/g, c => ({"&":"&amp;","<":"&lt;",
    ">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
}
function fail(e){ document.getElementById("err").textContent = e; }
// CRUD actions (reference P6 web apps): everything goes through the
// same /apis routes the CLI uses, then re-renders. Buttons carry
// data-* attributes read via dataset (never interpolate object names
// into inline JS: the HTML parser decodes entities BEFORE the JS
// engine parses, so entity-escaping cannot protect a string literal).
async function submitSpec(kind, spec){
  const r = await fetch("apis/"+kind, {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify(spec)});
  const err = r.ok ? null : kind+" apply: "+await r.text();
  await main();  // re-render resets the banner; report AFTER
  if (err) fail(err);
}
async function del(kind, ns, name){
  if (!confirm("delete " + kind + " " + ns + "/" + name + "?")) return;
  const r = await fetch("apis/"+kind+"/"+encodeURIComponent(ns)+"/"
    +encodeURIComponent(name), {method: "DELETE"});
  const err = r.ok ? null : kind+" delete: "+await r.text();
  await main();
  if (err) fail(err);
}
async function toggleStop(ns, name){
  const r = await fetch("apis/Notebook/"+encodeURIComponent(ns)+"/"
    +encodeURIComponent(name));
  if (!r.ok) { fail("notebook get: "+await r.text()); return; }
  const o = await r.json();
  o.metadata.annotations = o.metadata.annotations || {};
  if (STOP_ANN in o.metadata.annotations)
    delete o.metadata.annotations[STOP_ANN];
  else o.metadata.annotations[STOP_ANN] = "dashboard";
  await submitSpec("Notebook", o);
}
document.addEventListener("click", ev => {
  const b = ev.target.closest("button[data-act]");
  if (!b) return;
  const d = b.dataset;
  if (d.act === "del") del(d.kind, d.ns, d.name).catch(fail);
  else if (d.act === "stop") toggleStop(d.ns, d.name).catch(fail);
});
function createNotebook(ev){
  ev.preventDefault();
  const f = ev.target;
  const args = f.args.value.trim();
  submitSpec("Notebook", {kind: "Notebook",
    metadata: {name: f.name_.value, namespace: f.ns.value || "default"},
    spec: {template: {entrypoint: f.entry.value,
                      args: args ? args.split(/\\s+/) : []}}}).catch(fail);
}
function createTensorboard(ev){
  ev.preventDefault();
  const f = ev.target;
  const spec = {};
  if (f.job.value) spec.job = f.job.value;
  if (f.logdir.value) spec.log_dir = f.logdir.value;
  submitSpec("Tensorboard", {kind: "Tensorboard",
    metadata: {name: f.name_.value, namespace: f.ns.value || "default"},
    spec: spec}).catch(fail);
}
const CREATE_FORMS = {
  Notebook: '<details><summary>new notebook</summary>'
    +'<form class="create" onsubmit="createNotebook(event)">'
    +'<input name="name_" placeholder="name" required>'
    +'<input name="ns" placeholder="namespace (default)">'
    +'<input name="entry" placeholder="entrypoint module" required>'
    +'<input name="args" placeholder="args" size="24">'
    +'<button>create</button></form></details>',
  Tensorboard: '<details><summary>new tensorboard</summary>'
    +'<form class="create" onsubmit="createTensorboard(event)">'
    +'<input name="name_" placeholder="name" required>'
    +'<input name="ns" placeholder="namespace (default)">'
    +'<input name="job" placeholder="job name">'
    +'<input name="logdir" placeholder="or log dir" size="24">'
    +'<button>create</button></form></details>',
};
async function main(){
  const root = document.getElementById("root");
  let html = "";
  const listErrs = [];
  for (const kind of KINDS){
    let items = [], listErr = null;
    try {
      const r = await fetch("apis/" + kind);
      if (r.ok) items = (await r.json()).items || [];
      else listErr = kind + " list: HTTP " + r.status;
    } catch (e) { listErr = kind + " list: " + e; }
    const form = CREATE_FORMS[kind] || "";
    if (!items.length && !form && !listErr) continue;
    if (listErr) listErrs.push(listErr);
    const rows = items.map(o=>{
      let ph = phaseOf(o);
      // Escape everything object-controlled; links only for http(s).
      const raw = o.status && o.status.url;
      const url = raw && /^https?:\\/\\//.test(raw)
        ? ' <a href="'+esc(raw)+'">open</a>' : "";
      const ns = esc(o.metadata.namespace||"default");
      let name = esc(o.metadata.name);
      if (kind === "Experiment")  // drill-down: trials + objective plot
        name = '<a href="dashboard/experiment/'+ns+'/'+name+'">'+name+'</a>';
      if (kind === "InferenceService")  // drill-down: replica metrics
        name = '<a href="dashboard/isvc/'+ns+'/'+name+'">'+name+'</a>';
      if (kind === "Pipeline")  // drill-down: step/expansion phases
        name = '<a href="dashboard/pipeline/'+ns+'/'+name+'">'+name+'</a>';
      const attrs = ' data-kind="'+esc(kind)+'" data-ns="'+ns
        +'" data-name="'+esc(o.metadata.name)+'"';
      let actions = '<button data-act="del"'+attrs+'>delete</button>';
      if (kind === "Notebook"){
        const stopped = (o.metadata.annotations||{})[STOP_ANN] !== undefined;
        if (stopped) ph = "Stopped";
        actions += ' <button data-act="stop"'+attrs+'>'
          +(stopped ? "resume" : "stop")+'</button>';
      }
      return "<tr><td>"+ns+"</td><td>"
        +name+'</td><td class="'+esc(ph)+'">'
        +esc(ph)+url+"</td><td>"+actions+"</td></tr>";
    }).join("");
    const table = items.length
      ? "<table><tr><th>namespace</th><th>name</th><th>phase</th>"
        +"<th>actions</th></tr>"+rows+"</table>"
      : "";
    const count = listErr ? "list failed" : items.length;
    html += "<h2>"+kind+" ("+count+")</h2>"+form+table;
  }
  root.innerHTML = html || "no objects yet";
  // A successful render clears stale errors; failed lists aggregate.
  fail(listErrs.join("; "));
}
main().catch(fail);
</script></body></html>
"""


def main(argv=None) -> int:
    p = argparse.ArgumentParser("kftpu control-plane server")
    p.add_argument("--state-dir", default=os.path.expanduser("~/.kftpu"))
    p.add_argument("--port", type=int, default=7450)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--chips", type=int, default=None,
                   help="TPU chip capacity (default: autodetect)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    chips = args.chips
    if chips is None:
        try:
            import jax

            chips = max(len(jax.devices()), 1)
        except Exception as e:  # noqa: BLE001 -- no jax / no backend is a
            # supported control-plane-only deployment, but say so: a typo'd
            # TPU env silently degrading to 1 chip cost a debugging session.
            logger.warning("jax device probe failed (%s); --chips "
                           "defaulting to 1", e)
            chips = 1

    # Adopt KFTPU_TRACE_* so reconcile/spawn/evict spans record in this
    # process; workers and replicas inherit the context via spawn env.
    from kubeflow_tpu.obs import trace as obs_trace

    obs_trace.activate_from_env(plane="controller", label="control-plane")

    cp = ControlPlane(args.state_dir, total_chips=chips)
    # Transformer replicas call predictors back through this ingress;
    # wildcard binds are not dialable, so point callbacks at loopback.
    cb_host = "127.0.0.1" if args.host in ("0.0.0.0", "::") else args.host
    if ":" in cb_host:  # IPv6 literals need brackets in a URL authority
        cb_host = f"[{cb_host}]"
    cp.isvc.base_url = f"http://{cb_host}:{args.port}"
    app = cp.build_app()
    logger.info(
        "control plane on http://%s:%d (state %s, %d chips)",
        args.host, args.port, args.state_dir, chips,
    )
    web.run_app(app, host=args.host, port=args.port, print=None)
    # Graceful shutdown: drop this process's spans where `kftpu trace
    # dump` merges them.
    obs_trace.write_process_trace()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
