"""Control-plane server: HTTP API over the store + controllers.

The 'API server' face of the mini control plane (SURVEY.md 7.0): the CLI
and SDK talk HTTP to this daemon exactly as kubectl talks to the k8s API
server; the JobController (and later HPO/serving controllers) run inside
it on the same event loop.
"""

from kubeflow_tpu.server.app import ControlPlane, main  # noqa: F401
