"""Per-resource CRUD web apps (SURVEY.md 3.4 P6).

The reference ships a separate single-purpose web app per workbench
resource -- jupyter-web-app, tensorboards-web-app, volumes-web-app --
each a list + create-form + actions UI over that resource's API. The
central dashboard (P5, server/app.py) aggregates every kind; these
pages are the P6 equivalents: one focused app per resource at
``/apps/notebooks``, ``/apps/tensorboards``, ``/apps/volumes``, each
driving exactly the same ``/apis/<Kind>`` routes the CLI uses (so
authorization and validation are identical) with resource-specific
columns and actions:

- notebooks: phase, connect URL, restart count, idle time, stop/start
  (the culling annotation), delete; create form = name/entrypoint/args.
- tensorboards: phase, connect URL, job-or-logdir source, delete;
  create form = name + job | log_dir.
- volumes: phase, browse link (the traversal-safe volume_viewer),
  path, delete; create form = name + path.

Server-side shell + small fetch-driven table, same house style and the
same XSS rule as the dashboard: object names never reach inline JS --
buttons carry data-* attributes.
"""

from __future__ import annotations

from aiohttp import web

_BASE_CSS = (
    "body{font-family:monospace;margin:2em;background:#fafafa}"
    "table{border-collapse:collapse;margin:.6em 0}"
    "td,th{border:1px solid #ccc;padding:4px 8px;font-size:13px}"
    "th{background:#eee;text-align:left}"
    "button{font-family:monospace;font-size:12px;margin-left:4px}"
    "form.create{margin:.4em 0 1em}"
    "form.create input{font-family:monospace;font-size:12px;"
    "margin-right:4px}"
    "a{color:#06c}"
)

_SHARED_JS = """
function esc(s){return String(s).replace(/[&<>"']/g,c=>({"&":"&amp;",
  "<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));}
function fail(e){document.getElementById("err").textContent=e;}
function phaseOf(o){
  // Same order as the central dashboard's phaseOf -- the two UIs must
  // never disagree on an object's phase.
  const act=(o.status&&o.status.conditions||[]).filter(c=>c.status)
    .map(c=>c.type);
  for(const t of ["Failed","Succeeded","Suspended","Restarting",
                  "Running","Ready","Unready","Created"])
    if(act.includes(t)) return t==="Created"?"Pending":t;
  return "Pending";
}
async function api(path,opts){
  const r=await fetch(path,opts);
  if(!r.ok) throw path+": "+await r.text();
  return r.status===204?null:r.json();
}
async function del(kind,ns,name){
  if(!confirm("delete "+kind+" "+ns+"/"+name+"?")) return;
  await api("../apis/"+kind+"/"+encodeURIComponent(ns)+"/"
    +encodeURIComponent(name),{method:"DELETE"});
  await render();
}
document.addEventListener("click",ev=>{
  const b=ev.target.closest("button[data-act]");
  if(!b) return;
  const d=b.dataset;
  if(d.act==="del") del(d.kind,d.ns,d.name).catch(fail);
  else if(d.act==="stop") toggleStop(d.ns,d.name).catch(fail);
});
"""

_NOTEBOOKS_JS = _SHARED_JS + """
const STOP="kftpu.io/stopped";
async function toggleStop(ns,name){
  const o=await api("../apis/Notebook/"+encodeURIComponent(ns)+"/"
    +encodeURIComponent(name));
  o.metadata.annotations=o.metadata.annotations||{};
  if(STOP in o.metadata.annotations) delete o.metadata.annotations[STOP];
  else o.metadata.annotations[STOP]="notebooks-app";
  await api("../apis/Notebook",{method:"POST",
    headers:{"Content-Type":"application/json"},body:JSON.stringify(o)});
  await render();
}
function idle(o){
  const t=o.status&&o.status.last_activity;
  return t?Math.round((Date.now()/1000-t)/60)+"m":"-";
}
async function render(){
  const items=(await api("../apis/Notebook")).items;
  const rows=items.map(o=>{
    const m=o.metadata,ph=phaseOf(o),url=o.status&&o.status.url;
    const stopped=(m.annotations||{})[STOP]!==undefined;
    return "<tr><td>"+esc(m.namespace)+"</td><td>"+esc(m.name)
      +"</td><td>"+esc(stopped?"Stopped":ph)+"</td><td>"
      +(url&&!stopped?'<a href="'+esc(url)+'">connect</a>':"-")
      +"</td><td>"+(o.status?o.status.restart_count:0)+"</td><td>"
      +idle(o)+'</td><td><button data-act="stop" data-ns="'
      +esc(m.namespace)+'" data-name="'+esc(m.name)+'">'
      +(stopped?"start":"stop")+'</button>'
      +'<button data-act="del" data-kind="Notebook" data-ns="'
      +esc(m.namespace)+'" data-name="'+esc(m.name)
      +'">delete</button></td></tr>';
  }).join("");
  document.getElementById("tbl").innerHTML=
    "<tr><th>namespace</th><th>name</th><th>status</th><th>connect"
    +"</th><th>restarts</th><th>idle</th><th>actions</th></tr>"+rows;
}
async function create(ev){
  ev.preventDefault();
  const f=ev.target,args=f.args.value.trim();
  await api("../apis/Notebook",{method:"POST",
    headers:{"Content-Type":"application/json"},
    body:JSON.stringify({kind:"Notebook",
      metadata:{name:f.name_.value,namespace:f.ns.value||"default"},
      spec:{template:{entrypoint:f.entry.value,
        args:args?args.split(/\\s+/):[]}}})});
  f.reset();
  await render();
}
render().catch(fail);
"""

_TENSORBOARDS_JS = _SHARED_JS + """
async function render(){
  const items=(await api("../apis/Tensorboard")).items;
  const rows=items.map(o=>{
    const m=o.metadata,url=o.status&&o.status.url;
    const src=o.spec.job?("job: "+o.spec.job):("logdir: "
      +(o.spec.log_dir||""));
    return "<tr><td>"+esc(m.namespace)+"</td><td>"+esc(m.name)
      +"</td><td>"+esc(phaseOf(o))+"</td><td>"+esc(src)+"</td><td>"
      +(url?'<a href="'+esc(url)+'">open</a>':"-")
      +'</td><td><button data-act="del" data-kind="Tensorboard" '
      +'data-ns="'+esc(m.namespace)+'" data-name="'+esc(m.name)
      +'">delete</button></td></tr>';
  }).join("");
  document.getElementById("tbl").innerHTML=
    "<tr><th>namespace</th><th>name</th><th>status</th><th>source"
    +"</th><th>url</th><th>actions</th></tr>"+rows;
}
async function create(ev){
  ev.preventDefault();
  const f=ev.target,spec={};
  if(f.job.value) spec.job=f.job.value;
  if(f.logdir.value) spec.log_dir=f.logdir.value;
  await api("../apis/Tensorboard",{method:"POST",
    headers:{"Content-Type":"application/json"},
    body:JSON.stringify({kind:"Tensorboard",
      metadata:{name:f.name_.value,namespace:f.ns.value||"default"},
      spec:spec})});
  f.reset();
  await render();
}
render().catch(fail);
"""

_VOLUMES_JS = _SHARED_JS + """
async function render(){
  const items=(await api("../apis/VolumeViewer")).items;
  const rows=items.map(o=>{
    const m=o.metadata,url=o.status&&o.status.url;
    return "<tr><td>"+esc(m.namespace)+"</td><td>"+esc(m.name)
      +"</td><td>"+esc(phaseOf(o))+"</td><td>"+esc(o.spec.path)
      +"</td><td>"+(url?'<a href="'+esc(url)+'">browse</a>':"-")
      +'</td><td><button data-act="del" data-kind="VolumeViewer" '
      +'data-ns="'+esc(m.namespace)+'" data-name="'+esc(m.name)
      +'">delete</button></td></tr>';
  }).join("");
  document.getElementById("tbl").innerHTML=
    "<tr><th>namespace</th><th>name</th><th>status</th><th>path"
    +"</th><th>browse</th><th>actions</th></tr>"+rows;
}
async function create(ev){
  ev.preventDefault();
  const f=ev.target;
  await api("../apis/VolumeViewer",{method:"POST",
    headers:{"Content-Type":"application/json"},
    body:JSON.stringify({kind:"VolumeViewer",
      metadata:{name:f.name_.value,namespace:f.ns.value||"default"},
      spec:{path:f.path.value}})});
  f.reset();
  await render();
}
render().catch(fail);
"""


def _page(title: str, form_html: str, js: str) -> str:
    return (
        "<!doctype html><html><head><title>" + title + "</title>"
        "<style>" + _BASE_CSS + "</style></head><body>"
        "<h1>" + title + "</h1><div id='err' style='color:#b00'></div>"
        + form_html +
        "<table id='tbl'></table>"
        "<p><a href='../dashboard'>central dashboard</a></p>"
        "<script>" + js + "</script></body></html>"
    )


NOTEBOOKS_PAGE = _page(
    "notebooks",
    "<form class='create' onsubmit='create(event)'>"
    "<input name='name_' placeholder='name' required>"
    "<input name='ns' placeholder='namespace (default)'>"
    "<input name='entry' placeholder='entrypoint' value='python' required>"
    "<input name='args' placeholder='args' size='30'>"
    "<button>create notebook</button></form>",
    _NOTEBOOKS_JS,
)

TENSORBOARDS_PAGE = _page(
    "tensorboards",
    "<form class='create' onsubmit='create(event)'>"
    "<input name='name_' placeholder='name' required>"
    "<input name='ns' placeholder='namespace (default)'>"
    "<input name='job' placeholder='job name (or logdir below)'>"
    "<input name='logdir' placeholder='log_dir' size='28'>"
    "<button>create tensorboard</button></form>",
    _TENSORBOARDS_JS,
)

VOLUMES_PAGE = _page(
    "volumes",
    "<form class='create' onsubmit='create(event)'>"
    "<input name='name_' placeholder='name' required>"
    "<input name='ns' placeholder='namespace (default)'>"
    "<input name='path' placeholder='/path/to/volume' size='34' required>"
    "<button>create viewer</button></form>",
    _VOLUMES_JS,
)

_PAGES = {
    "notebooks": NOTEBOOKS_PAGE,
    "tensorboards": TENSORBOARDS_PAGE,
    "volumes": VOLUMES_PAGE,
}


async def handle_app(req: web.Request) -> web.Response:
    page = _PAGES.get(req.match_info["app"])
    if page is None:
        return web.Response(status=404, text="unknown app (notebooks, "
                                             "tensorboards, volumes)")
    return web.Response(text=page, content_type="text/html")
