import sys

from kubeflow_tpu.cli.main import main

sys.exit(main())
