"""kftpu: kubectl-shaped CLI against the control-plane server.

``kftpu serve`` runs the control plane; every other command is an HTTP
client of it (KFTPU_SERVER env or --server flag), exactly the kubectl/API-
server split of the reference (call stack 4.1).

    kftpu serve --chips 8 &
    kftpu apply -f examples/llama_jaxjob.yaml
    kftpu get jaxjob
    kftpu logs llama-dp --replica worker-0 --follow
    kftpu delete jaxjob llama-dp
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import yaml

from kubeflow_tpu.api.types import phase_of_obj
from kubeflow_tpu.sdk.client import (
    ApiError,
    ControlPlaneUnreachable,
    TrainingClient,
)

DEFAULT_SERVER = os.environ.get("KFTPU_SERVER", "http://127.0.0.1:7450")

KIND_ALIASES = {
    "jaxjob": "JAXJob", "jaxjobs": "JAXJob", "jj": "JAXJob",
    "tfjob": "TFJob", "tfjobs": "TFJob",
    "pytorchjob": "PyTorchJob", "pytorchjobs": "PyTorchJob", "ptj": "PyTorchJob",
    "mpijob": "MPIJob", "mpijobs": "MPIJob",
    "xgboostjob": "XGBoostJob", "paddlejob": "PaddleJob",
    "experiment": "Experiment", "experiments": "Experiment", "exp": "Experiment",
    "trial": "Trial", "trials": "Trial",
    "inferenceservice": "InferenceService", "inferenceservices": "InferenceService",
    "isvc": "InferenceService",
    "trainedmodel": "TrainedModel", "trainedmodels": "TrainedModel",
    "tm": "TrainedModel",
    "pipeline": "Pipeline", "pipelines": "Pipeline", "pl": "Pipeline",
    "inferencegraph": "InferenceGraph", "inferencegraphs": "InferenceGraph",
    "ig": "InferenceGraph",
    "notebook": "Notebook", "notebooks": "Notebook", "nb": "Notebook",
    "tensorboard": "Tensorboard", "tensorboards": "Tensorboard",
    "tb": "Tensorboard",
    "volumeviewer": "VolumeViewer", "volumeviewers": "VolumeViewer",
    "vv": "VolumeViewer", "pvcviewer": "VolumeViewer",
    "profile": "Profile", "profiles": "Profile",
    "poddefault": "PodDefault", "poddefaults": "PodDefault",
    "event": "Event", "events": "Event",
}


def resolve_kind(k: str) -> str:
    return KIND_ALIASES.get(k.lower(), k)


def age_of(obj: dict) -> str:
    created = obj.get("metadata", {}).get("creation_time")
    if not created:
        return "?"
    s = int(time.time() - created)
    for div, unit in ((86400, "d"), (3600, "h"), (60, "m")):
        if s >= div:
            return f"{s // div}{unit}"
    return f"{s}s"


def cmd_apply(args, client: TrainingClient) -> int:
    paths = []
    for path in args.filename:
        if path != "-" and os.path.isdir(path):
            # Directory apply (the reference's kustomize-install analog):
            # every .yaml inside, sorted, so manifests/ trees install in
            # one command.
            found = sorted(
                os.path.join(path, n) for n in os.listdir(path)
                if n.endswith((".yaml", ".yml"))
            )
            if not found:
                raise SystemExit(f"error: no .yaml files in {path}")
            paths.extend(found)
        else:
            paths.append(path)
    for path in paths:
        try:
            f = sys.stdin if path == "-" else open(path)
        except OSError as e:
            raise SystemExit(f"error: cannot read {path}: {e.strerror}")
        with f:
            try:
                docs = [d for d in yaml.safe_load_all(f) if d]
            except yaml.YAMLError as e:
                raise SystemExit(f"error: invalid YAML in {path}: {e}")
        for doc in docs:
            kind = doc.get("kind")
            if not kind:
                raise SystemExit(f"error: document in {path} has no kind")
            saved = client.apply(kind, doc)
            meta = saved["metadata"]
            print(f"{kind.lower()}/{meta['name']} applied "
                  f"(generation {meta['generation']})")
    return 0


def cmd_get(args, client: TrainingClient) -> int:
    kind = resolve_kind(args.kind)
    if args.name:
        obj = client.get(kind, args.name, args.namespace)
        if args.output == "json":
            print(json.dumps(obj, indent=2))
        else:
            print(yaml.safe_dump(obj, sort_keys=False))
        return 0
    items = client.list(kind, args.namespace)
    if args.output == "json":
        print(json.dumps(items, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(items, sort_keys=False))
        return 0
    if not items:
        print(f"No {kind} objects found")
        return 0
    rows = [("NAMESPACE", "NAME", "PHASE", "RESTARTS", "AGE")]
    for o in items:
        rows.append((
            o["metadata"].get("namespace", "default"),
            o["metadata"]["name"],
            phase_of_obj(o),
            str(o.get("status", {}).get("restart_count", 0)),
            age_of(o),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return 0


def cmd_describe(args, client: TrainingClient) -> int:
    kind = resolve_kind(args.kind)
    obj = client.get(kind, args.name, args.namespace)
    print(yaml.safe_dump({k: v for k, v in obj.items() if k != "status"},
                         sort_keys=False))
    print("status:")
    print(yaml.safe_dump(obj.get("status", {}), sort_keys=False, indent=2))
    events = client.events(args.name, args.namespace)
    if events:
        print("events:")
        for e in events:
            ts = time.strftime("%H:%M:%S", time.localtime(e.get("time", 0)))
            print(f"  {ts}  {e['reason']:24s} {e['message']}")
    return 0


def cmd_logs(args, client: TrainingClient) -> int:
    if not args.follow:
        print(client.logs(args.name, args.namespace, args.replica, args.tail))
        return 0
    seen = None
    while True:
        text = client.logs(args.name, args.namespace, args.replica, 0)
        lines = text.splitlines()
        if seen is None:
            # First fetch honors --tail, like kubectl logs -f --tail.
            start = max(len(lines) - args.tail, 0) if args.tail else 0
        else:
            start = seen
        for line in lines[start:]:
            print(line, flush=True)
        seen = len(lines)
        obj = None
        for kind in ("JAXJob", "TFJob", "PyTorchJob", "MPIJob", "Trial"):
            try:
                obj = client.get(kind, args.name, args.namespace)
                break
            except ApiError:
                continue
        if obj is not None and phase_of_obj(obj) in ("Succeeded", "Failed"):
            return 0
        time.sleep(1.0)


def cmd_delete(args, client: TrainingClient) -> int:
    kind = resolve_kind(args.kind)
    deleted = client.delete(kind, args.name, args.namespace)
    print(f"{kind.lower()}/{args.name} {'deleted' if deleted else 'not found'}")
    return 0


def cmd_events(args, client: TrainingClient) -> int:
    for e in client.events(args.name, args.namespace):
        ts = time.strftime("%H:%M:%S", time.localtime(e.get("time", 0)))
        print(f"{ts}  {e['reason']:24s} {e['message']}")
    return 0


def cmd_analyze(args, _client) -> int:
    """Static analysis gate (local; no control-plane server involved).

    Exit-code contract (stable for CI): 0 = clean vs the committed
    baseline, 1 = new findings or regressed metrics. --update-baseline
    re-snapshots after fixes so the ratchet only ever tightens.
    """
    from kubeflow_tpu import analysis

    only = set(args.only or [])
    perf_findings: list = []
    perf_measured: dict = {}
    if args.diff:
        # Fast pre-push path: Tier A lint over files changed vs the rev
        # only (full tree + trace families remain the CI default).
        from kubeflow_tpu.analysis.astlint import lint_diff

        findings = lint_diff(args.diff)
        metrics = {}
    else:
        findings, metrics = analysis.run_analysis(
            trace=not args.no_trace, serving=not args.no_serving,
            families=(only - {"perf"}) if only else None,
        )
        # Perf-curve ratchet: committed bench floors + live-metric
        # ceilings. Violations are hard findings, so they ride the same
        # strict gate and are never grandfathered by --update-baseline
        # (hard != countable).
        if not only or "perf" in only:
            perf_findings, perf_measured = analysis.check_perf(
                analysis.load_perf_baseline(args.perf_baseline),
                metrics=metrics,
            )
    findings.extend(perf_findings)
    baseline = analysis.load_baseline(args.baseline)
    cmp = analysis.compare(findings, metrics, baseline)
    if args.sarif:
        with open(args.sarif, "w") as f:
            json.dump(analysis.to_sarif(findings, cmp), f, indent=2)
            f.write("\n")
        print(f"sarif: {len(findings)} result(s) -> {args.sarif}")
    if args.update_baseline:
        # Raw metrics only: perf_measured values are floor-checked (lower
        # is worse) and must not enter the higher-is-worse metric ratchet.
        data = analysis.write_baseline(
            findings, metrics, path=args.baseline
        )
        print(f"baseline updated: {data['total']} grandfathered finding(s)"
              f" (initial scan had {data['initial_total']})")
        return 0
    print(analysis.render_report(findings, dict(metrics, **perf_measured),
                                 cmp, as_json=args.json))
    if args.strict and not cmp.clean:
        return 1
    return 0


def cmd_trace(args, _client) -> int:
    """``kftpu trace dump``: merge per-process trace dumps (the
    ``trace-*.json`` files workers/controllers write into
    KFTPU_TRACE_DIR) plus live serving ``/debug/trace`` fetches into ONE
    Chrome trace-event JSON, loadable at https://ui.perfetto.dev."""
    from kubeflow_tpu.obs import trace as obs_trace

    docs = []
    tdir = args.dir or os.environ.get(obs_trace.ENV_TRACE_DIR, "")
    if tdir and os.path.isdir(tdir):
        for name in sorted(os.listdir(tdir)):
            if name.startswith("trace-") and name.endswith(".json"):
                path = os.path.join(tdir, name)
                try:
                    with open(path) as f:
                        docs.append(json.load(f))
                except (OSError, json.JSONDecodeError) as e:
                    print(f"skipping {path}: {e}", file=sys.stderr)
    for url in args.serving:
        import urllib.request

        if "://" not in url:
            url = f"http://{url}"
        if not url.endswith("/debug/trace"):
            url = url.rstrip("/") + "/debug/trace"
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                docs.append(json.load(r))
        except Exception as e:  # noqa: BLE001 - a dead replica must not
            print(f"skipping {url}: {e}", file=sys.stderr)  # kill the dump
    if not docs:
        # Empty is a normal state (tracing off, nothing has run yet),
        # not an error: exit 0 with guidance, so scripted pipelines that
        # dump opportunistically don't fail on quiet deployments.
        print(
            "no trace documents found -- set KFTPU_TRACE_DIR (or --dir) "
            "to a directory of trace-*.json dumps, or point --serving at "
            "a live replica; nothing written"
        )
        return 0
    merged = obs_trace.merge(docs)
    if args.out == "-":
        json.dump(merged, sys.stdout)
        print()
        return 0
    with open(args.out, "w") as f:
        json.dump(merged, f)
    counts = dict(obs_trace.span_counts(merged))
    total = counts.pop("total", 0)
    per = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    print(f"wrote {args.out}: {len(docs)} document(s), {total} span(s)"
          + (f" ({per})" if per else ""))
    for plane, summ in sorted(obs_trace.plane_summaries(merged).items()):
        line = (f"  {plane}: {summ['spans']} span(s), "
                f"{summ['instants']} instant(s)")
        routes = summ.get("routes")
        if routes:
            line += " | router " + " ".join(
                f"{k}={v}" for k, v in sorted(routes.items()))
        print(line)
        for pid, eng in sorted((summ.get("engines") or {}).items()):
            print(f"    engine pid {pid}: queue={eng['queue_depth']} "
                  f"active={eng['slots_active']} "
                  f"ttft_ema={eng['ttft_ema_ms']}ms "
                  f"tokens={eng['tokens_generated']} "
                  f"finished={eng['requests_finished']}")
        mig = summ.get("kv_migration")
        if mig:
            pairs = " ".join(f"{k}={v}" for k, v in
                             sorted(mig["pairs"].items()))
            print(f"    kv-migration: {mig['entries']} entr"
                  f"{'y' if mig['entries'] == 1 else 'ies'} shipped, "
                  f"{mig['bytes']} bytes"
                  + (f" ({pairs})" if pairs else ""))
    print("view: https://ui.perfetto.dev -> Open trace file")
    return 0


def _render_top(snap: dict) -> str:
    """Table over one ``/debug/series`` snapshot: per-job goodput
    fraction, attribution, live throughput, SLO burn state."""
    goodput = snap.get("goodput") or {}
    alerts = snap.get("alerts") or {}
    series = snap.get("series") or []
    tok: dict = {}
    for s in series:
        if s["name"] == "train.tokens_per_sec" and not s["stale"] \
                and s["points"]:
            job = s["labels"].get("job", "?")
            tok[job] = tok.get(job, 0.0) + s["points"][-1][1]
    header = ("JOB", "GOODPUT", "WALL_S", "TOK/S", "BADPUT(top)",
              "CONSV_ERR", "INCARN", "SLO")
    rows = []
    for job in sorted(set(goodput) | set(alerts) | set(tok)):
        g = goodput.get(job)
        slo = f"ALERT:{alerts[job]}" if job in alerts else "ok"
        if g is None:
            rows.append((job, "-", "-", f"{tok.get(job, 0.0):.0f}",
                         "-", "-", "-", slo))
            continue
        bad = {k: v for k, v in g["attributed_seconds"].items()
               if k != "compute" and v > 0}
        top_bad = (max(bad.items(), key=lambda kv: kv[1]) if bad else None)
        rows.append((
            job,
            f"{g['fraction']:.3f}",
            f"{g['wall_seconds']:.1f}",
            f"{tok.get(job, 0.0):.0f}",
            f"{top_bad[0]}={top_bad[1]:.1f}s" if top_bad else "-",
            f"{g['conservation_error']:.4f}",
            str(g["incarnations"]),
            slo,
        ))
    out = []
    if rows:
        table = [header] + rows
        widths = [max(len(str(r[i])) for r in table)
                  for i in range(len(header))]
        for r in table:
            out.append("  ".join(
                str(v).ljust(w) for v, w in zip(r, widths)).rstrip())
    else:
        out.append("no jobs reporting telemetry yet")
    stale = sum(1 for s in series if s["stale"])
    out.append(f"{len(series)} series ({stale} stale), "
               f"{len(alerts)} SLO alert(s) firing")
    return "\n".join(out)


def cmd_top(args, _client) -> int:
    """``kftpu top``: fleet telemetry one-pager from the control plane's
    ``/debug/series`` -- per-job goodput fraction, badput attribution,
    live throughput, and SLO burn-rate alert state."""
    import urllib.request

    url = (args.server.rstrip("/")
           + f"/debug/series?since={float(args.since):g}")
    while True:
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                snap = json.load(r)
        except Exception as e:  # noqa: BLE001 - one message, not a trace
            raise SystemExit(
                f"error: cannot fetch {url}: {e}; start the control "
                f"plane with: kftpu serve")
        print(_render_top(snap), flush=True)
        if not args.watch:
            return 0
        time.sleep(args.watch)


def cmd_sched(args, _client) -> int:
    """``kftpu sched plan``: run one multi-tenant scheduling round and
    print the assignment diff, without actuating anything.

    File mode (``-f`` YAMLs, repeatable) plans the given specs onto an
    empty cluster -- a what-if for capacity planning. Server mode (no
    ``-f``) plans over the live control plane's jobs, seeding current
    placements from ``status.formed_replicas``, so the diff shows what
    the next live round would change."""
    from kubeflow_tpu.api.types import ReplicaType, TrainJob
    from kubeflow_tpu.api.validation import apply_defaults
    from kubeflow_tpu.controller.scheduler import (
        Domain,
        MultiTenantPolicy,
        Placement,
        sched_job_from_spec,
    )

    domains = []
    for part in args.domains.split(","):
        name, _, spec = part.partition("=")
        chips, _, chip_type = spec.partition(":")
        try:
            domains.append(Domain(name.strip(), int(chips),
                                  chip_type=chip_type.strip() or "v5e"))
        except ValueError:
            raise SystemExit(
                f"error: bad --domains entry {part!r} "
                f"(want name=chips or name=chips:chip_type)")

    jobs = []
    if args.filename:
        for path in args.filename:
            try:
                f = sys.stdin if path == "-" else open(path)
            except OSError as e:
                raise SystemExit(f"error: cannot read {path}: {e.strerror}")
            with f:
                try:
                    docs = [d for d in yaml.safe_load_all(f) if d]
                except yaml.YAMLError as e:
                    raise SystemExit(f"error: invalid YAML in {path}: {e}")
            for doc in docs:
                job = apply_defaults(TrainJob.from_dict(doc))
                jobs.append(sched_job_from_spec(job, arrival_seq=len(jobs)))
    else:
        from kubeflow_tpu.controller.reconciler import JOB_KINDS

        client = TrainingClient(args.server)
        live = []
        for kind in JOB_KINDS:
            for obj in client.list(kind, args.namespace):
                job = TrainJob.from_dict(obj)
                if job.status.phase.value in ("Succeeded", "Failed",
                                              "Suspended"):
                    continue
                live.append(job)
        live.sort(key=lambda j: (j.metadata.creation_time or 0, j.key))
        for i, job in enumerate(live):
            spec = job.spec.replica_specs.get(ReplicaType.Worker)
            per = spec.resources.tpu if spec is not None else 0
            formed = job.status.formed_replicas
            current = (Placement(domains[0].name, formed * per)
                       if formed and per else None)
            jobs.append(sched_job_from_spec(job, arrival_seq=i,
                                            current=current))
    if not jobs:
        print("no schedulable jobs")
        return 0

    plan = MultiTenantPolicy(domains).plan(jobs)
    placed = plan.placements
    rows = []
    for sj in jobs:
        dec = next(d for d in plan.decisions if d.job == sj.key)
        new = placed.get(sj.key)
        cur = (f"{sj.current.chips}@{sj.current.domain}"
               if sj.current else "-")
        tgt = f"{new.chips}@{new.domain}" if new else "-"
        rows.append((sj.key, sj.tenant, sj.workload, cur, tgt, dec.action,
                     new.fit_source if new else sj.fit_source,
                     f"{dec.cost_seconds:g}", dec.reason))
    header = ("JOB", "TENANT", "CLASS", "CURRENT", "PLANNED", "ACTION",
              "FIT", "COST_S", "REASON")
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    for r in [header] + rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)).rstrip())
    print(f"plan: {plan.summary()}  preemptions={plan.preemptions} "
          f"migrations={plan.migrations} "
          f"mem_rejections={plan.mem_rejections}  "
          f"capacity={sum(d.chips for d in domains)} chips "
          f"across {len(domains)} domain(s)")
    if not args.dry_run:
        print("note: sched plan never actuates; the live round runs inside "
              "the controller (ElasticPolicy.scheduler_managed)")
    return 0


def cmd_serve(args, _client) -> int:
    from kubeflow_tpu.server.app import main as server_main

    argv = ["--state-dir", args.state_dir, "--port", str(args.port)]
    if args.chips is not None:
        argv += ["--chips", str(args.chips)]
    return server_main(argv)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kftpu", description="TPU-native training control plane CLI"
    )
    p.add_argument("--server", default=DEFAULT_SERVER)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("apply", help="apply object(s) from YAML")
    sp.add_argument("-f", "--filename", action="append", required=True)
    sp.set_defaults(fn=cmd_apply)

    sp = sub.add_parser("get", help="list/get objects")
    sp.add_argument("kind")
    sp.add_argument("name", nargs="?")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("-o", "--output", choices=("table", "json", "yaml"),
                    default="table")
    sp.set_defaults(fn=cmd_get)

    sp = sub.add_parser("describe", help="object details + events")
    sp.add_argument("kind")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")
    sp.set_defaults(fn=cmd_describe)

    sp = sub.add_parser("logs", help="worker logs")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--replica", default="worker-0")
    sp.add_argument("--tail", type=int, default=0)
    sp.add_argument("-f", "--follow", action="store_true")
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("delete", help="delete an object")
    sp.add_argument("kind")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")
    sp.set_defaults(fn=cmd_delete)

    sp = sub.add_parser("events", help="events for an object")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser(
        "analyze",
        help="JAX-aware static analysis (AST lint + trace-time audits)",
    )
    sp.add_argument("--strict", action="store_true",
                    help="exit 1 on findings above the baseline ratchet")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable report")
    sp.add_argument("--update-baseline", action="store_true",
                    help="re-snapshot the ratchet after fixes")
    sp.add_argument("--no-trace", action="store_true",
                    help="tier A (AST) only; skip jaxpr audits")
    sp.add_argument("--no-serving", action="store_true",
                    help="skip the serving-engine audit (fastest trace run)")
    # Choices come from the one family registry so an unknown name
    # exits 2 with the valid list and new families can never drift out
    # of the CLI contract.
    from kubeflow_tpu.analysis import FAMILIES as _families

    sp.add_argument("--only", action="append", default=None,
                    metavar="FAMILY",
                    choices=_families,
                    help="run only the named analysis family "
                         "(repeatable): " + " | ".join(_families) +
                         ". Default: all families.")
    sp.add_argument("--diff", default=None, metavar="REV",
                    help="Tier A lint restricted to package files "
                         "changed vs this git rev (fast pre-push mode; "
                         "skips trace families and the perf ratchet)")
    sp.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write findings as a SARIF 2.1.0 document "
                         "for CI line annotations")
    sp.add_argument("--baseline", default=None,
                    help="baseline path (default: committed baseline.json)")
    sp.add_argument("--perf-baseline", default=None,
                    help="perf-curve ratchet path "
                         "(default: committed perf_baseline.json)")
    sp.set_defaults(fn=cmd_analyze)

    sp = sub.add_parser(
        "trace", help="distributed trace tools (Perfetto export)"
    )
    sp.add_argument("action", choices=("dump",),
                    help="dump: merge per-process trace-*.json files and "
                         "live serving /debug/trace into one JSON")
    sp.add_argument("--dir", default=None,
                    help="trace dump directory (default: $KFTPU_TRACE_DIR)")
    sp.add_argument("--serving", action="append", default=[], metavar="URL",
                    help="serving replica base URL to fetch /debug/trace "
                         "from (repeatable)")
    sp.add_argument("--out", default="trace-merged.json",
                    help="output path ('-' = stdout)")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser(
        "sched",
        help="multi-tenant scheduler tools (dry-run planning)",
    )
    sp.add_argument("action", choices=("plan",),
                    help="plan: one scheduling round, print the "
                         "assignment diff, actuate nothing")
    sp.add_argument("-f", "--filename", action="append", default=[],
                    help="plan these YAML specs onto an empty cluster "
                         "instead of the live server's jobs (repeatable)")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--domains", default="d0=16,d1=16",
                    help="comma-separated name=chips[:chip_type] "
                         "interconnect domains; chip_type (v5e/v5p/v4) "
                         "sets per-chip HBM for the memory-fit mask "
                         "(default: d0=16,d1=16)")
    sp.add_argument("--dry-run", action="store_true",
                    help="explicit no-actuation marker (plan is always "
                         "dry; suppresses the reminder note)")
    sp.set_defaults(fn=cmd_sched)

    sp = sub.add_parser(
        "top",
        help="fleet telemetry: per-job goodput, throughput, SLO state",
    )
    sp.add_argument("--since", type=float, default=600.0,
                    help="lookback window in seconds (default: 600)")
    sp.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="refresh every SECONDS instead of one-shot")
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("serve", help="run the control-plane server")
    sp.add_argument("--state-dir", default=os.path.expanduser("~/.kftpu"))
    sp.add_argument("--port", type=int, default=7450)
    sp.add_argument("--chips", type=int, default=None)
    sp.set_defaults(fn=cmd_serve)

    args = p.parse_args(argv)
    # No control-plane client needed (sched builds its own in server mode).
    local_cmds = ("serve", "analyze", "trace", "sched", "top")
    client = TrainingClient(args.server) if args.cmd not in local_cmds else None
    try:
        return args.fn(args, client)
    except ApiError as e:
        raise SystemExit(f"error: {e} (HTTP {e.status})")
    except ControlPlaneUnreachable as e:
        raise SystemExit(f"error: {e}; start it with: kftpu serve")


if __name__ == "__main__":
    sys.exit(main())
