"""kftpu CLI -- the kubectl-shaped user surface (SURVEY.md 7.1 step 5)."""
