"""Deterministic fault injection for both planes (docs/FLEET.md,
failure semantics).

``inject.py`` holds the seeded :class:`FaultPlan` and the env-gated
hooks (``KFTPU_CHAOS_PLAN``) that the real seams call: controller
spawn, router load-poll, engine decode loop, checkpoint write, and the
KV-handoff transport. The same plan replays bit-identically -- firing
is a pure function of (plan, per-site hit counters), never of wall
clock or process RNG state.
"""

from kubeflow_tpu.chaos.inject import (  # noqa: F401
    ENV_CHAOS_PLAN,
    Fault,
    FaultPlan,
    active_plan,
    apply,
    corrupt_bytes,
    enabled,
    reset,
    should,
)
