"""Seeded, replayable fault injection actuated at the real seams.

A :class:`FaultPlan` is JSON (inline or a file path) in
``KFTPU_CHAOS_PLAN``:

    {"seed": 7, "faults": [
        {"kind": "crash",     "site": "engine.decode",    "at": [40]},
        {"kind": "straggler", "site": "engine.decode",    "at": [5, 9],
         "seconds": 0.2},
        {"kind": "wedge",     "site": "engine.decode",    "at": [60]},
        {"kind": "drop_poll", "site": "router.load_poll", "target": "1",
         "at": [2, 3, 4]},
        {"kind": "corrupt_packet", "site": "kv.packet",   "at": [0]},
        {"kind": "torn_ckpt", "site": "ckpt.write",       "at": [1],
         "mode": "flip"}
    ]}

Sites are the hook names the code calls (``controller.spawn``,
``router.load_poll``, ``engine.decode``, ``ckpt.write``,
``kv.packet``); ``site``/``target`` match with fnmatch globs. Firing is
decided ONLY by the per-(site, target) hit counter: hit index ``i``
fires a fault when ``i`` is in its ``at`` list, or -- with ``prob`` set
instead -- when a blake2b of (seed, site, target, i) lands under the
probability. Both are pure functions of the plan and the call sequence,
so the same plan over the same execution replays bit-identically; no
wall clock, no process RNG.

Every hook is free when no plan is loaded (one cached None check), so
the seams stay hot-path safe in production.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import json
import logging
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_CHAOS_PLAN = "KFTPU_CHAOS_PLAN"

KINDS = ("crash", "wedge", "straggler", "drop_poll", "corrupt_packet",
         "torn_ckpt", "spawn_env")

# Wedge "forever": long enough that every watchdog in the repo (hang
# detection, drain timeouts, bench budgets) fires first.
WEDGE_SECONDS = 3600.0


@dataclasses.dataclass
class Fault:
    """One fault spec; see the module docstring for the JSON shape."""

    kind: str
    site: str = "*"
    target: str = "*"
    at: Optional[Tuple[int, ...]] = None   # hit indices that fire
    prob: Optional[float] = None           # else seeded per-hit coin
    seconds: float = 0.0                   # straggler/wedge duration
    exit_code: int = 137                   # crash (SIGKILL's wait code)
    offset: Optional[int] = None           # corrupt: byte to flip
    mode: str = "flip"                     # torn_ckpt: flip | truncate
    env: Optional[Dict[str, str]] = None   # spawn_env: injected child env

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Fault":
        kind = d.get("kind")
        if kind not in KINDS:
            raise ValueError(f"chaos fault kind {kind!r} not in {KINDS}")
        at = d.get("at")
        if at is not None:
            at = tuple(int(a) for a in (at if isinstance(at, list) else [at]))
        return cls(
            kind=kind,
            site=str(d.get("site", "*")),
            target=str(d.get("target", "*")),
            at=at,
            prob=(float(d["prob"]) if d.get("prob") is not None else None),
            seconds=float(d.get("seconds", 0.0)),
            exit_code=int(d.get("exit_code", 137)),
            offset=(int(d["offset"]) if d.get("offset") is not None
                    else None),
            mode=str(d.get("mode", "flip")),
            env=(dict(d["env"]) if d.get("env") else None),
        )

    def matches(self, site: str, target: str) -> bool:
        return (fnmatch.fnmatchcase(site, self.site)
                and fnmatch.fnmatchcase(target, self.target))

    def fires_at(self, seed: int, site: str, target: str, hit: int) -> bool:
        if self.at is not None:
            return hit in self.at
        if self.prob is not None:
            d = hashlib.blake2b(
                f"{seed}|{self.kind}|{site}|{target}|{hit}".encode(),
                digest_size=4,
            ).digest()
            return int.from_bytes(d, "big") < self.prob * (1 << 32)
        return False


class FaultPlan:
    """Parsed plan plus the mutable replay state (hit counters and the
    fired log). Thread-safe: seams fire from engine threads, asyncio
    callbacks, and the bench driver at once."""

    def __init__(self, seed: int, faults: List[Fault]) -> None:
        self.seed = int(seed)
        self.faults = list(faults)
        self._hits: Dict[Tuple[str, str], int] = {}
        # (site, target, hit, kind) in firing order -- the determinism
        # witness chaoscheck replays.
        self.fired: List[Tuple[str, str, int, str]] = []
        self._lock = threading.Lock()

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        return cls(
            seed=int(d.get("seed", 0)),
            faults=[Fault.from_dict(f) for f in d.get("faults", [])],
        )

    @classmethod
    def from_env(cls, value: str) -> "FaultPlan":
        value = value.strip()
        if not value.startswith("{") and os.path.exists(value):
            with open(value) as f:
                value = f.read()
        return cls.from_json(value)

    def poke(self, site: str, target: str = "") -> Optional[Fault]:
        """Advance the (site, target) hit counter by one and return the
        first fault that fires at it, if any."""
        with self._lock:
            key = (site, target)
            hit = self._hits.get(key, 0)
            self._hits[key] = hit + 1
            for f in self.faults:
                if f.matches(site, target) and f.fires_at(
                        self.seed, site, target, hit):
                    self.fired.append((site, target, hit, f.kind))
                    return f
        return None

    def reset_state(self) -> None:
        with self._lock:
            self._hits.clear()
            self.fired.clear()


# -- process-global plan (env-gated) ----------------------------------------

_plan: Optional[FaultPlan] = None
_plan_env: Optional[str] = None
_plan_lock = threading.Lock()


def active_plan() -> Optional[FaultPlan]:
    """The process's plan, parsed once per distinct env value. None
    (the overwhelmingly common case) costs one env read."""
    global _plan, _plan_env
    raw = os.environ.get(ENV_CHAOS_PLAN) or None
    if raw == _plan_env:
        return _plan
    with _plan_lock:
        raw = os.environ.get(ENV_CHAOS_PLAN) or None
        if raw != _plan_env:
            _plan_env = raw
            if raw is None:
                _plan = None
            else:
                try:
                    _plan = FaultPlan.from_env(raw)
                    logger.warning(
                        "chaos: plan armed (seed=%d, %d fault(s))",
                        _plan.seed, len(_plan.faults),
                    )
                except (ValueError, OSError, json.JSONDecodeError) as e:
                    # A broken plan must not take the process down with
                    # it -- chaos is a test input, not a dependency.
                    logger.error("chaos: unparsable %s (%s); disabled",
                                 ENV_CHAOS_PLAN, e)
                    _plan = None
    return _plan


def enabled() -> bool:
    return active_plan() is not None


def reset() -> None:
    """Drop the cached plan and its counters (tests re-arm via env)."""
    global _plan, _plan_env
    with _plan_lock:
        _plan = None
        _plan_env = None


def should(site: str, target: str = "") -> Optional[Fault]:
    """The raw hook: advance the site's counter, return a firing fault
    or None. Callers that need custom actuation (dropping a poll,
    corrupting a buffer, failing a spawn) branch on the result."""
    plan = active_plan()
    if plan is None:
        return None
    return plan.poke(site, str(target))


def apply(site: str, target: str = "") -> Optional[str]:
    """Inline actuation for in-process faults. ``straggler`` and
    ``wedge`` sleep here; ``crash`` SIGKILLs the process (exactly the
    signal a preempted or OOM-killed replica dies by). Returns the kind
    fired for log/bench accounting, None when nothing fired. Other
    kinds are caller-actuated and pass through as a return value."""
    f = should(site, target)
    if f is None:
        return None
    logger.warning("chaos: firing %s at %s[%s]", f.kind, site, target)
    if f.kind == "straggler":
        time.sleep(f.seconds or 0.1)
    elif f.kind == "wedge":
        time.sleep(f.seconds or WEDGE_SECONDS)
    elif f.kind == "crash":
        os.kill(os.getpid(), signal.SIGKILL)
        os._exit(f.exit_code)  # unreachable fallback for exotic platforms
    return f.kind


def corrupt_bytes(buf: bytes, site: str = "kv.packet",
                  target: str = "") -> bytes:
    """Flip one byte of ``buf`` when a corrupt_packet fault fires at
    this hit (deterministic offset: the fault's, else seeded from the
    hit index). Identity otherwise."""
    f = should(site, target)
    if f is None or f.kind != "corrupt_packet" or not buf:
        return buf
    if f.offset is not None:
        off = f.offset % len(buf)
    else:
        plan = active_plan()
        d = hashlib.blake2b(
            f"{plan.seed if plan else 0}|corrupt|{site}|{target}".encode(),
            digest_size=8,
        ).digest()
        off = int.from_bytes(d, "big") % len(buf)
    out = bytearray(buf)
    out[off] ^= 0xFF
    logger.warning("chaos: corrupted packet byte %d at %s[%s]",
                   off, site, target)
    return bytes(out)


def mangle_file(path: str, fault: Fault) -> bool:
    """Actuate a torn_ckpt fault against one file: flip a byte
    (``mode: flip``) or truncate to half (``mode: truncate``). Returns
    True when the file was touched. Caller decides WHICH file (the
    checkpoint hook picks the newest step's largest payload)."""
    try:
        size = os.path.getsize(path)
        if size <= 0:
            return False
        if fault.mode == "truncate":
            with open(path, "r+b") as f:
                f.truncate(max(1, size // 2))
        else:
            off = (fault.offset if fault.offset is not None
                   else size // 2) % size
            with open(path, "r+b") as f:
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0xFF]))
        logger.warning("chaos: tore %s (%s)", path, fault.mode)
        return True
    except OSError as e:
        logger.error("chaos: torn_ckpt on %s failed: %s", path, e)
        return False
