"""Process-wide cache of the traced fixtures shared by the Tier B
families (audit / shard / mem).

The three families price the SAME repo entry points: the four train
tasks on the default data mesh, the llama ring/ulysses sequence
variants, and the tp=2 serving engine. Building those fixtures is what
dominates analyze wall-clock -- ``init_state`` compiles the init jit,
the engine warmup compiles prefill/insert/decode -- while the families
themselves only trace and lower, never execute the step. The built
(task, state, step, batch, mesh) tuples are therefore safe to share:
one build serves every family in the process, both under ``kftpu
analyze`` and across the analysis test files.

Deliberately NOT cached: the audit family's tp=1 serving engine. Its
DonationWatch/CompileWatch wrappers must observe a fresh build -- the
warmup's donation and compile events ARE the thing under audit.

``train_setup`` keys on the task kwargs as well as the name, so tests
that monkeypatch ``jaxpr_audit.TRAIN_TASKS`` with different settings
never see a stale fixture for the same task name.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Optional, Tuple

TrainSetup = Tuple[Any, Any, Any, Any, Any, Any]


@lru_cache(maxsize=None)
def _train_setup(name: str, kwargs_key: tuple) -> TrainSetup:
    import jax

    from kubeflow_tpu.analysis.jaxpr_audit import _mesh
    from kubeflow_tpu.models import get_task

    task = get_task(name, **dict(kwargs_key))
    mesh = _mesh()
    state = task.init_state(jax.random.PRNGKey(0), mesh)
    step = task.train_step_fn(mesh)
    jitted = getattr(step, "jitted", step)
    batch = next(iter(task.data_iter(1, 0, mesh)))
    return task, state, step, jitted, batch, mesh


def train_setup(name: str) -> TrainSetup:
    """(task, state, step, jitted, batch, mesh) for a TRAIN_TASKS entry
    on the default data mesh. Trace-only consumers share one build."""
    from kubeflow_tpu.analysis.jaxpr_audit import TRAIN_TASKS

    return _train_setup(name, tuple(sorted(TRAIN_TASKS[name].items())))


@lru_cache(maxsize=None)
def seq_setup(impl: str, seq: int) -> TrainSetup:
    """llama-tiny train setup on a sequence mesh (ring=2 / ulysses=4).
    Re-enter ``mesh_context(mesh)`` before tracing against it."""
    import jax

    from kubeflow_tpu.models import get_task
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh, \
        mesh_context

    task = get_task("llama", preset="llama-tiny", batch_size=8,
                    seq_len=16, attention_impl=impl)
    mesh = build_mesh(MeshConfig(data=-1, sequence=seq))
    with mesh_context(mesh):
        state = task.init_state(jax.random.PRNGKey(0), mesh)
        step = task.train_step_fn(mesh)
        jitted = getattr(step, "jitted", step)
        batch = next(iter(task.data_iter(1, 0, mesh)))
    return task, state, step, jitted, batch, mesh


@lru_cache(maxsize=None)
def tp2_engine() -> Optional[Any]:
    """Warmed tensor-parallel (tp=2) serving engine, or None when the
    process has fewer than 2 devices. The warmup generate() populates
    the per-key decode jit cache both shard and mem families price."""
    import dataclasses as dc

    import jax

    from kubeflow_tpu.models.llama import PRESETS
    from kubeflow_tpu.serving.engine import GenerationEngine

    if len(jax.devices()) < 2:
        return None
    cfg = dc.replace(PRESETS["llama-tiny"], max_seq=64)
    eng = GenerationEngine(config=cfg, max_slots=2, decode_block=4,
                           tensor_parallel=2)
    eng.generate([3, 5, 7], max_new_tokens=6)
    return eng
