"""Tier A: JAX-aware AST lint over the kubeflow_tpu package.

Pure-source analysis -- no imports of the linted modules, so it runs in
milliseconds and cannot be broken by import-time side effects. Rules
(catalog with rationale and examples in docs/ANALYSIS.md):

- KT-SYNC01   host-device sync reachable from traced code (np.asarray,
              .item(), .tolist(), .block_until_ready(), jax.device_get,
              float()/int() of a traced name) -- each is a silent
              device->host round trip that serializes the dispatch
              pipeline when it appears under jit/scan/shard_map.
- KT-BRANCH01 Python `if`/`while` on a traced function's own argument:
              branching on a tracer either crashes (ConcretizationError)
              or, for shape-dependent code, silently forks compilations.
- KT-SWALLOW01 broad `except Exception` whose handler neither logs,
              raises, returns, nor calls anything -- the failure mode
              that turns a crashed reconciler into a silent stall.
- KT-MUTDEF01 mutable default argument ([] / {} / set() / dict()).
- KT-DONATE01 jax.jit of a carry-updating function (cache.at[...] /
              apply_gradients) without donate_argnums: the old buffer
              stays live across the update and doubles HBM.
- KT-IMPORT01 unused module-level import (ruff F401 analog; the
              container image has no ruff, so the check lives here).
- KT-ATOMIC01 `os.replace(staging, final)` whose staging name is a
              constant `.tmp`-style suffix with no pid/uuid component:
              two processes staging to the same name clobber each
              other's half-written file (the reshard command-file bug);
              the blessed pattern is obs/trace.py's `.tmp.{os.getpid()}`.
- KT-SHARD01  `P(...)`/`PartitionSpec(...)` naming a mesh axis that no
              mesh constructed anywhere in the repo declares -- checked
              against a repo-wide axis table harvested by AST (Mesh
              axis_names, MeshConfig keywords, AXES tuples). A typo'd
              axis name silently means "replicated" at runtime.
- KT-SHARD02  `reshape`/`flatten`/`ravel` applied, inside traced code,
              to a value that was explicitly annotated with a sharded
              PartitionSpec: merging or splitting a sharded dimension
              forces GSPMD to re-lay the value out (hidden all-gather)
              -- re-constrain after reshaping instead.
- KT-ASYNC01  blocking call (`time.sleep`, `subprocess.run`, `open`,
              `requests.*`, `urlopen`) directly inside an `async def`:
              it stalls the whole event loop -- every reconcile loop,
              watch stream, and HTTP handler sharing it -- for the
              call's full duration (use asyncio.sleep / to_thread /
              create_subprocess_exec).
- KT-MEM01    device-array allocation (`jnp.zeros/ones/full/empty` and
              `_like` variants) inside a Python `for`/`while` in a
              decode/step/prefill-shaped hot path: a fresh HBM buffer
              every iteration defeats donation/reuse and churns the
              allocator -- hoist the allocation out of the loop or
              carry one buffer updated with `.at[]`.
- KT-MEM02    appending device values (`jnp.`/`jax.`-rooted
              expressions) to a module- or class-level container that
              never shrinks anywhere in the module: each retained
              Python reference pins an HBM buffer forever, the
              host-side HBM leak -- bound the container or drop the
              references after use.

Suppression: a trailing same-line comment
    # kt-lint: disable=KT-SYNC01 -- <justification>
disables the named rule(s) for that line. The justification after
``--`` is REQUIRED; a bare disable tag is ignored (and so the finding
still fires), which keeps every suppression self-documenting.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kubeflow_tpu.analysis.report import Finding

# f(x) forms whose first callable argument is traced by JAX.
_TRACING_ENTRY_ARGS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "shard_map": (0,),
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "custom_vjp": (0,),
    "custom_jvp": (0,),
    "make_jaxpr": (0,),
    "eval_shape": (0,),
}

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_NAMES = {"np", "numpy", "onp"}

_DISABLE_RE = re.compile(
    r"#\s*kt-lint:\s*disable=([A-Z0-9,\-\s]+?)\s*--\s*\S"
)
_PB2_RE = re.compile(r"_pb2(_grpc)?\.py$")


def _call_target_name(func: ast.AST) -> Optional[str]:
    """Trailing identifier of a call target: jax.lax.scan -> 'scan'."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _resolve_fn_arg(node: ast.AST) -> Optional[str]:
    """Name of the function referenced by a traced-callable argument.

    Handles a bare Name, ``partial(f, ...)``, and ``module.f`` (returns
    the attribute, resolved best-effort against local defs).
    """
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call) and _call_target_name(node.func) == "partial":
        if node.args:
            return _resolve_fn_arg(node.args[0])
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _Module:
    def __init__(self, path: str, rel: str, source: str,
                 mesh_axes: Optional[Set[str]] = None) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # Known mesh axis names for KT-SHARD01. None = harvest from this
        # module alone; lint_package passes the repo-wide table so specs
        # may legitimately reference axes a *different* module declares.
        self.mesh_axes = (harvest_mesh_axes([self.tree])
                          if mesh_axes is None else mesh_axes)
        # name -> FunctionDef nodes (same name in different scopes all
        # recorded; trace-root resolution is best-effort by name).
        self.defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)

    def suppressed(self, line: int, rule: str) -> bool:
        if 1 <= line <= len(self.lines):
            m = _DISABLE_RE.search(self.lines[line - 1])
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                return rule in rules
        return False


def _traced_roots(mod: _Module) -> Set[ast.AST]:
    """Function defs whose bodies run under a JAX trace."""
    roots: Set[ast.AST] = set()
    # Decorated defs: @jax.jit / @jit / @partial(jax.jit, ...).
    for nodes in mod.defs.values():
        for node in nodes:
            for deco in node.decorator_list:
                target = deco.func if isinstance(deco, ast.Call) else deco
                name = _call_target_name(target)
                if name == "partial" and isinstance(deco, ast.Call) and deco.args:
                    name = _call_target_name(deco.args[0])
                if name in _TRACING_ENTRY_ARGS:
                    roots.add(node)
    # Call sites: jax.jit(step, ...), lax.scan(body, ...), shard_map(f, ...)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_target_name(node.func)
        if name not in _TRACING_ENTRY_ARGS:
            continue
        for idx in _TRACING_ENTRY_ARGS[name]:
            if idx < len(node.args):
                fname = _resolve_fn_arg(node.args[idx])
                if fname and fname in mod.defs:
                    roots.update(mod.defs[fname])
    return roots


def _traced_defs(mod: _Module) -> Set[ast.AST]:
    """Roots plus every def nested inside a root (trace-time closures)."""
    traced = set(_traced_roots(mod))
    for root in list(traced):
        for sub in ast.walk(root):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                traced.add(sub)
    return traced


def _params_of(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    params = {n for n in names if n not in ("self", "cls")}
    # static_argnames/static_argnums in a jit decorator mark Python-level
    # (hashable) arguments: branching on those is the intended idiom.
    for deco in getattr(fn, "decorator_list", ()):
        if not isinstance(deco, ast.Call):
            continue
        for kw in deco.keywords:
            if kw.arg == "static_argnames":
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and isinstance(
                        node.value, str
                    ):
                        params.discard(node.value)
            elif kw.arg == "static_argnums":
                ordered = [p.arg for p in a.posonlyargs + a.args]
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and isinstance(
                        node.value, int
                    ) and 0 <= node.value < len(ordered):
                        params.discard(ordered[node.value])
    return params


def _none_checked_names(test: ast.AST) -> Set[str]:
    """Names whose only role in ``test`` is an `is (not) None` check --
    the standard optional-argument dispatch, static at trace time."""
    checked_nodes: Set[int] = set()
    for node in ast.walk(test):
        if (
            isinstance(node, ast.Compare)
            and isinstance(node.left, ast.Name)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops)
            and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            )
        ):
            checked_nodes.add(id(node.left))
    # isinstance(x, ...) probes pytree STRUCTURE (e.g. dict-vs-array KV
    # cache), which is static at trace time -- same bucket as is-None.
    for node in ast.walk(test):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "isinstance"
            and node.args
            and isinstance(node.args[0], ast.Name)
        ):
            checked_nodes.add(id(node.args[0]))
    only_checked: Set[str] = set()
    plain: Set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Name):
            (only_checked if id(node) in checked_nodes else plain).add(node.id)
    # A name also used OUTSIDE a static check is genuinely branched on.
    return only_checked - plain


def _emit(
    out: List[Finding], mod: _Module, rule: str, line: int, message: str
) -> None:
    if not mod.suppressed(line, rule):
        out.append(Finding(rule=rule, path=mod.rel, line=line, message=message))


# -- rule bodies ------------------------------------------------------------

def _check_sync_and_branch(mod: _Module, out: List[Finding]) -> None:
    traced = _traced_defs(mod)
    seen_calls: Set[int] = set()
    for fn in traced:
        params = _params_of(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and id(node) not in seen_calls:
                seen_calls.add(id(node))
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in _SYNC_METHODS
                ):
                    _emit(out, mod, "KT-SYNC01", node.lineno,
                          f".{func.attr}() syncs device->host inside "
                          f"traced fn {getattr(fn, 'name', '?')!r}")
                elif (
                    isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in _NUMPY_NAMES
                    and func.attr in ("asarray", "array")
                ):
                    _emit(out, mod, "KT-SYNC01", node.lineno,
                          f"{func.value.id}.{func.attr}() forces a host "
                          f"copy inside traced fn "
                          f"{getattr(fn, 'name', '?')!r}")
                elif (
                    isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "jax"
                    and func.attr == "device_get"
                ):
                    _emit(out, mod, "KT-SYNC01", node.lineno,
                          "jax.device_get inside traced fn "
                          f"{getattr(fn, 'name', '?')!r}")
                elif (
                    isinstance(func, ast.Name)
                    and func.id in ("float", "int")
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                ):
                    _emit(out, mod, "KT-SYNC01", node.lineno,
                          f"{func.id}() of traced argument "
                          f"{node.args[0].id!r} concretizes on host")
        # Branch rule: only this def's own statements, not nested defs
        # (they get their own pass with their own params).
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                owner = _innermost_def(fn, node)
                if owner is not fn:
                    continue
                names = {
                    n.id for n in ast.walk(node.test)
                    if isinstance(n, ast.Name)
                }
                hits = (names - _none_checked_names(node.test)) & params
                if hits:
                    _emit(out, mod, "KT-BRANCH01", node.lineno,
                          "Python branch on traced argument(s) "
                          f"{sorted(hits)} in {getattr(fn, 'name', '?')!r}")


def _innermost_def(root: ast.AST, target: ast.AST) -> ast.AST:
    """The nearest enclosing def of ``target`` within ``root``."""
    owner = root
    stack = [(root, root)]
    while stack:
        node, cur = stack.pop()
        if node is target:
            return cur
        for child in ast.iter_child_nodes(node):
            nxt = (
                child
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                else cur
            )
            stack.append((child, nxt))
    return owner


_BROAD = ("Exception", "BaseException")


def _is_broad_except(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def _check_swallow(mod: _Module, out: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad_except(node):
            continue
        acts = (
            ast.Call, ast.Raise, ast.Return, ast.Await,
            ast.Yield, ast.YieldFrom,
        )
        if any(isinstance(n, acts) for s in node.body for n in ast.walk(s)):
            continue
        _emit(out, mod, "KT-SWALLOW01", node.lineno,
              "broad except swallows the error: no log/raise/return in "
              "handler")


def _check_mutable_defaults(mod: _Module, out: List[Finding]) -> None:
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for default in fn.args.defaults + [
            d for d in fn.args.kw_defaults if d is not None
        ]:
            bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            )
            if bad:
                _emit(out, mod, "KT-MUTDEF01", default.lineno,
                      f"mutable default argument in {fn.name!r}")


def _has_carry_update(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "apply_gradients":
                    return True
                # cache.at[idx].set(...) / .add(...)
                if (
                    func.attr in ("set", "add")
                    and isinstance(func.value, ast.Subscript)
                    and isinstance(func.value.value, ast.Attribute)
                    and func.value.value.attr == "at"
                ):
                    return True
    return False


def _check_donation(mod: _Module, out: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_target_name(node.func) != "jit":
            continue
        kw = {k.arg for k in node.keywords}
        if "donate_argnums" in kw or "donate_argnames" in kw:
            continue
        if not node.args:
            continue
        fname = _resolve_fn_arg(node.args[0])
        if not fname or fname not in mod.defs:
            continue
        # ALL same-name defs must carry-update: generic inner names like
        # ``fn`` recur per closure in one module, and flagging on ``any``
        # would misattribute another closure's cache update to this jit.
        if all(_has_carry_update(d) for d in mod.defs[fname]):
            _emit(out, mod, "KT-DONATE01", node.lineno,
                  f"jax.jit({fname}) updates a carry (.at[].set / "
                  "apply_gradients) but declares no donate_argnums")


def _check_unused_imports(mod: _Module, out: List[Finding]) -> None:
    if os.path.basename(mod.path) == "__init__.py":
        return  # re-export modules: every import is intentionally unused
    imported: List[Tuple[str, int, str]] = []  # (binding, line, display)
    import_nodes: Set[int] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Import):
            import_nodes.add(id(node))
            for alias in node.names:
                binding = alias.asname or alias.name.split(".")[0]
                imported.append((binding, node.lineno, alias.name))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # compiler directive, never "used"
            import_nodes.add(id(node))
            for alias in node.names:
                if alias.name == "*":
                    continue
                binding = alias.asname or alias.name
                imported.append((binding, node.lineno, alias.name))
    if not imported:
        return
    used: Set[str] = set()
    for node in ast.walk(mod.tree):
        if id(node) in import_nodes:
            continue
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    # __all__ re-exports and docstring/annotation string references.
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value))
    noqa_re = re.compile(r"#\s*noqa\b(?::\s*([A-Z0-9, ]+))?")
    for binding, line, display in imported:
        if binding.startswith("_"):
            continue
        if binding not in used:
            # Honor ruff/flake8 noqa for this rule (bare or F401): the
            # deliberate-re-export idiom predates this linter.
            if 1 <= line <= len(mod.lines):
                m = noqa_re.search(mod.lines[line - 1])
                if m and (m.group(1) is None or "F401" in m.group(1)):
                    continue
            _emit(out, mod, "KT-IMPORT01", line,
                  f"unused import {display!r}")


# Calls that make a staging name unique per process/attempt.
_UNIQ_CALLS = {
    "getpid", "mkstemp", "mkdtemp", "uuid1", "uuid4", "urandom",
    "token_hex", "token_urlsafe", "NamedTemporaryFile",
}
# Identifier substrings that signal a uniqueness component (``pid`` in
# an f-string, a precomputed ``suffix`` from uuid, ...).
_UNIQ_NAME_RE = re.compile(r"pid|uuid|uniq|rand|token|nonce", re.I)
_TMP_FRAGMENT_RE = re.compile(r"\.?tmp\b|\.partial\b|\.staging\b", re.I)


def _expr_has_uniqueness(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = _call_target_name(n.func)
            if name in _UNIQ_CALLS:
                return True
        if isinstance(n, ast.Name) and _UNIQ_NAME_RE.search(n.id):
            return True
        if isinstance(n, ast.Attribute) and _UNIQ_NAME_RE.search(n.attr):
            return True
    return False


def _is_bare_tmp_staging(node: ast.AST) -> bool:
    """True when ``node`` builds a path with a constant tmp-ish suffix
    and no per-process uniqueness component -- f-strings, ``+`` concat,
    ``%``/``.format`` all reduce to 'has a constant .tmp fragment'."""
    frags = [
        n.value for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]
    if not any(_TMP_FRAGMENT_RE.search(f) for f in frags):
        return False
    return not _expr_has_uniqueness(node)


def _check_atomic_staging(mod: _Module, out: List[Finding]) -> None:
    """KT-ATOMIC01: os.replace() staging names must carry a pid/uuid
    component. Resolution is best-effort and conservative: a Name
    argument is resolved through its local assignments; an argument we
    can't resolve (parameter, attribute, call result) is not flagged."""
    # name -> assigned value exprs, per enclosing def (module = None).
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and func.attr in ("replace", "rename")
            and isinstance(func.value, ast.Name)
            and func.value.id == "os"
            and node.args
        ):
            continue
        src = node.args[0]
        exprs: List[ast.AST] = []
        if isinstance(src, ast.Name):
            owner = _innermost_def(mod.tree, src)
            for n in ast.walk(owner):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Name) and t.id == src.id:
                            exprs.append(n.value)
        else:
            exprs.append(src)
        if exprs and all(_is_bare_tmp_staging(e) for e in exprs):
            _emit(out, mod, "KT-ATOMIC01", node.lineno,
                  "os.%s() staging name has no pid/uuid component: "
                  "concurrent writers clobber each other's staging "
                  "file (use the obs/trace.py '.tmp.{os.getpid()}' "
                  "pattern)" % func.attr)


# -- sharding rules (KT-SHARD01 / KT-SHARD02) -------------------------------

_MESH_CTORS = ("Mesh", "AbstractMesh", "make_mesh", "create_device_mesh")
_AXES_NAME_RE = re.compile(r"(^|_)AXES$")


def _str_constants(node: ast.AST) -> List[str]:
    return [
        n.value for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]


def harvest_mesh_axes(trees: Iterable[ast.AST]) -> Set[str]:
    """Repo-wide mesh-axis table: every axis name any reachable mesh
    construction declares -- ``Mesh(devs, axis_names=...)`` (kwarg or
    2nd positional), ``MeshConfig(data=..., sequence=...)`` keywords,
    and ``AXES = ("data", ...)``-style tuple assignments."""
    axes: Set[str] = set()
    for tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = _call_target_name(node.func)
                if name in _MESH_CTORS:
                    for kw in node.keywords:
                        if kw.arg == "axis_names":
                            axes.update(_str_constants(kw.value))
                    if len(node.args) >= 2:
                        axes.update(_str_constants(node.args[1]))
                elif name == "MeshConfig":
                    axes.update(kw.arg for kw in node.keywords
                                if kw.arg is not None)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Name)
                            and _AXES_NAME_RE.search(t.id)
                            and isinstance(node.value, (ast.Tuple,
                                                        ast.List))):
                        axes.update(_str_constants(node.value))
    return axes


def _check_partition_axes(mod: _Module, out: List[Finding]) -> None:
    """KT-SHARD01: every axis name a PartitionSpec references must be
    declared by SOME mesh construction in the repo; an unknown name is
    silently treated as replicated by JAX's spec resolution paths."""
    if not mod.mesh_axes:
        return  # no mesh table to validate against: stay conservative
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_target_name(node.func) not in ("P", "PartitionSpec"):
            continue
        for arg in node.args:
            for name in _str_constants(arg):
                if name not in mod.mesh_axes:
                    _emit(out, mod, "KT-SHARD01", node.lineno,
                          f"PartitionSpec axis {name!r} is not declared "
                          f"by any mesh in the repo (known axes: "
                          f"{sorted(mod.mesh_axes)}); a typo'd axis "
                          f"silently means 'replicated'")


_RESHAPERS = ("reshape", "flatten", "ravel")
_CONSTRAINT_FNS = ("with_sharding_constraint", "with_logical_constraint")


def _spec_is_sharded(call: ast.Call) -> bool:
    """A constraint call whose spec carries any axis-name string is a
    sharded annotation (P() / P(None, None) are replication hints)."""
    return any(bool(_str_constants(a)) for a in call.args[1:])


def _check_shard_reshape(mod: _Module, out: List[Finding]) -> None:
    """KT-SHARD02: reshape/flatten/ravel of a value that carries an
    explicit sharded-spec annotation, inside traced code. The reshape
    discards the constraint and GSPMD re-lays the operand out however
    propagation likes -- re-apply the constraint on the reshaped value."""
    for fn in _traced_defs(mod):
        sharded: Set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _call_target_name(node.value.func)
                    in _CONSTRAINT_FNS
                    and _spec_is_sharded(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        sharded.add(t.id)
        if not sharded:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = None
            if (isinstance(func, ast.Attribute)
                    and func.attr in _RESHAPERS
                    and isinstance(func.value, ast.Name)
                    and func.value.id in sharded):
                hit = (func.value.id, func.attr)
            elif (isinstance(func, ast.Attribute)
                    and func.attr == "reshape"
                    and isinstance(func.value, ast.Name)
                    and func.value.id in _NUMPY_NAMES | {"jnp"}
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in sharded):
                hit = (node.args[0].id, f"{func.value.id}.reshape")
            if hit:
                _emit(out, mod, "KT-SHARD02", node.lineno,
                      f".{hit[1]}() of {hit[0]!r}, which carries an "
                      f"explicit sharded PartitionSpec: the reshape "
                      f"drops the constraint and invites a hidden "
                      f"re-layout -- re-constrain the reshaped value")


# -- async blocking calls (KT-ASYNC01) --------------------------------------

_BLOCKING_ATTRS = {
    ("time", "sleep"): "asyncio.sleep",
    ("subprocess", "run"): "asyncio.create_subprocess_exec",
    ("subprocess", "call"): "asyncio.create_subprocess_exec",
    ("subprocess", "check_call"): "asyncio.create_subprocess_exec",
    ("subprocess", "check_output"): "asyncio.create_subprocess_exec",
    ("subprocess", "Popen"): "asyncio.create_subprocess_exec",
    ("request", "urlopen"): "an async HTTP client or asyncio.to_thread",
}
_BLOCKING_NAMES = {
    "open": "asyncio.to_thread",
    "urlopen": "an async HTTP client or asyncio.to_thread",
}


def _walk_own_statements(fn: ast.AST):
    """Yield nodes of ``fn`` without descending into nested defs (a
    nested sync def is typically shipped to an executor, which is the
    fix this rule recommends)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_async_blocking(mod: _Module, out: List[Finding]) -> None:
    for nodes in mod.defs.values():
        for fn in nodes:
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _walk_own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                label = fix = None
                if (isinstance(func, ast.Attribute)
                        and isinstance(func.value, ast.Name)):
                    key = (func.value.id, func.attr)
                    if key in _BLOCKING_ATTRS:
                        label = f"{key[0]}.{key[1]}"
                        fix = _BLOCKING_ATTRS[key]
                    elif func.value.id == "requests":
                        label = f"requests.{func.attr}"
                        fix = "an async HTTP client or asyncio.to_thread"
                elif (isinstance(func, ast.Name)
                      and func.id in _BLOCKING_NAMES):
                    label = f"{func.id}"
                    fix = _BLOCKING_NAMES[func.id]
                if label:
                    _emit(out, mod, "KT-ASYNC01", node.lineno,
                          f"blocking {label}() inside async def "
                          f"{fn.name!r} stalls the event loop for its "
                          f"full duration (use {fix})")


# KT-MEM01: hot-path shapes whose loops run every step/block -- an
# allocation inside them churns HBM at dispatch rate.
_HOT_PATH_RE = re.compile(
    r"step|decode|prefill|sample|generate|dispatch|block|loop", re.I
)
_ALLOC_FNS = frozenset((
    "zeros", "ones", "full", "empty",
    "zeros_like", "ones_like", "full_like", "empty_like",
))


def _device_alloc_label(call: ast.Call) -> Optional[str]:
    """'jnp.zeros'-style label when ``call`` allocates a device array
    via jnp/jax.numpy, else None."""
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in _ALLOC_FNS:
        return None
    v = func.value
    if isinstance(v, ast.Name) and v.id == "jnp":
        return f"jnp.{func.attr}"
    if (isinstance(v, ast.Attribute) and v.attr == "numpy"
            and isinstance(v.value, ast.Name) and v.value.id == "jax"):
        return f"jax.numpy.{func.attr}"
    return None


def _check_loop_alloc(mod: _Module, out: List[Finding]) -> None:
    for nodes in mod.defs.values():
        for fn in nodes:
            if not _HOT_PATH_RE.search(fn.name):
                continue
            seen: Set[int] = set()
            for node in _walk_own_statements(fn):
                if not isinstance(node, (ast.For, ast.While)):
                    continue
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call) or id(sub) in seen:
                        continue
                    seen.add(id(sub))
                    label = _device_alloc_label(sub)
                    if label:
                        _emit(out, mod, "KT-MEM01", sub.lineno,
                              f"{label}() inside a Python loop in hot "
                              f"path {fn.name!r} allocates a fresh HBM "
                              f"buffer every iteration -- hoist it out "
                              f"of the loop or carry one buffer updated "
                              f"with .at[]")


# KT-MEM02: growth/shrink vocabularies for container-leak detection.
_GROW_METHODS = frozenset(("append", "add", "extend", "insert"))
_SHRINK_METHODS = frozenset((
    "clear", "pop", "popleft", "popitem", "remove", "discard",
))


def _jax_rooted(expr: ast.AST) -> bool:
    """True when the expression mentions a jnp/jax-rooted value -- the
    static signal that what is being retained is a device buffer."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in ("jnp", "jax"):
            return True
    return False


def _check_container_leak(mod: _Module, out: List[Finding]) -> None:
    # Module-level and class-body container bindings: X = [] / {} /
    # set() / dict() / list() / deque().
    containers: Set[str] = set()
    scopes = [mod.tree] + [
        n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)
    ]
    for scope in scopes:
        for stmt in scope.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            is_container = isinstance(value, (ast.List, ast.Dict, ast.Set))
            if (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id in ("list", "dict", "set", "deque")):
                is_container = True
            if not is_container:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    containers.add(t.id)
    if not containers:
        return

    def _base_name(value: ast.AST) -> Optional[str]:
        # X.append / self.X.append / Cls.X.append all resolve to X.
        if isinstance(value, ast.Name):
            return value.id
        if isinstance(value, ast.Attribute):
            return value.attr
        return None

    shrunk: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SHRINK_METHODS):
            name = _base_name(node.func.value)
            if name in containers:
                shrunk.add(name)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                name = _base_name(
                    t.value if isinstance(t, ast.Subscript) else t)
                if name in containers:
                    shrunk.add(name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                # X[:] = ... or X[k] = ... rewrites entries; a plain
                # function-local rebinding X = ... also resets it.
                if isinstance(t, ast.Subscript):
                    name = _base_name(t.value)
                    if name in containers:
                        shrunk.add(name)
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _GROW_METHODS):
            continue
        name = _base_name(node.func.value)
        if name not in containers or name in shrunk:
            continue
        if not any(_jax_rooted(a) for a in node.args):
            continue
        _emit(out, mod, "KT-MEM02", node.lineno,
              f"device value appended to module/class-level container "
              f"{name!r} that never shrinks in this module: each "
              f"retained reference pins an HBM buffer forever -- bound "
              f"the container or drop references after use")


# -- driver -----------------------------------------------------------------

RULES = (
    _check_sync_and_branch,
    _check_swallow,
    _check_mutable_defaults,
    _check_donation,
    _check_unused_imports,
    _check_atomic_staging,
    _check_partition_axes,
    _check_shard_reshape,
    _check_async_blocking,
    _check_loop_alloc,
    _check_container_leak,
)


def lint_file(path: str, rel: Optional[str] = None,
              mesh_axes: Optional[Set[str]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    mod = _Module(path, rel or path, source, mesh_axes=mesh_axes)
    out: List[Finding] = []
    for rule in RULES:
        rule(mod, out)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def iter_python_files(root: str) -> Iterable[Tuple[str, str]]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if d not in ("__pycache__", ".git")
        ]
        for name in sorted(filenames):
            if not name.endswith(".py") or _PB2_RE.search(name):
                continue
            path = os.path.join(dirpath, name)
            yield path, os.path.relpath(path, os.path.dirname(root))


def package_mesh_axes(package_root: str) -> Set[str]:
    """First lint pass: the repo-wide mesh-axis table KT-SHARD01
    validates PartitionSpecs against."""
    trees = []
    for path, _rel in iter_python_files(package_root):
        with open(path, encoding="utf-8") as f:
            trees.append(ast.parse(f.read(), filename=path))
    return harvest_mesh_axes(trees)


def lint_package(package_root: Optional[str] = None) -> List[Finding]:
    """Lint every .py under the kubeflow_tpu package (generated _pb2
    files excluded)."""
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(__file__))
    mesh_axes = package_mesh_axes(package_root)
    findings: List[Finding] = []
    for path, rel in iter_python_files(package_root):
        findings.extend(lint_file(path, rel, mesh_axes=mesh_axes))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_diff(rev: str, package_root: Optional[str] = None) -> List[Finding]:
    """Tier A lint restricted to package files changed vs a git rev --
    the fast pre-push path (``kftpu analyze --diff <rev>``); the full
    tree remains the CI default. The mesh-axis table is still harvested
    repo-wide so KT-SHARD01 stays cross-module."""
    import subprocess

    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(__file__))
    repo_root = os.path.dirname(package_root)
    proc = subprocess.run(
        ["git", "diff", "--name-only", rev, "--", "*.py"],
        cwd=repo_root, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {rev} failed: "
            f"{proc.stderr.strip() or proc.stdout.strip()}"
        )
    prefix = os.path.basename(package_root) + os.sep
    mesh_axes = package_mesh_axes(package_root)
    findings: List[Finding] = []
    for rel in sorted(set(proc.stdout.split())):
        if not rel.startswith(prefix) or _PB2_RE.search(rel):
            continue
        path = os.path.join(repo_root, rel)
        if os.path.exists(path):
            findings.extend(lint_file(path, rel, mesh_axes=mesh_axes))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
