"""Tier B: trace-time jaxpr audits over the repo's real entry points.

Everything here runs on the CPU backend (an 8-virtual-device mesh when
available): tracing and lowering are backend-faithful for the
invariants we check, so the bugs tier-1 CPU tests cannot see -- dropped
buffer donations, f32 upcasts in bf16 regions, recompiles in a
steady-state serving loop, collective miscounts under shard_map -- are
caught without a TPU in the loop.

Mechanisms (all public, reused by tests to prove non-vacuity):

- ``check_donation(jitted, args, ...)``: lowers the function and (a)
  captures JAX's "Some donated buffers were not usable" warning --
  a declared donation the compiler could NOT consume; (b) counts
  ``tf.aliasing_output`` attributes in the lowered StableHLO -- the
  positive proof that donation was plumbed through to XLA.
- ``count_upcasts(fn, args)``: recursively walks the closed jaxpr
  (descending into pjit/scan/cond/remat sub-jaxprs) counting
  ``convert_element_type`` equations of bf16 -> f32. Deliberate
  upcasts exist (softmax/logit accuracy), so this is a RATCHETED
  metric, not a zero assertion.
- ``count_collectives(fn, args)``: same walk, counting collective
  primitives; audited entry points assert exact counts derived from
  their declared sharding plan (ring = 2 ppermute for K/V rotation,
  Ulysses = 4 all_to_alls for q/k/v/out resharding).
- ``CompileWatch``: captures jax's compile log and records every
  (function, abstract signature) pair; the serving audit runs one
  warmup request, then a second request with shapes inside the same
  padding buckets and fails on ANY compilation in the steady-state
  round -- shape-signature churn is how serving latency quietly rots.

Donation / recompile / collective violations are HARD findings (never
grandfathered); upcast counts flow into the ratcheted baseline.
"""

from __future__ import annotations

import logging
import warnings
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

from kubeflow_tpu.analysis.report import Finding

DONATION_WARNING = "donated buffers were not usable"

# pbroadcast is deliberately absent: shard_map inserts it for
# replication-rule bookkeeping (check_rep) and it moves zero bytes.
_COLLECTIVES = (
    "psum", "ppermute", "all_gather", "all_to_all", "reduce_scatter",
    "pmax", "pmin",
)


# -- jaxpr walking ----------------------------------------------------------

def _iter_eqns(jaxpr):
    """Yield every eqn in a (Closed)Jaxpr, descending into sub-jaxprs
    carried in params (pjit/scan/while/cond/remat/custom_* ...)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    for eqn in inner.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in _as_jaxprs(val):
                yield from _iter_eqns(sub)


def _as_jaxprs(val):
    if hasattr(val, "eqns") or hasattr(val, "jaxpr"):
        return [val]
    if isinstance(val, (tuple, list)):
        return [v for v in val if hasattr(v, "eqns") or hasattr(v, "jaxpr")]
    return []


def count_upcasts(fn, args, from_dtype="bfloat16", to_dtype="float32") -> int:
    """Number of convert_element_type eqns casting from_dtype->to_dtype
    anywhere in fn's jaxpr (sub-jaxprs included)."""
    import jax
    import jax.numpy as jnp

    src = jnp.dtype(from_dtype)
    dst = jnp.dtype(to_dtype)
    closed = jax.make_jaxpr(fn)(*args)
    n = 0
    for eqn in _iter_eqns(closed):
        if eqn.primitive.name != "convert_element_type":
            continue
        new = eqn.params.get("new_dtype")
        if new is None or jnp.dtype(new) != dst:
            continue
        invar = eqn.invars[0]
        if getattr(invar, "aval", None) is not None and (
            jnp.dtype(invar.aval.dtype) == src
        ):
            n += 1
    return n


def count_collectives(fn, args) -> Dict[str, int]:
    """Counts of collective primitives in fn's jaxpr, zero-suppressed."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    counts: Dict[str, int] = {}
    for eqn in _iter_eqns(closed):
        name = eqn.primitive.name
        if name in _COLLECTIVES:
            counts[name] = counts.get(name, 0) + 1
    return counts


# -- donation ---------------------------------------------------------------

def check_donation(
    jitted,
    args: Sequence,
    entry: str,
    min_aliased: Optional[int] = None,
) -> List[Finding]:
    """Lower ``jitted`` at ``args`` and verify declared donations are
    consumed. Returns hard findings (empty list = pass)."""
    findings: List[Finding] = []
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = jitted.lower(*args)
        text = lowered.as_text()
    for w in caught:
        if DONATION_WARNING in str(w.message):
            findings.append(Finding(
                rule="KT-AUDIT-DONATE", path=entry, line=0, hard=True,
                message=f"declared donation not consumed: {w.message}",
            ))
    aliased = text.count("tf.aliasing_output")
    if min_aliased is not None and aliased < min_aliased:
        findings.append(Finding(
            rule="KT-AUDIT-DONATE", path=entry, line=0, hard=True,
            message=(
                f"only {aliased} output alias(es) in lowered HLO, "
                f"expected >= {min_aliased}: donation dropped"
            ),
        ))
    return findings


class DonationWatch:
    """Capture donation-unusable warnings across arbitrary code (e.g. a
    whole serving warmup, where the jits live in closures)."""

    def __init__(self) -> None:
        self.messages: List[str] = []

    def __enter__(self):
        self._ctx = warnings.catch_warnings(record=True)
        self._caught = self._ctx.__enter__()
        warnings.simplefilter("always")
        return self

    def __exit__(self, *exc):
        for w in self._caught:
            if DONATION_WARNING in str(w.message):
                self.messages.append(str(w.message))
        return self._ctx.__exit__(*exc)

    def findings(self, entry: str) -> List[Finding]:
        return [
            Finding(rule="KT-AUDIT-DONATE", path=entry, line=0, hard=True,
                    message=f"declared donation not consumed: {m}")
            for m in self.messages
        ]


# -- blocking host-sync detection -------------------------------------------

class HostTransferWatch:
    """Count BLOCKING device->host materializations (``np.asarray`` /
    ``np.array`` / ``jax.device_get`` applied to a ``jax.Array``) while
    the context is active.

    numpy resolves ``__array__`` at the C level, so patching the
    ArrayImpl type is a no-op (verified: the wrapper never fires); the
    watch instead patches the MODULE entry points the engine's host
    code actually calls. C-level escapes (``float(arr)``, the buffer
    protocol) are outside the net -- the engine's host paths go through
    numpy exclusively, and the non-vacuity test plants a sync through
    the patched surface to prove the net is live.
    ``copy_to_host_async`` is deliberately NOT counted: it is the
    non-blocking prefetch the dispatch pipeline exists to use.
    """

    def __init__(self) -> None:
        self.count = 0

    def __enter__(self):
        import jax
        import numpy

        self._mods = (numpy, jax)
        self._saved = (numpy.asarray, numpy.array, jax.device_get)
        real_asarray, real_array, real_get = self._saved
        watch = self

        def asarray(obj, *a, **kw):
            if isinstance(obj, jax.Array):
                watch.count += 1
            return real_asarray(obj, *a, **kw)

        def array(obj, *a, **kw):
            if isinstance(obj, jax.Array):
                watch.count += 1
            return real_array(obj, *a, **kw)

        def device_get(x, *a, **kw):
            watch.count += 1
            return real_get(x, *a, **kw)

        numpy.asarray = asarray
        numpy.array = array
        jax.device_get = device_get
        return self

    def __exit__(self, *exc):
        numpy, jax = self._mods
        numpy.asarray, numpy.array, jax.device_get = self._saved
        return False


def audit_decode_host_syncs(
    eng,
    entry: str = "serve.decode",
    metric: str = "serve.host_syncs_per_block",
) -> Tuple[List[Finding], Dict[str, float]]:
    """Steady-state decode must block on the host AT MOST once per
    decode block (the single consume of a landed block's outputs); a
    second sync means an ``np.asarray`` snuck between two dispatches
    and the TPU idles at every block boundary again. Holds at EVERY
    pipeline depth: sequential consumes each block once, a depth-N
    pipeline consumes block N under its queued successor lanes --
    audit_serving_engine re-runs this bound per depth (the ``.d2`` /
    ``.d4`` metric variants). The denominator is blocks CONSUMED in
    the window, not blocks dispatched: a deep pipeline pre-fills its
    lane deque before the window opens and the remaining-budget
    predictor then clamps fresh dispatches, so a window can legally
    consume (and pay its one sync for) more blocks than it dispatches
    -- counting dispatches flagged depth 4 as 2 syncs/block on slow
    hosts when every consume was the single legitimate one."""
    from kubeflow_tpu.serving.engine import Request

    findings: List[Finding] = []
    metrics: Dict[str, float] = {}
    # Enough requests to SATURATE the slots: the dispatch pipeline only
    # engages when no slot is free, and the pipelined mode is exactly
    # what this audit must cover (consume of block N under block N+1).
    # The extra depth*decode_block headroom keeps the remaining-budget
    # predictor from clamping dispatch inside the watched window at
    # deeper pipeline depths (the deque is pre-filled before it opens).
    depth = max(1, getattr(eng, "pipeline_depth", 1))
    budget = (4 + 2 * depth) * eng.decode_block + 8
    futs = [
        eng.submit(Request([2 + i, 4 + i, 6 + i], max_new_tokens=budget))
        for i in range(len(eng.free_slots))
    ]
    # Admission (prefill + first token) and the first decode dispatch
    # run OUTSIDE the watch: the window below is pure steady state.
    eng.step()
    c0 = eng.decode_blocks_consumed
    with HostTransferWatch() as w:
        for _ in range(4):
            eng.step()
    blocks = eng.decode_blocks_consumed - c0
    while any(not f.done() for f in futs):  # drain so the engine ends clean
        eng.step()
    if blocks <= 0:
        findings.append(Finding(
            rule="KT-AUDIT-HOSTSYNC", path=entry, line=0,
            hard=True,
            message="host-sync audit drove no decode blocks; the "
                    "steady-state sync bound was not exercised",
        ))
        return findings, metrics
    if w.count > blocks:
        findings.append(Finding(
            rule="KT-AUDIT-HOSTSYNC", path=entry, line=0,
            hard=True,
            message=f"{w.count} blocking host syncs over {blocks} decode "
                    f"blocks at steady state (bound: 1 per block) -- a "
                    f"sync sits between dispatches",
        ))
    metrics[metric] = round(w.count / blocks, 4)
    return findings, metrics


def audit_decode_host_syncs_traced(eng) -> Tuple[List[Finding], Dict[str, float]]:
    """Re-run the steady-state host-sync bound WITH span tracing on.

    The span recorder is required to be consumption-side only: a span
    around the decode loop must never materialize a ``jax.Array`` (no
    numpy on device values inside ``_record``). If instrumentation ever
    regresses into the dispatch path, this audit's
    ``serve.host_syncs_per_block_traced`` metric rises above the
    untraced bound and strict mode fails."""
    from kubeflow_tpu.obs import trace

    was = trace.enabled()
    trace.configure(enabled=True, plane="serving", label="jaxpr-audit")
    try:
        return audit_decode_host_syncs(
            eng,
            entry="serve.decode.traced",
            metric="serve.host_syncs_per_block_traced",
        )
    finally:
        trace.configure(enabled=was)


# -- recompile detection ----------------------------------------------------

class CompileWatch:
    """Record every XLA compilation (function name + abstract signature)
    issued while the context is active, via jax's compile log."""

    _LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch")

    def __init__(self) -> None:
        self.compiles: List[str] = []

    def __enter__(self):
        import jax

        class _H(logging.Handler):
            def emit(_self, record):
                msg = record.getMessage()
                if msg.startswith("Compiling "):
                    self.compiles.append(msg)

        self._handler = _H(level=logging.DEBUG)
        self._prev = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        self._restore = []
        for name in self._LOGGERS:
            lg = logging.getLogger(name)
            # propagate=False keeps jax_log_compiles' WARNING firehose off
            # the user's stderr; our handler still sees every record.
            self._restore.append((lg, lg.level, lg.propagate))
            lg.addHandler(self._handler)
            lg.propagate = False
            if lg.level > logging.DEBUG or lg.level == logging.NOTSET:
                lg.setLevel(logging.DEBUG)
        return self

    def __exit__(self, *exc):
        import jax

        jax.config.update("jax_log_compiles", self._prev)
        for lg, level, prop in self._restore:
            lg.removeHandler(self._handler)
            lg.setLevel(level)
            lg.propagate = prop
        return False

    def signatures(self) -> List[str]:
        # "Compiling <name> with global shapes and types [...]" -- the
        # whole message IS the abstract signature hash key.
        return list(self.compiles)


# -- entry-point audits -----------------------------------------------------

def _mesh():
    import jax

    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh

    return build_mesh(MeshConfig(), devices=jax.devices())


TRAIN_TASKS = {
    "mnist": dict(batch_size=8),
    "llama": dict(preset="llama-tiny", batch_size=8, seq_len=16),
    "bert": dict(preset="bert-tiny", batch_size=8, seq_len=16),
    "vit": dict(preset="vit-tiny", batch_size=8),
}

# bf16-activation tasks whose upcast count is a ratcheted metric.
_BF16_TASKS = ("llama",)


def audit_train_steps(
    tasks: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], Dict[str, float]]:
    import jax

    from kubeflow_tpu.analysis._trace_cache import train_setup

    findings: List[Finding] = []
    metrics: Dict[str, float] = {}
    for name in tasks or sorted(TRAIN_TASKS):
        entry = f"train.{name}"
        _task, state, _step, jitted, batch, _mesh_ = train_setup(name)
        if not hasattr(jitted, "lower"):
            findings.append(Finding(
                rule="KT-AUDIT-DONATE", path=entry, line=0, hard=True,
                message="train step exposes no .lower/.jitted; cannot "
                        "verify donation",
            ))
            continue
        # Every array leaf of the donated state must come back aliased:
        # a train step that double-buffers its TrainState doubles the
        # optimizer+param HBM footprint (PR 1's bug class).
        n_state_leaves = len(jax.tree.leaves(state))
        findings.extend(check_donation(
            jitted, (state, *batch), entry, min_aliased=n_state_leaves,
        ))
        if name in _BF16_TASKS:
            metrics[f"upcasts.{entry}"] = count_upcasts(
                jitted, (state, *batch)
            )
    return findings, metrics


def audit_serving_engine() -> Tuple[List[Finding], Dict[str, float]]:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.llama import PRESETS
    from kubeflow_tpu.serving.engine import GenerationEngine

    findings: List[Finding] = []
    metrics: Dict[str, float] = {}
    cfg = dataclasses.replace(PRESETS["llama-tiny"], max_seq=64)

    with DonationWatch() as warmup_donations, CompileWatch() as warm_watch:
        eng = GenerationEngine(config=cfg, max_slots=2, decode_block=4)
        # Warmup: compiles prefill (one length bucket), insert, decode
        # blocks, sampling. The prompt/token counts are chosen so round
        # two below stays inside every bucket warmed here.
        eng.generate([3, 5, 7], max_new_tokens=6)
    findings.extend(warmup_donations.findings("serve.warmup"))
    if not warm_watch.signatures():
        # The warmup MUST compile; zero events means the compile-log
        # capture is broken and the steady-state check below is vacuous.
        findings.append(Finding(
            rule="KT-AUDIT-RECOMPILE", path="serve.warmup", line=0,
            hard=True,
            message="compile watcher recorded nothing during warmup; "
                    "recompile detection is not functioning",
        ))

    # Steady state: same buckets, different content/length -> the jit
    # caches must absorb everything. Any compile here is a recompile bug.
    with CompileWatch() as watch, DonationWatch() as steady_donations:
        eng.generate([2, 4], max_new_tokens=6)
    findings.extend(steady_donations.findings("serve.steady"))
    for sig in watch.signatures():
        findings.append(Finding(
            rule="KT-AUDIT-RECOMPILE", path="serve.steady", line=0,
            hard=True,
            message=f"steady-state serving loop recompiled: {sig[:200]}",
        ))

    reg = getattr(eng, "_jit_registry", None)
    if reg is None:
        findings.append(Finding(
            rule="KT-AUDIT-DONATE", path="serve.insert", line=0, hard=True,
            message="engine exposes no _jit_registry; cannot verify "
                    "insert/decode donation",
        ))
        return findings, metrics

    # Insert: both caches are donated; every cache leaf must alias out.
    tokens = jnp.zeros((1, 32), jnp.int32)
    lengths = jnp.asarray([5], jnp.int32)
    _, k_seq, v_seq = eng._prefill(tokens, lengths)
    slots = jnp.asarray([0], jnp.int32)
    n_cache_leaves = len(jax.tree.leaves((eng.cache_k, eng.cache_v)))
    findings.extend(check_donation(
        reg["insert"],
        (eng.cache_k, eng.cache_v, k_seq, v_seq, slots),
        "serve.insert", min_aliased=n_cache_leaves,
    ))

    # Decode block: donated KV carry. The engine populated its per-key
    # jit cache during warmup; audit each compiled variant with the
    # argument shapes the engine itself uses.
    b = eng.max_slots
    toks = jnp.zeros((b,), jnp.int32)  # 1-D decode lanes (_pack_decode_lanes)
    lens = jnp.zeros((b,), jnp.int32)
    rng = jax.random.PRNGKey(0)
    temps = jnp.zeros((b,), jnp.float32)
    tks = jnp.zeros((b,), jnp.int32)
    tps = jnp.ones((b,), jnp.float32)
    nonces = jnp.zeros((b,), jnp.int32)
    for key, jfn in sorted(reg["decode_block"].items(), key=repr):
        n, filtered, want_lp, masked = key
        if masked:
            continue  # mask aval depends on live vocab state; warmup
            # already covered it via DonationWatch.
        args = (eng.weights, eng.cache_k, eng.cache_v, toks, lens, rng,
                temps, tks, tps, nonces)
        findings.extend(check_donation(
            jfn, args, f"serve.decode_block[n={n}]",
            min_aliased=n_cache_leaves,
        ))

    # Upcast ratchet over the bf16 prefill path (weights are arguments,
    # so the count covers embed->layers->logits end to end).
    metrics["upcasts.serve.prefill"] = count_upcasts(
        reg["prefill"], (eng.weights, tokens, lengths)
    )

    # Steady-state blocking host-sync bound over the same live engine
    # (at most one materialization per decode block; the dispatch
    # pipeline's whole point is that nothing else blocks in between).
    sync_findings, sync_metrics = audit_decode_host_syncs(eng)
    findings.extend(sync_findings)
    metrics.update(sync_metrics)

    # Same bound at the DEEPER pipeline depths depth-N dispatch allows:
    # pipeline_depth / drain_overshoot_bound are plain host attributes
    # (no new compiles -- the same decode jits serve every depth), so
    # the one warmed engine re-runs the window per depth. A depth whose
    # fill loop ever syncs between dispatches regresses its own
    # ratcheted metric (serve.host_syncs_per_block.dN, ceiling 1.0).
    saved = (eng.pipeline_depth, eng.drain_overshoot_bound)
    try:
        for depth in (2, 4):
            eng.pipeline_depth = depth
            # Let the lane deque actually reach ``depth`` full blocks;
            # the default bound (2 * decode_block) would clamp depth 4.
            eng.drain_overshoot_bound = depth * eng.decode_block
            d_findings, d_metrics = audit_decode_host_syncs(
                eng,
                entry=f"serve.decode.d{depth}",
                metric=f"serve.host_syncs_per_block.d{depth}",
            )
            findings.extend(d_findings)
            metrics.update(d_metrics)
    finally:
        eng.pipeline_depth, eng.drain_overshoot_bound = saved
    # Worst single-drain queued-lane discard across every depth driven
    # above -- perf_baseline.json caps it (an unbounded drain is a perf
    # regression, not a correctness one: outputs stay bit-identical).
    metrics["serve.overshoot_max_per_drain"] = float(
        eng.overshoot_max_per_drain
    )

    # Same bound with span tracing ON: instrumentation is required to be
    # consumption-side only, so the traced ratchet must match.
    traced_findings, traced_metrics = audit_decode_host_syncs_traced(eng)
    findings.extend(traced_findings)
    metrics.update(traced_metrics)
    return findings, metrics


def audit_collectives() -> Tuple[List[Finding], Dict[str, float]]:
    """Ring/Ulysses shard_map bodies: collective counts must match the
    declared plan exactly -- a missing ppermute breaks causality, an
    extra all_gather silently re-materializes the full sequence."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh

    findings: List[Finding] = []
    n_dev = len(jax.devices())
    if n_dev < 2:
        return findings, {}

    seq = min(4, n_dev)
    expected = {
        # K and V each rotate once per ring step; the jaxpr carries the
        # pair once (inside the fori_loop body's skip-last-hop cond).
        "ring_attention": ({"ppermute": 2}, "sequence"),
        # q, k, v reshard seq->heads plus one out reshard heads->seq.
        "ulysses_attention": ({"all_to_all": 4}, "sequence"),
    }

    mesh = build_mesh(MeshConfig(data=1, sequence=seq),
                      devices=jax.devices()[:seq])
    q = jnp.zeros((2, 16, 4, 8), jnp.float32)
    k = jnp.zeros((2, 16, 4, 8), jnp.float32)
    v = jnp.zeros((2, 16, 4, 8), jnp.float32)

    from kubeflow_tpu.ops.ring_attention import ring_attention_sharded
    from kubeflow_tpu.ops.ulysses import ulysses_attention_sharded

    for name, fn in (
        ("ring_attention", ring_attention_sharded),
        ("ulysses_attention", ulysses_attention_sharded),
    ):
        want, _axis = expected[name]
        got = count_collectives(
            partial(fn, mesh=mesh, causal=True), (q, k, v)
        )
        if got != want:
            findings.append(Finding(
                rule="KT-AUDIT-COLLECTIVE", path=f"ops.{name}", line=0,
                hard=True,
                message=f"collective counts {got} != declared plan {want} "
                        f"on a {seq}-way sequence mesh",
            ))
    return findings, {}


def audit_all(
    include_serving: bool = True,
) -> Tuple[List[Finding], Dict[str, float]]:
    findings: List[Finding] = []
    metrics: Dict[str, float] = {}
    for fn in ([audit_train_steps, audit_collectives]
               + ([audit_serving_engine] if include_serving else [])):
        f, m = fn()
        findings.extend(f)
        metrics.update(m)
    return findings, metrics
